"""Benchmark: FL round throughput + time-to-accuracy + LLM-step MFU.

Prints one JSON line per metric (flagship first):

1. ``fedavg_resnet56_cifar10_rounds_per_hour`` — the BASELINE.md north-star
   shape: FedAvg ResNet-56, 64 clients/round on the mesh engine, bf16.
   ``vs_baseline`` = mesh rounds/hour ÷ the reference-architecture golden
   loop (per-sample normalized). Real CIFAR-10 when cached/downloadable,
   loud synthetic stand-in otherwise (throughput is shape-determined).
   MFU counts only REAL local steps (padded hetero batches are skipped by
   the dynamic local loop — see engine.round_cost_flops).
2. ``fedavg_digits_time_to_90pct_s`` — real data (sklearn-bundled digits),
   FedAvg+LR: wall-clock to 90% test accuracy and final accuracy.
   BASELINE.json names time-to-target-accuracy a primary metric; this line
   keeps an accuracy axis on real data in every bench run.
3. ``llm_train_step_mfu`` — single-chip causal-LM train step (the FedLLM
   hot loop: Llama-style block, bf16, bs x seq = 8 x 1024). Shows the MFU
   the engine reaches when the workload has MXU-sized operands — the
   flagship's low MFU is a property of CIFAR ResNet's 16..64-wide channels,
   not of the runtime (see BASELINE.md "Roofline").
"""

from __future__ import annotations

import json
import time


# MFU comes from the observability layer's profiling plane
# (core/obs/profiler): the peak table and the MFU formula live there —
# single source of truth, so the bench's MFU columns and the engine's
# fed_round_mfu gauge can never disagree. The FLOPs model is unchanged
# (engine.round_cost_flops), so the BENCH trajectory stays comparable.
from fedml_tpu.core.obs import metrics as _obs_metrics
from fedml_tpu.core.obs import profiler as _obs_profiler


def _peak_tflops(device):
    return _obs_profiler.peak_tflops(device)


def _force(tree):
    """Force execution: block_until_ready is unreliable on the tunneled TPU
    platform — read back a scalar instead."""
    import jax
    return float(jax.tree_util.tree_leaves(tree)[0].sum())


def _hbm_peak_gb():
    """Per-device peak HBM (GiB) from memory_stats, or None off-TPU.
    NOTE: the counter is monotonic per process — deltas between snapshots
    attribute only what ran in between."""
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        return round(peak / 2**30, 4) if peak else None
    except Exception:
        return None


def bench_flagship():
    import jax
    import jax.numpy as jnp

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.sp.simulator import SPSimulator
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    n_clients = 64
    args = Arguments(
        dataset="cifar10", model="resnet56", precision="bfloat16",
        client_num_in_total=n_clients, client_num_per_round=n_clients,
        comm_round=1, epochs=1, batch_size=32, learning_rate=0.1,
        frequency_of_the_test=10_000, random_seed=0,
        allow_synthetic=True,  # loud, labeled fallback when no net/cache
        synthetic_size=50_000,  # stand-in matches real CIFAR-10's workload
    )
    fed, output_dim = load(args)
    provenance = getattr(fed, "provenance", "real")
    bundle = create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate), epochs=1)

    def time_rounds(run_one, params_of, warmup=1, iters=3):
        """Min-of-iters: the tunneled chip occasionally hiccups for tens
        of seconds (remote service contention) and a mean would let one
        stall swing the headline; the minimum is the steady state, and
        the raw trials are disclosed in the JSON."""
        for _ in range(warmup):
            run_one()
        _force(params_of())
        trials = []
        for _ in range(iters):
            t0 = time.perf_counter()
            run_one()
            _force(params_of())
            trials.append(time.perf_counter() - t0)
        return min(trials), trials

    # --- mesh engine (ours): rounds run in fused blocks of 8 — ONE
    # dispatch per block, exactly what engine.run() does in production
    # (the per-round tunnel dispatch is ~120 ms, 4.4% of a round;
    # BASELINE.md §3b)
    opt = create_optimizer(args, spec)
    tpu_sim = TPUSimulator(args, fed, bundle, opt, spec)
    r = [0]
    BLOCK = 8

    def tpu_block():
        tpu_sim.run_rounds_fused(r[0], BLOCK, hyper)
        r[0] += BLOCK

    tpu_block_s, tpu_trials = time_rounds(tpu_block,
                                          lambda: tpu_sim.params)
    tpu_round_s = tpu_block_s / BLOCK

    # HBM-peak delta of buffer donation (params/server_state/client_states
    # alias their outputs when donate_buffers is on — the default): peak
    # after the donating run vs after one extra block with donation OFF.
    # The counter is monotonic, so the delta is a LOWER bound on the
    # double-residency donation removes; off-TPU both read null.
    hbm_peak_on = _hbm_peak_gb()
    hbm_peak_off = None
    try:
        # same simulator, same data buffers — only the round program is
        # rebuilt without donation, so the delta attributes the program's
        # in/out double-residency and nothing else
        tpu_sim._donate = False
        tpu_sim._fused_fn = tpu_sim._build_fused_fn()
        tpu_block()
        _force(tpu_sim.params)
        hbm_peak_off = _hbm_peak_gb()
    except Exception as e:
        # the donation-OFF leg is the one that can OOM (it deliberately
        # needs more HBM) — a null column must say why, not swallow it
        print(json.dumps({"metric": "hbm_peak_donation_off_gb",
                          "error": f"{type(e).__name__}: {e}"}),
              flush=True)
    finally:
        tpu_sim._donate = True
        tpu_sim._fused_fn = tpu_sim._build_fused_fn()

    # FLOPs of the real (non-padded) work per round, for MFU — computed
    # by the profiling plane (same formula as the engine's per-round
    # fed_round_mfu gauge) and recorded there so a bench run's metrics
    # snapshot carries the flagship MFU too
    flops = tpu_sim.round_cost_flops(hyper)
    n_dev = tpu_sim.n_devices
    achieved_tflops = (flops / tpu_round_s) / 1e12 if flops else 0.0
    mfu = _obs_profiler.mfu_value(flops, tpu_round_s, n_dev,
                                  device=jax.devices()[0])
    if mfu is not None:
        _obs_metrics.record_round_mfu(mfu, tflops=achieved_tflops)

    # --- baseline: golden per-client loop (reference SP architecture),
    # scaled down (8 of 64 clients) then per-sample normalized
    base_clients = 8
    bargs = Arguments(
        dataset="cifar10", model="resnet56", precision="bfloat16",
        client_num_in_total=base_clients, client_num_per_round=base_clients,
        comm_round=1, epochs=1, batch_size=32, learning_rate=0.1,
        frequency_of_the_test=-1,  # timing: no eval inside the timed call
        random_seed=0, allow_synthetic=True,
        synthetic_size=6_250, max_total_samples=6_250,
    )
    bfed, _ = load(bargs)
    sp_sim = SPSimulator(bargs, bfed, bundle, create_optimizer(bargs, spec),
                         spec)

    def sp_round():
        sp_sim.run(comm_round=1)

    # iters=4: the SP loop is 8 small dispatches/round through the tunnel
    # and its latency varies session-to-session far more than the mesh
    # engine's single dispatch; sp_round_s is disclosed in the JSON so
    # vs_baseline is auditable against the raw legs
    sp_round_s, sp_trials = time_rounds(sp_round, lambda: sp_sim.params,
                                        warmup=1, iters=4)
    tpu_samples = float(fed.total_train_samples)
    sp_samples = float(bfed.total_train_samples)
    rounds_per_hour = 3600.0 / tpu_round_s
    vs_baseline = (sp_round_s / sp_samples) / (tpu_round_s / tpu_samples)
    print(json.dumps({
        "metric": "fedavg_resnet56_cifar10_rounds_per_hour",
        "value": round(rounds_per_hour, 1),
        "unit": f"rounds/hour (64 clients/round, 1 local epoch, bf16, "
                f"{provenance} data)",
        "vs_baseline": round(vs_baseline, 3),
        "sp_baseline_round_s": round(sp_round_s, 4),
        "sp_baseline_trials": [round(t, 3) for t in sp_trials],
        "sp_baseline_samples": int(sp_samples),
        "step_time_s": round(tpu_round_s, 4),
        "block_trials": [round(t, 3) for t in tpu_trials],
        "tflops": round(achieved_tflops, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "n_devices": n_dev,
        # donation HBM accounting (peak counter is monotonic: the delta is
        # a lower bound on the double-residency donation removes)
        "hbm_peak_donation_on_gb": hbm_peak_on,
        "hbm_peak_donation_off_gb": hbm_peak_off,
        "hbm_peak_delta_gb": (round(hbm_peak_off - hbm_peak_on, 4)
                              if hbm_peak_on and hbm_peak_off else None),
        "data_provenance": provenance,
        # honesty note: the SP baseline deliberately runs a 1/8-size
        # workload (per-sample normalized); disclose any train-set caps
        "baseline_train_capped_to": getattr(bargs, "_train_capped_to",
                                            None),
    }), flush=True)


def bench_time_to_acc(target_acc=0.90, max_rounds=80):
    """Real-data accuracy axis: FedAvg + logistic regression on the
    sklearn-bundled digits set (no network needed — provenance 'real')."""
    import jax.numpy as jnp

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    args = Arguments(
        dataset="digits", model="lr", client_num_in_total=10,
        client_num_per_round=10, comm_round=max_rounds, epochs=1,
        batch_size=32, learning_rate=0.3, frequency_of_the_test=10_000,
        random_seed=0)  # eval below, once per round — not also in-engine
    fed, output_dim = load(args)
    provenance = getattr(fed, "provenance", "real")
    bundle = create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    opt = create_optimizer(args, spec)
    sim = TPUSimulator(args, fed, bundle, opt, spec)
    hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                       epochs=1)

    t0 = time.perf_counter()
    t_hit, acc, hit_round = None, 0.0, None
    for round_idx in range(max_rounds):
        sim.run_round(round_idx, hyper)
        stats = sim._evaluate(sim.params, sim.fed.test["x"],
                              sim.fed.test["y"], sim.fed.test["mask"])
        acc = float(stats["correct"]) / max(float(stats["count"]), 1.0)
        if t_hit is None and acc >= target_acc:
            t_hit = time.perf_counter() - t0
            hit_round = round_idx
    total_s = time.perf_counter() - t0
    print(json.dumps({
        "metric": "fedavg_digits_time_to_90pct_s",
        "value": round(t_hit, 3) if t_hit is not None else None,
        "unit": f"s wall-clock to {target_acc:.0%} test acc "
                f"(10 clients, FedAvg+LR, incl. compile)",
        "vs_baseline": None,
        "final_acc": round(acc, 4),
        "rounds_to_target": hit_round,
        "total_rounds": max_rounds,
        "total_s": round(total_s, 2),
        "data_provenance": provenance,
    }), flush=True)


def _secagg_wire_leg(target_acc=0.90, rounds=40, bits=4):
    """SecAgg-compatible lane compression column (ISSUE 19): the digits
    FedAvg trajectory driven through the REAL secure-uplink wire math —
    ``core/wire.field_encode`` (EF + stochastic lane quantization),
    pairwise ``core/mpc.expand_mask`` masks, mod-p summation, and
    ``lane_dequantize_sum`` — once over dense field vectors (the
    frac_bits=16 layout, 4 B/coord) and once over ``bits``-bit lanes
    (k_max=4 silos -> 5 lanes/word, 0.8 B/coord). The Bonawitz FSM
    itself needs the ``cryptography`` package (absent here); this
    harness is the same per-round algebra with the key agreement
    elided, so the masked bytes and the mask-cancellation bit-exactness
    it reports are exactly what the FSM would put on the wire.
    Every round asserts masked-sum == unmasked quantized sum."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import make_trainer_spec
    from fedml_tpu.core.algframe.local_training import run_local_sgd
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.core.collectives import (tree_flatten_to_vector,
                                            vector_to_tree_like)
    from fedml_tpu.core.mpc import P, dequantize, expand_mask, quantize
    from fedml_tpu.core.wire import (field_encode, lane_dequantize_sum,
                                     plan_for, suggest_scale)
    from fedml_tpu.cross_silo.horizontal.runner import _make_eval_fn
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer

    K = 4
    args = Arguments(
        dataset="digits", model="lr", client_num_in_total=K,
        client_num_per_round=K, comm_round=rounds, epochs=1,
        batch_size=32, learning_rate=0.3, frequency_of_the_test=1,
        random_seed=0, training_type="cross_silo")
    fed, output_dim = load(args)
    bundle = create(args, output_dim)
    spec = make_trainer_spec(fed, bundle)
    opt = create_optimizer(args, spec)
    eval_fn = _make_eval_fn(spec, fed)
    hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                       epochs=1)
    init_rng, _ = jax.random.split(jax.random.PRNGKey(0))
    params0 = jax.device_get(bundle.init(init_rng, fed.train.x[0, 0]))
    d = int(np.asarray(tree_flatten_to_vector(params0)).shape[0])

    def impl(params, cdata, rng, hyper):
        inner = opt.make_inner_opt(hyper)
        new_params, _, _ = run_local_sgd(
            spec, inner, params, cdata, rng, hyper,
            grad_transform=opt.grad_transform,
            ctx={"global_params": params, "server_state": {},
                 "client_state": {}, "hyper": hyper})
        return new_params

    train_jit = jax.jit(impl)

    def local_vec(global_p, cidx, rnd):
        cdata = jax.tree_util.tree_map(lambda a: a[cidx], fed.train)
        key = jax.random.fold_in(jax.random.PRNGKey(17 + cidx), rnd)
        new_p = train_jit(jax.tree_util.tree_map(jnp.asarray, global_p),
                          cdata, key, hyper)
        return np.asarray(tree_flatten_to_vector(jax.device_get(new_p)),
                          np.float32)

    def leg(use_lanes: bool):
        plan = plan_for(bits, K) if use_lanes else None
        scale = suggest_scale(4.0, plan) if plan else None
        residuals = [None] * K
        global_p = params0
        plen = plan.packed_len(d) if plan else d
        hit, acc, exact = None, 0.0, True
        for rnd in range(rounds):
            qs = []
            for k in range(K):
                vec = local_vec(global_p, k, rnd)
                if plan:
                    packed, residuals[k] = field_encode(
                        vec, scale, plan, residuals[k],
                        np.random.default_rng((k + 1) * 1000003 + rnd))
                    qs.append(packed.astype(np.uint64))
                else:
                    qs.append(np.asarray(quantize(jnp.asarray(vec)),
                                         np.uint64))
            # pairwise mask algebra over the packed length: +s_ij for
            # i<j, -s_ij for i>j — sums cancel bit-for-bit mod p
            masked, plain = np.zeros(plen, np.uint64), np.zeros(plen,
                                                                np.uint64)
            for i in range(K):
                m = qs[i] % P
                for j in range(K):
                    if i == j:
                        continue
                    seed = (rnd << 16) ^ (min(i, j) << 8) ^ max(i, j)
                    s = expand_mask(seed, plen).astype(np.uint64)
                    m = (m + s) % P if i < j else (m + P - s) % P
                masked = (masked + m) % P
                plain = (plain + qs[i]) % P
            exact = exact and bool(np.array_equal(masked, plain))
            if plan:
                ssum = lane_dequantize_sum(masked.astype(np.uint32), K,
                                           scale, plan, d)
                avg = ssum / K
                # auto-scale EMA, mirroring SecAggServerManager
                per_client = float(np.abs(ssum).max()) / K
                scale = 0.5 * scale + 0.5 * suggest_scale(
                    max(2.0 * per_client, 1e-8), plan)
            else:
                avg = np.asarray(dequantize(jnp.asarray(
                    masked.astype(np.uint32))), np.float32)[:d] / K
            global_p = jax.tree_util.tree_map(
                np.asarray, vector_to_tree_like(np.asarray(avg, np.float32),
                                                params0))
            stats = eval_fn(global_p) or {}
            acc = float(stats.get("test_acc", 0.0))
            if hit is None and acc >= target_acc:
                hit = rnd
        return {"bytes_per_round": float(plen * 4 * K),
                "rounds_to_target": hit, "final_acc": round(acc, 4),
                "mask_sum_bit_exact": exact}

    dense = leg(use_lanes=False)
    lanes = leg(use_lanes=True)
    return {
        "bytes_per_round": lanes["bytes_per_round"],
        "dense_field_bytes_per_round": dense["bytes_per_round"],
        "reduction_vs_dense_field": round(
            dense["bytes_per_round"] / lanes["bytes_per_round"], 2),
        "rounds_to_target": lanes["rounds_to_target"],
        "dense_field_rounds_to_target": dense["rounds_to_target"],
        "final_acc": lanes["final_acc"],
        "dense_field_final_acc": dense["final_acc"],
        "mask_sum_bit_exact": bool(lanes["mask_sum_bit_exact"]
                                   and dense["mask_sum_bit_exact"]),
        "bits": bits, "k_max": K,
    }


def _gossip_wire_leg(rounds=8):
    """Gossip delta-chain compression column (ISSUE 19): the synthetic
    gossip session dense vs ``gossip_compression: topk_qsgd`` — N2N
    model-bearing bytes per round off the same ``WireStats`` ledger."""
    from fedml_tpu import data as data_mod
    from fedml_tpu import model as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.distributed.communication.message import WIRE_STATS
    from fedml_tpu.cross_silo.decentralized import GossipMsg,\
        run_gossip_inproc

    def session(**kw):
        args = Arguments(
            dataset="digits", model="lr", client_num_in_total=4,
            client_num_per_round=4, comm_round=rounds, epochs=1,
            batch_size=32, learning_rate=0.3, random_seed=0,
            training_type="cross_silo", **kw)
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        WIRE_STATS.reset()
        result = run_gossip_inproc(args, fed, bundle)
        by_type = WIRE_STATS.snapshot()["by_type"]
        rec = by_type.get(str(GossipMsg.N2N_PARAMS),
                          by_type.get(GossipMsg.N2N_PARAMS, {"bytes": 0}))
        return {"bytes_per_round": rec["bytes"] / rounds,
                "final_acc": result.get("final_test_acc"),
                "consensus_dist": result.get("consensus_dist")}

    off = session()
    on = session(gossip_compression="topk_qsgd", comm_compression_ratio=0.1)
    return {
        "bytes_per_round": round(on["bytes_per_round"], 1),
        "dense_bytes_per_round": round(off["bytes_per_round"], 1),
        "reduction_vs_dense": round(
            off["bytes_per_round"] / on["bytes_per_round"], 2)
        if on["bytes_per_round"] else None,
        "final_acc": on["final_acc"],
        "dense_final_acc": off["final_acc"],
        "consensus_dist": round(on["consensus_dist"], 4)
        if on["consensus_dist"] is not None else None,
    }


def bench_cross_silo_wire(target_acc=0.90, rounds=40):
    """Wire-efficiency axis (QSGD + error-feedback top-k, ISSUE 1): the
    digits FedAvg session runs twice over the in-proc WAN FSM — dense
    float32 vs ``comm_compression: topk_qsgd`` with compressed broadcast —
    and reports model-bearing bytes-on-wire per round (types INIT/SYNC/
    C2S_MODEL from the ``WireStats`` ledger at the ``Message.encode``
    seam; the in-proc broker encode/decodes every message exactly like
    TCP/gRPC). The compressed session must still reach the accuracy
    target — wire savings that cost convergence are not savings."""
    from fedml_tpu import data as data_mod
    from fedml_tpu import model as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.distributed.communication.message import WIRE_STATS
    from fedml_tpu.cross_silo.horizontal.runner import run_cross_silo_inproc
    from fedml_tpu.cross_silo.message_define import MyMessage

    model_types = (str(MyMessage.MSG_TYPE_S2C_INIT_CONFIG),
                   str(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT),
                   str(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER))

    def session(**cc):
        args = Arguments(
            dataset="digits", model="lr", client_num_in_total=10,
            client_num_per_round=10, comm_round=rounds, epochs=1,
            batch_size=32, learning_rate=0.3, frequency_of_the_test=1,
            random_seed=0, training_type="cross_silo", **cc)
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        WIRE_STATS.reset()
        t0 = time.perf_counter()
        result = run_cross_silo_inproc(args, fed, bundle)
        wall = time.perf_counter() - t0
        by_type = WIRE_STATS.snapshot()["by_type"]
        model_bytes = sum(by_type.get(t, {"bytes": 0})["bytes"]
                          for t in model_types)
        accs = [h.get("test_acc", 0.0) for h in result["history"]]
        hit = next((i for i, a in enumerate(accs) if a >= target_acc), None)
        return {"bytes_per_round": model_bytes / rounds,
                "final_acc": accs[-1] if accs else 0.0,
                "rounds_to_target": hit, "wall_s": wall}

    off = session()
    on = session(comm_compression="topk_qsgd", comm_compression_ratio=0.05,
                 comm_compression_broadcast="compress")
    reduction = (off["bytes_per_round"] / on["bytes_per_round"]
                 if on["bytes_per_round"] else None)
    print(json.dumps({
        "metric": "fedavg_cross_silo_wire_bytes_per_round",
        "value": round(on["bytes_per_round"], 1),
        "unit": f"model-bearing wire bytes/round (10 silos, FedAvg+LR "
                f"digits, topk_qsgd 5% + EF, compressed broadcast, "
                f"{rounds} rounds incl. dense init)",
        "vs_baseline": round(reduction, 2) if reduction else None,
        "dense_bytes_per_round": round(off["bytes_per_round"], 1),
        "compressed_final_acc": round(on["final_acc"], 4),
        "dense_final_acc": round(off["final_acc"], 4),
        "target_acc": target_acc,
        "compressed_rounds_to_target": on["rounds_to_target"],
        "dense_rounds_to_target": off["rounds_to_target"],
        "compressed_wall_s": round(on["wall_s"], 2),
        "dense_wall_s": round(off["wall_s"], 2),
        # ISSUE 19 columns: SecAgg-compatible lane compression (masked
        # uplink bytes vs the dense field layout, same trajectory gate)
        # and the gossip delta-chain (N2N bytes dense vs compressed).
        # Under `legs` so scripts/bench_diff.py flattens + gates them.
        "legs": {
            "secagg_compressed": _secagg_wire_leg(target_acc=target_acc),
            "gossip_compressed": _gossip_wire_leg(),
        },
    }), flush=True)


def bench_chaos_dropout(target_acc=0.90, max_rounds=80):
    """Fault-tolerance axis (chaos subsystem, ISSUE 3): digits FedAvg+LR
    under a seeded 20% client dropout + 10% stragglers (half local work),
    tolerance ON (dropped clients renormalized out of the weighted
    average, the chaos default) vs OFF (their scheduled weight stays in
    the denominator, diluting every round's aggregate with zeros — what a
    fault-oblivious aggregator does). Same 90% digits target as
    ``fedavg_digits_time_to_90pct_s``: tolerance must reach it; the
    intolerant leg degrades (more rounds) or stalls (None). lr 0.1 (not
    the time-to-acc leg's 0.3): the smoother trajectory is where dilution
    shows — at 0.3 the first rounds overshoot past 90% regardless."""
    import jax.numpy as jnp

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    def leg(tolerance: bool):
        args = Arguments(
            dataset="digits", model="lr", client_num_in_total=10,
            client_num_per_round=10, comm_round=max_rounds, epochs=1,
            batch_size=32, learning_rate=0.1, frequency_of_the_test=10_000,
            random_seed=0, chaos_dropout_prob=0.2,
            chaos_straggler_prob=0.1, chaos_straggler_work=0.5,
            chaos_seed=7, chaos_tolerance=tolerance)
        fed, output_dim = load(args)
        bundle = create(args, output_dim)
        spec = ClassificationTrainer(bundle.apply)
        opt = create_optimizer(args, spec)
        sim = TPUSimulator(args, fed, bundle, opt, spec)
        hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                           epochs=1)
        t0 = time.perf_counter()
        hit_round, acc = None, 0.0
        for round_idx in range(max_rounds):
            sim.run_round(round_idx, hyper)
            stats = sim._evaluate(sim.params, sim.fed.test["x"],
                                  sim.fed.test["y"], sim.fed.test["mask"])
            acc = float(stats["correct"]) / max(float(stats["count"]), 1.0)
            if hit_round is None and acc >= target_acc:
                hit_round = round_idx
        injected = sum(len(r["injected"]["dropped"])
                       for r in sim.chaos_ledger.rounds())
        return {"rounds_to_target": hit_round, "final_acc": acc,
                "wall_s": time.perf_counter() - t0,
                "injected_dropouts": injected,
                "provenance": getattr(fed, "provenance", "real")}

    on = leg(tolerance=True)
    off = leg(tolerance=False)
    print(json.dumps({
        "metric": "fedavg_chaos_dropout_rounds_to_target",
        "value": on["rounds_to_target"],
        "unit": f"rounds to {target_acc:.0%} digits test acc under seeded "
                f"20% dropout + 10% stragglers (10 clients, FedAvg+LR, "
                f"tolerance on; max {max_rounds})",
        "vs_baseline": (off["rounds_to_target"] / max(
                            on["rounds_to_target"], 1)
                        if on["rounds_to_target"] is not None
                        and off["rounds_to_target"] is not None else None),
        "tolerance_on_rounds_to_target": on["rounds_to_target"],
        "tolerance_off_rounds_to_target": off["rounds_to_target"],
        "tolerance_on_final_acc": round(on["final_acc"], 4),
        "tolerance_off_final_acc": round(off["final_acc"], 4),
        "injected_dropouts": on["injected_dropouts"],
        "tolerance_on_wall_s": round(on["wall_s"], 2),
        "tolerance_off_wall_s": round(off["wall_s"], 2),
        "data_provenance": on["provenance"],
    }), flush=True)


def bench_async_chaos(straggler_probs=(0.2, 0.4), sync_rounds=60,
                      async_pours=100):
    """Buffered-async axis (core/async_rounds, ISSUE 6): digits FedAvg+LR,
    10 clients, seeded 10% dropout + straggler faults — the sync round
    barrier vs ``round_mode: async_buffered`` (K=5 staleness-weighted
    pours), measured as CLIENT UPDATES INCORPORATED PER SIMULATED HOUR on
    the shared seeded arrival model (``core/async_rounds/arrivals.py``;
    both legs train for real — the clock is simulated because one machine
    serializes what a fleet runs in parallel).

    Time semantics, per leg:

    * sync (the PR 3 barrier): the round closes at a deadline T = 1.35x
      the slowest client's healthy duration (a tuned ``round_timeout_s``);
      stragglers (2.5x slowdown) miss it and their uploads are DROPPED
      (the cross-silo stale-tag behavior), dropped clients stall the round
      to T. The engine leg runs ``chaos_straggler_work: 0`` so training
      matches the clock verdict exactly: a straggler contributes nothing.
    * async: nobody waits — a straggler's update arrives 2.5x late and is
      staleness-DOWN-WEIGHTED, never dropped; a dropped client's dispatch
      is lost and the client redeems into the rotation after its duration.

    The win must GROW with fault rate (4th acceptance criterion): sync
    throughput falls as (1 - p_straggler) — every straggler is wasted
    work plus a stalled barrier — while async only pays the (mild) extra
    time the straggler spends training."""
    import jax.numpy as jnp
    import numpy as np

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.core.async_rounds import client_durations
    from fedml_tpu.core.chaos import FaultPlan
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.async_engine import AsyncBufferedSimulator
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    n_clients, k, p_drop, seed = 10, 5, 0.1, 7
    durations = client_durations(n_clients, random_seed=0)
    deadline = 1.35 * float(np.max(durations))

    def build(extra):
        args = Arguments(
            dataset="digits", model="lr", client_num_in_total=n_clients,
            client_num_per_round=n_clients, epochs=1, batch_size=32,
            learning_rate=0.1, frequency_of_the_test=10_000, random_seed=0,
            chaos_dropout_prob=p_drop, chaos_seed=seed, **extra)
        fed, output_dim = load(args)
        bundle = create(args, output_dim)
        spec = ClassificationTrainer(bundle.apply)
        opt = create_optimizer(args, spec)
        return args, fed, bundle, opt, spec

    def eval_acc(sim):
        stats = sim._evaluate(sim.params, sim.fed.test["x"],
                              sim.fed.test["y"], sim.fed.test["mask"])
        return float(stats["correct"]) / max(float(stats["count"]), 1.0)

    def sync_leg(p_strag):
        # straggler_work 0: a barrier-missed upload contributes nothing —
        # training and the clock read the SAME plan verdicts
        args, fed, bundle, opt, spec = build(dict(
            comm_round=sync_rounds, chaos_straggler_prob=p_strag,
            chaos_straggler_work=0.0))
        sim = TPUSimulator(args, fed, bundle, opt, spec)
        hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                           epochs=1)
        plan = FaultPlan.from_args(args)
        sim_t, updates = 0.0, 0
        wall0 = time.perf_counter()
        for r in range(sync_rounds):
            sim.run_round(r, hyper)
            healthy = [c for c in range(n_clients)
                       if plan.work_scale(r, c) >= 1.0]
            # any fault stalls the barrier to its deadline; an all-healthy
            # round closes when its slowest member reports
            sim_t += (deadline if len(healthy) < n_clients
                      else float(np.max(durations[healthy])))
            updates += len(healthy)
        return {"updates_per_h": updates / sim_t * 3600.0,
                "versions_per_h": sync_rounds / sim_t * 3600.0,
                "final_acc": eval_acc(sim), "sim_t": sim_t,
                "wall_s": time.perf_counter() - wall0,
                "provenance": getattr(fed, "provenance", "real")}

    def async_leg(p_strag):
        args, fed, bundle, opt, spec = build(dict(
            comm_round=async_pours, round_mode="async_buffered",
            async_buffer_k=k, chaos_straggler_prob=p_strag,
            chaos_straggler_work=0.4))  # 2.5x slowdown, full work
        sim = AsyncBufferedSimulator(args, fed, bundle, opt, spec)
        wall0 = time.perf_counter()
        r = sim.run()
        stal = [h["staleness_mean"] for h in sim.history]
        return {"updates_per_h": (r["updates_aggregated"]
                                  / r["virtual_time_s"] * 3600.0),
                "versions_per_h": r["rounds"] / r["virtual_time_s"] * 3600.0,
                "final_acc": r["final_test_acc"], "sim_t": r["virtual_time_s"],
                "wall_s": time.perf_counter() - wall0,
                "staleness_mean": float(np.mean(stal))}

    legs = {}
    for p in straggler_probs:
        legs[p] = {"sync": sync_leg(p), "async": async_leg(p)}
    p0 = straggler_probs[0]
    ratios = {p: legs[p]["async"]["updates_per_h"]
              / max(legs[p]["sync"]["updates_per_h"], 1e-9)
              for p in straggler_probs}
    rec = {
        "metric": "fedavg_async_chaos_updates_per_hour",
        "value": round(legs[p0]["async"]["updates_per_h"], 1),
        "unit": (f"client updates incorporated per SIMULATED hour (digits "
                 f"FedAvg+LR, 10 clients, K={k} buffered-async pours, "
                 f"seeded {int(p_drop*100)}% dropout + "
                 f"{int(p0*100)}% stragglers at 2.5x slowdown; sync "
                 f"barrier deadline {deadline:.2f}s drops late uploads)"),
        "vs_baseline": round(ratios[p0], 3),
        "data_provenance": legs[p0]["sync"]["provenance"],
    }
    for p in straggler_probs:
        tag = f"straggler_{int(p*100)}pct"
        rec[f"{tag}_sync_updates_per_h"] = round(
            legs[p]["sync"]["updates_per_h"], 1)
        rec[f"{tag}_async_updates_per_h"] = round(
            legs[p]["async"]["updates_per_h"], 1)
        rec[f"{tag}_async_vs_sync"] = round(ratios[p], 3)
        rec[f"{tag}_sync_final_acc"] = round(legs[p]["sync"]["final_acc"], 4)
        rec[f"{tag}_async_final_acc"] = round(
            legs[p]["async"]["final_acc"], 4)
        rec[f"{tag}_async_staleness_mean"] = round(
            legs[p]["async"]["staleness_mean"], 2)
    rec["win_grows_with_fault_rate"] = bool(
        ratios[straggler_probs[-1]] > ratios[p0])
    print(json.dumps(rec), flush=True)


def bench_async_robust(p_strag=0.2, n_byz=2, sync_rounds=50,
                       async_pours=80):
    """Byzantine-robust async axis (ISSUE 7): digits FedAvg+LR, 10
    clients, 2 byzantine clients injecting ``byzantine_random`` at 10x
    scale, seeded 20% stragglers (2.5x slowdown) + 10% dropout — the sync
    DEFENDED barrier (robust_fused engine) vs DEFENDED buffered-async
    pours (async+krum and async+foolsgold), measured as client updates
    incorporated per simulated hour on the shared arrival model (the
    ISSUE 6 clock semantics, unchanged: sync stragglers miss the barrier
    deadline and are dropped; async stragglers arrive late, re-based and
    staleness-down-weighted).

    Byzantine containment is the second column: each async defended
    attacked run is compared against its attack-free twin (same seed,
    same defense) as a relative params distance. Krum must keep the
    10x-scaled rows out — the distance stays in the attack-free run's
    neighborhood while an UNDEFENDED attacked async run lands far away
    (reported for contrast); ``byzantine_kept_out`` pins that. FoolsGold
    faces colluding sign-flipped rows (its sybil signature — random
    byzantine noise is exactly what it cannot see) and its containment
    of a 2-strong collusion on this workload is WEAK in sync and async
    alike — the column the foolsgold leg is honest about is parity of
    behavior (async acc tracks the sync defended acc under the same
    attack) plus the stateful defended-pour throughput."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.core.async_rounds import client_durations
    from fedml_tpu.core.chaos import FaultPlan
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.async_engine import AsyncBufferedSimulator
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    n_clients, k, p_drop, seed = 10, 5, 0.1, 7
    durations = client_durations(n_clients, random_seed=0)
    deadline = 1.35 * float(np.max(durations))

    def build(extra):
        args = Arguments(
            dataset="digits", model="lr", client_num_in_total=n_clients,
            client_num_per_round=n_clients, epochs=1, batch_size=32,
            learning_rate=0.1, frequency_of_the_test=10_000, random_seed=0,
            chaos_dropout_prob=p_drop, chaos_seed=seed, **extra)
        fed, output_dim = load(args)
        bundle = create(args, output_dim)
        spec = ClassificationTrainer(bundle.apply)
        return args, fed, bundle, create_optimizer(args, spec), spec

    def eval_acc(sim):
        stats = sim._evaluate(sim.params, sim.fed.test["x"],
                              sim.fed.test["y"], sim.fed.test["mask"])
        return float(stats["correct"]) / max(float(stats["count"]), 1.0)

    def pvec(params):
        return np.concatenate([np.asarray(jax.device_get(l)).ravel()
                               for l in jax.tree_util.tree_leaves(params)])

    def rel_dist(a, b):
        va, vb = pvec(a), pvec(b)
        return float(np.linalg.norm(va - vb)
                     / max(np.linalg.norm(va), 1e-12))

    # byzantine_client_num rides defense_kw (both the attacker and the
    # defender read it from args; passing it twice would collide).
    # Per-defense attack: krum faces 10x random byzantine rows (the
    # distance outlier it is built to exclude); foolsgold faces COLLUDING
    # 5x flipped rows (the sybil similarity signature it is built to
    # down-weight — random noise is exactly what it cannot see).
    ATK = {"krum": dict(enable_attack=True,
                        attack_type="byzantine_random", attack_scale=10.0),
           "foolsgold": dict(enable_attack=True,
                             attack_type="byzantine_flip",
                             attack_scale=1.0)}

    def defense_kw(d):
        return dict(enable_defense=True, defense_type=d,
                    byzantine_client_num=n_byz,
                    **({"krum_param_m": 3} if d == "multi_krum" else {}))

    def sync_defended_leg(defense):
        args, fed, bundle, opt, spec = build(dict(
            comm_round=sync_rounds, chaos_straggler_prob=p_strag,
            chaos_straggler_work=0.0, **defense_kw(defense),
            **ATK[defense]))
        sim = TPUSimulator(args, fed, bundle, opt, spec)
        hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                           epochs=1)
        plan = FaultPlan.from_args(args)
        sim_t, updates = 0.0, 0
        wall0 = time.perf_counter()
        for r in range(sync_rounds):
            sim.run_round(r, hyper)
            healthy = [c for c in range(n_clients)
                       if plan.work_scale(r, c) >= 1.0]
            sim_t += (deadline if len(healthy) < n_clients
                      else float(np.max(durations[healthy])))
            updates += len(healthy)
        return {"updates_per_h": updates / sim_t * 3600.0,
                "final_acc": eval_acc(sim),
                "wall_s": time.perf_counter() - wall0,
                "provenance": getattr(fed, "provenance", "real")}

    def async_leg(defense, attacked=True):
        extra = dict(comm_round=async_pours, round_mode="async_buffered",
                     async_buffer_k=k, chaos_straggler_prob=p_strag,
                     chaos_straggler_work=0.4)
        if defense is not None:
            extra.update(defense_kw(defense))
        else:
            extra["byzantine_client_num"] = n_byz
        if attacked:
            extra.update(ATK[defense] if defense is not None
                         else ATK["krum"])
        args, fed, bundle, opt, spec = build(extra)
        sim = AsyncBufferedSimulator(args, fed, bundle, opt, spec)
        wall0 = time.perf_counter()
        r = sim.run()
        return {"updates_per_h": (r["updates_aggregated"]
                                  / r["virtual_time_s"] * 3600.0),
                "final_acc": r["final_test_acc"],
                "params": r["params"],
                "wall_s": time.perf_counter() - wall0}

    legs = {}
    for d in ("krum", "foolsgold"):
        legs[d] = {
            "sync": sync_defended_leg(d),
            "async": async_leg(d),
            "async_clean": async_leg(d, attacked=False),
        }
    # the undefended contrast needs its OWN clean twin: measuring the
    # undefended attacked run against a DEFENDED clean run would inflate
    # the denominator with defense-vs-mean aggregation divergence and let
    # the containment gate pass even when the defense failed
    undefended = async_leg(None)
    undefended_clean = async_leg(None, attacked=False)

    rec = {
        "metric": "fedavg_async_robust_updates_per_hour",
        "value": round(legs["krum"]["async"]["updates_per_h"], 1),
        "unit": (f"client updates incorporated per SIMULATED hour (digits "
                 f"FedAvg+LR, {n_clients} clients, {n_byz} byzantine at "
                 f"10x byzantine_random, K={k} DEFENDED async pours with "
                 f"base-ring re-basing; seeded {int(p_drop*100)}% dropout "
                 f"+ {int(p_strag*100)}% stragglers at 2.5x; sync "
                 f"defended barrier deadline {deadline:.2f}s drops late "
                 "uploads)"),
        "vs_baseline": round(legs["krum"]["async"]["updates_per_h"]
                             / max(legs["krum"]["sync"]["updates_per_h"],
                                   1e-9), 3),
        "data_provenance": legs["krum"]["sync"]["provenance"],
    }
    for d in ("krum", "foolsgold"):
        L = legs[d]
        rec[f"{d}_sync_updates_per_h"] = round(L["sync"]["updates_per_h"],
                                               1)
        rec[f"{d}_async_updates_per_h"] = round(
            L["async"]["updates_per_h"], 1)
        rec[f"{d}_async_vs_sync"] = round(
            L["async"]["updates_per_h"]
            / max(L["sync"]["updates_per_h"], 1e-9), 3)
        rec[f"{d}_sync_final_acc"] = round(L["sync"]["final_acc"], 4)
        rec[f"{d}_async_final_acc"] = round(L["async"]["final_acc"], 4)
        # byzantine containment: attacked-defended vs attack-free-defended
        rec[f"{d}_params_dist_vs_attack_free"] = round(
            rel_dist(L["async_clean"]["params"], L["async"]["params"]), 4)
    rec["undefended_attacked_final_acc"] = round(
        undefended["final_acc"], 4)
    rec["undefended_params_dist_vs_attack_free"] = round(
        rel_dist(undefended_clean["params"], undefended["params"]), 4)
    rec["byzantine_kept_out"] = bool(
        rec["krum_params_dist_vs_attack_free"]
        < 0.1 * rec["undefended_params_dist_vs_attack_free"])
    rec["foolsgold_containment_note"] = (
        "weak vs a 2-strong flip collusion in sync AND async alike — "
        "the leg pins async/sync behavior parity + stateful defended-"
        "pour throughput, not containment")
    print(json.dumps(rec), flush=True)


def bench_chaos_selection(target_acc=0.90, max_rounds=80):
    """Participant-selection axis (core/selection, ISSUE 5): digits
    FedAvg+LR with PARTIAL participation (5 of 10 clients per round)
    under the chaos bench's seeded 20% dropout + 10% stragglers —
    ``uniform`` (the static default: fixed cohort size, blind draw) vs
    ``oort`` (loss-utility cohorts) and ``reputation``, both with
    adaptive over-sampling from the OBSERVED Beta-posterior dropout rate
    in place of the static ``chaos_over_sample`` knob. Same 90% digits
    target as the other chaos leg; a selection strategy must strictly
    beat uniform rounds-to-target for the subsystem to earn its keep."""
    import jax.numpy as jnp

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    def leg(strategy: str):
        extra = {}
        if strategy != "uniform":
            extra = dict(client_selection=strategy,
                         selection_adaptive_oversample=True,
                         selection_max_over_sample=1.0)
        args = Arguments(
            dataset="digits", model="lr", client_num_in_total=10,
            client_num_per_round=5, comm_round=max_rounds, epochs=1,
            batch_size=32, learning_rate=0.1, frequency_of_the_test=10_000,
            random_seed=0, chaos_dropout_prob=0.2,
            chaos_straggler_prob=0.1, chaos_straggler_work=0.5,
            chaos_seed=7, chaos_tolerance=True, **extra)
        fed, output_dim = load(args)
        bundle = create(args, output_dim)
        spec = ClassificationTrainer(bundle.apply)
        opt = create_optimizer(args, spec)
        sim = TPUSimulator(args, fed, bundle, opt, spec)
        hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                           epochs=1)
        t0 = time.perf_counter()
        hit_round, acc = None, 0.0
        for round_idx in range(max_rounds):
            sim.run_round(round_idx, hyper)
            stats = sim._evaluate(sim.params, sim.fed.test["x"],
                                  sim.fed.test["y"], sim.fed.test["mask"])
            acc = float(stats["correct"]) / max(float(stats["count"]), 1.0)
            if hit_round is None and acc >= target_acc:
                hit_round = round_idx
                break
        return {"rounds_to_target": hit_round, "final_acc": acc,
                "wall_s": time.perf_counter() - t0,
                "provenance": getattr(fed, "provenance", "real")}

    uni = leg("uniform")
    oort = leg("oort")
    rep = leg("reputation")
    best = min((l for l in (oort, rep)
                if l["rounds_to_target"] is not None),
               key=lambda l: l["rounds_to_target"], default=oort)
    print(json.dumps({
        "metric": "fedavg_chaos_selection_rounds_to_target",
        "value": best["rounds_to_target"],
        "unit": f"rounds to {target_acc:.0%} digits test acc under seeded "
                f"20% dropout + 10% stragglers (5 of 10 clients/round, "
                f"FedAvg+LR, best selection strategy; max {max_rounds})",
        "vs_baseline": (uni["rounds_to_target"] / max(
                            best["rounds_to_target"], 1)
                        if best["rounds_to_target"] is not None
                        and uni["rounds_to_target"] is not None else None),
        "uniform_rounds_to_target": uni["rounds_to_target"],
        "oort_rounds_to_target": oort["rounds_to_target"],
        "reputation_rounds_to_target": rep["rounds_to_target"],
        "uniform_final_acc": round(uni["final_acc"], 4),
        "oort_final_acc": round(oort["final_acc"], 4),
        "reputation_final_acc": round(rep["final_acc"], 4),
        "uniform_wall_s": round(uni["wall_s"], 2),
        "oort_wall_s": round(oort["wall_s"], 2),
        "data_provenance": uni["provenance"],
    }), flush=True)


def bench_engine_mfu_resnet18():
    """Engine MFU on an MXU-friendly federated CV workload (VERDICT r4
    item 2): FedAvg ResNet-18 (64..512-wide channels), 64 clients/round,
    bf16, fused 8-round dispatch — the proof that the ENGINE feeds the
    MXU once operand shapes allow it, completing the flagship roofline
    story (the ResNet-56 line's 6.9% is the workload's 16..64-wide
    channels, BASELINE.md §3b). Reference counterpart: the NCCL
    simulator's raison d'être
    (``/root/reference/python/fedml/simulation/nccl/README.md:5``).
    vs_baseline = per-sample-normalized speedup over the golden SP loop
    on the same model."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.sp.simulator import SPSimulator
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    n_clients = 64
    args = Arguments(
        dataset="cifar10", model="resnet18", precision="bfloat16",
        client_num_in_total=n_clients, client_num_per_round=n_clients,
        comm_round=1, epochs=1, batch_size=32, learning_rate=0.1,
        frequency_of_the_test=10_000, random_seed=0,
        allow_synthetic=True, synthetic_size=50_000)
    fed, output_dim = load(args)
    provenance = getattr(fed, "provenance", "real")
    bundle = create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                       epochs=1)
    opt = create_optimizer(args, spec)
    sim = TPUSimulator(args, fed, bundle, opt, spec)
    r = [0]
    BLOCK = 8

    def block():
        sim.run_rounds_fused(r[0], BLOCK, hyper)
        r[0] += BLOCK

    block()
    _force(sim.params)
    # min-of-3: the tunneled chip occasionally hiccups for seconds at a
    # time (remote compile service contention); the minimum is the
    # engine's actual steady-state, and the trials are disclosed
    trials = []
    for _ in range(3):
        t0 = time.perf_counter()
        block()
        _force(sim.params)
        trials.append((time.perf_counter() - t0) / BLOCK)
    round_s = min(trials)
    flops = sim.round_cost_flops(hyper)
    achieved_tflops = flops / round_s / 1e12
    peak = _peak_tflops(jax.devices()[0])
    mfu = (achieved_tflops / (peak * sim.n_devices)) if peak else None

    # SP golden baseline at 1/8 workload, per-sample normalized (same
    # honesty protocol as the flagship line)
    bargs = Arguments(
        dataset="cifar10", model="resnet18", precision="bfloat16",
        client_num_in_total=8, client_num_per_round=8, comm_round=1,
        epochs=1, batch_size=32, learning_rate=0.1,
        frequency_of_the_test=-1,  # timing: no eval inside the timed call
        random_seed=0, allow_synthetic=True, synthetic_size=6_250,
        max_total_samples=6_250)
    bfed, _ = load(bargs)
    sp_sim = SPSimulator(bargs, bfed, bundle,
                         create_optimizer(bargs, spec), spec)
    sp_sim.run(comm_round=1)
    _force(sp_sim.params)
    # same honesty protocol as the engine leg: min over DISCLOSED trials
    # (a tunnel hiccup in a mean would asymmetrically inflate the ratio)
    sp_trials = []
    for _ in range(3):
        t0 = time.perf_counter()
        sp_sim.run(comm_round=1)
        _force(sp_sim.params)
        sp_trials.append(time.perf_counter() - t0)
    sp_round_s = min(sp_trials)
    vs_baseline = ((sp_round_s / float(bfed.total_train_samples))
                   / (round_s / float(fed.total_train_samples)))
    print(json.dumps({
        "metric": "fedavg_resnet18_engine_mfu",
        "value": round(mfu, 4) if mfu is not None else None,
        "unit": f"MFU (FedAvg ResNet-18, 64 clients/round, bf16, fused "
                f"8-round dispatch, {provenance} data)",
        "vs_baseline": round(vs_baseline, 3),
        "rounds_per_hour": round(3600.0 / round_s, 1),
        "step_time_s": round(round_s, 4),
        "tflops": round(achieved_tflops, 2),
        "round_s_trials": [round(t, 4) for t in trials],
        "sp_baseline_round_s": round(sp_round_s, 4),
        "sp_baseline_trials": [round(t, 4) for t in sp_trials],
        "n_devices": sim.n_devices,
        "data_provenance": provenance,
        "mfu_vs_resnet56_line": "see fedavg_resnet56 line: same engine, "
                                "workload-bound channels",
    }), flush=True)


def bench_robust_defended(metric, unit_note, config_kw, rounds_per_leg=16,
                          block=8, host_kw=None):
    """Defended-round throughput (ISSUEs 2/4): run the SAME robust config
    twice — ``robust_fused: host`` (train dispatch -> host-ordered update
    matrix -> defense dispatch -> server-update dispatch, the pre-fusion
    pipeline; ``host_kw`` can force it further back, e.g.
    ``sharded_defense: false`` for the contribution leg's pre-ISSUE-4
    behavior) vs ``robust_fused: auto`` (the whole robust round as ONE
    jitted SPMD program, fused ``block`` rounds per dispatch). The two
    paths must agree client-for-client — identical defense verdicts imply
    identical final params, which is what ``params_max_abs_diff`` audits;
    a speedup that changes verdicts would be a bug, not a win."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    def build(mode, extra):
        args = Arguments(
            dataset="synthetic_mnist", model="lr",
            client_num_in_total=16, client_num_per_round=16,
            comm_round=rounds_per_leg, epochs=1, batch_size=32,
            learning_rate=0.1, frequency_of_the_test=10_000,
            random_seed=0, robust_fused=mode, **config_kw, **extra)
        fed, output_dim = load(args)
        bundle = create(args, output_dim)
        spec = ClassificationTrainer(bundle.apply)
        sim = TPUSimulator(args, fed, bundle,
                           create_optimizer(args, spec), spec)
        hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                           epochs=1)
        return sim, hyper

    def timed_leg(mode, extra):
        sim, hyper = build(mode, extra)
        r = [0]

        def leg_block():
            sim.run_rounds_fused(r[0], block, hyper)
            r[0] += block

        leg_block()  # compile warmup
        _force(sim.params)
        trials = []
        for _ in range(max(rounds_per_leg // block, 2)):
            t0 = time.perf_counter()
            leg_block()
            _force(sim.params)
            trials.append((time.perf_counter() - t0) / block)
        return min(trials), trials, sim

    fused_s, fused_trials, sim_f = timed_leg("auto", {})
    host_s, host_trials, sim_h = timed_leg("host", host_kw or {})
    assert sim_f.robust_fused and not sim_h.robust_fused
    # verdict audit: both engines ran the identical round sequence above —
    # identical params <=> identical defense verdicts client-for-client
    diff = max(float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                     - b.astype(jnp.float32))))
               for a, b in zip(jax.tree_util.tree_leaves(sim_f.params),
                               jax.tree_util.tree_leaves(sim_h.params)))
    speedup = host_s / fused_s if fused_s else None
    print(json.dumps({
        "metric": metric,
        "value": round(3600.0 / fused_s, 1),
        "unit": f"defended rounds/hour ({unit_note}, fused {block}-round "
                f"dispatch)",
        "vs_baseline": round(speedup, 3) if speedup else None,
        "host_path_rounds_per_hour": round(3600.0 / host_s, 1),
        "step_time_s": round(fused_s, 4),
        "host_path_step_time_s": round(host_s, 4),
        "fused_trials": [round(t, 4) for t in fused_trials],
        "host_trials": [round(t, 4) for t in host_trials],
        "params_max_abs_diff": diff,
        "verdicts_identical": bool(diff < 1e-5),
        "n_devices": sim_f.n_devices,
    }), flush=True)


def bench_robust_krum(rounds_per_leg=16, block=8):
    """ISSUE 2 leg: byzantine-flip x3 + multi-krum m=5."""
    bench_robust_defended(
        "fedavg_robust_krum_rounds_per_hour",
        "16 clients, byzantine-flip x3 + multi-krum m=5",
        dict(enable_attack=True, attack_type="byzantine_flip",
             byzantine_client_num=3, attack_scale=5.0, enable_defense=True,
             defense_type="multi_krum", krum_param_m=5),
        rounds_per_leg=rounds_per_leg, block=block)


def bench_robust_rfa(rounds_per_leg=16, block=8):
    """ISSUE 4 leg: RFA (smoothed Weiszfeld geometric median) — the
    strongest defense we ship, host-only before this issue. The fused
    program runs the whole Weiszfeld loop on feature shards (psum'd
    distance fragments per iteration), so the ~3x dispatch tax is gone."""
    bench_robust_defended(
        "fedavg_robust_rfa_rounds_per_hour",
        "16 clients, byzantine-flip x3 + RFA geometric median",
        dict(enable_attack=True, attack_type="byzantine_flip",
             byzantine_client_num=3, attack_scale=5.0, enable_defense=True,
             defense_type="rfa"),
        rounds_per_leg=rounds_per_leg, block=block)


def bench_contribution_fused(rounds_per_leg=16, block=8):
    """ISSUE 4 leg: contribution assessment (LOO) + multi-krum. Before
    this issue ``contribution.enabled`` forced the full host fallback
    (collect dispatch + host defense + host Shapley/LOO); now the round
    stays ONE fused dispatch and the K+1 coalition evaluations run on the
    sharded matrix. The host leg pins the pre-ISSUE-4 behavior
    (``sharded_defense: false`` so the defense AND assessor are
    host-side)."""
    bench_robust_defended(
        "fedavg_contribution_loo_rounds_per_hour",
        "16 clients, multi-krum m=5 + LOO contribution",
        dict(enable_defense=True, defense_type="multi_krum",
             krum_param_m=5, contribution_method="loo"),
        rounds_per_leg=rounds_per_leg, block=block,
        host_kw=dict(sharded_defense="false"))


def bench_hierarchical_femnist(global_rounds=2):
    """BASELINE config 5: cross-device hierarchical FL, FEMNIST shapes
    (28x28x1, 62 classes), MobileNetV3-Small — groups run
    ``group_comm_round`` edge FedAvg rounds per global round, then the
    edge models average (reference ``sp_hierarchicalfl_mnist_lr_example``
    + ``data/FederatedEMNIST`` + ``model/cv/mobilenet.py``). Real FEMNIST
    is a LEAF download (no egress here), so the stand-in is loudly
    synthetic with the real shapes; throughput is shape-determined."""
    import jax.numpy as jnp

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.runner import FedMLRunner

    args = Arguments(
        dataset="femnist", model="mobilenet", precision="bfloat16",
        client_num_in_total=24, client_num_per_round=24,
        comm_round=1, epochs=1, batch_size=16, learning_rate=0.05,
        group_num=4, group_comm_round=2,
        federated_optimizer="hierarchicalfl",
        frequency_of_the_test=-1,  # timing: no eval inside the timed call
        random_seed=0, allow_synthetic=True)
    fed, output_dim = load(args)
    provenance = getattr(fed, "provenance", "real")
    bundle = create(args, output_dim)
    runner = FedMLRunner(args, dataset=fed, model=bundle)
    sim = runner.runner
    sim.run(comm_round=1)  # warmup: compile (persistent-cached) + 1 round
    _force(sim.params)
    t0 = time.perf_counter()
    for _ in range(global_rounds):
        sim.run(comm_round=1)
    _force(sim.params)
    round_s = (time.perf_counter() - t0) / global_rounds
    print(json.dumps({
        "metric": "hierarchical_femnist_mobilenet_rounds_per_hour",
        "value": round(3600.0 / round_s, 1),
        "unit": f"global rounds/hour (24 clients, 4 groups x 2 edge "
                f"rounds, MobileNetV3-Small, bf16, {provenance} data)",
        "vs_baseline": None,
        "step_time_s": round(round_s, 4),
        "data_provenance": provenance,
    }), flush=True)


def bench_shakespeare_fedopt(rounds=12, target_acc=0.21):
    """BASELINE.json config 3: FedOpt + LSTM next-character prediction on
    REAL text — the bundled role-partitioned Shakespeare shard (public
    domain, client = speaking role, same natural partition as LEAF
    fed_shakespeare). Reports round throughput and accuracy vs the
    majority-character baseline (~0.19)."""
    import jax.numpy as jnp

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.core.algframe.client_trainer import make_trainer_spec
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    args = Arguments(
        dataset="shakespeare", model="rnn", client_num_in_total=10,
        client_num_per_round=10, comm_round=rounds, epochs=2,
        batch_size=16, learning_rate=0.4, federated_optimizer="fedopt",
        server_optimizer="sgd", server_lr=1.0, server_momentum=0.9,
        frequency_of_the_test=10_000, random_seed=0)
    fed, output_dim = load(args)
    provenance = getattr(fed, "provenance", "real")
    bundle = create(args, output_dim)
    spec = make_trainer_spec(fed, bundle)
    opt = create_optimizer(args, spec)
    sim = TPUSimulator(args, fed, bundle, opt, spec)
    hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                       epochs=int(args.epochs))

    sim.run_round(0, hyper)  # compile warmup
    _force(sim.params)
    # rounds/hour times run_round ALONE; time-to-target runs on its own
    # wall clock that legitimately includes the per-round eval cost
    # (mirrors bench_time_to_acc) — mixing them would let the eval passes
    # before the target hit contaminate the throughput headline
    train_s = 0.0
    t0 = time.perf_counter()
    t_hit, hit_round = None, None
    for round_idx in range(1, rounds):
        r0 = time.perf_counter()
        sim.run_round(round_idx, hyper)
        _force(sim.params)
        train_s += time.perf_counter() - r0
        if t_hit is None:
            stats = sim._evaluate(sim.params, sim.fed.test["x"],
                                  sim.fed.test["y"], sim.fed.test["mask"])
            acc = float(stats["correct"]) / max(float(stats["count"]), 1.0)
            if acc >= target_acc:
                t_hit, hit_round = time.perf_counter() - t0, round_idx
    dt = train_s / (rounds - 1)
    stats = sim._evaluate(sim.params, sim.fed.test["x"],
                          sim.fed.test["y"], sim.fed.test["mask"])
    acc = float(stats["correct"]) / max(float(stats["count"]), 1.0)
    print(json.dumps({
        "metric": "fedopt_shakespeare_rnn_rounds_per_hour",
        "value": round(3600.0 / dt, 1),
        "unit": "rounds/hour (10 roles, LSTM NWP, FedOpt momentum server)",
        "vs_baseline": None,
        "round_s": round(dt, 4),
        "final_acc": round(acc, 4),
        "target_acc": target_acc,
        "time_to_target_s": round(t_hit, 2) if t_hit else None,
        "rounds_to_target": hit_round,
        "data_provenance": provenance,
    }), flush=True)


def bench_federated_lora(rounds=4):
    """BASELINE.json config 4 as a *federated round* (not just one train
    step): two silos LoRA-fine-tune a causal LM on REAL bundled text; each
    round ships only the adapter tree. Reports federated round latency."""
    import jax.numpy as jnp

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.llm.federated import build_llm
    from fedml_tpu.llm.lora import lora_param_count
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    args = Arguments(
        dataset="llm", model="causal_lm", precision="bfloat16",
        client_num_in_total=2, client_num_per_round=2, comm_round=rounds,
        epochs=1, batch_size=8, learning_rate=1e-3,
        federated_optimizer="fedavg", frequency_of_the_test=10_000,
        random_seed=0, llm_corpus_fallback="shakespeare",
        llm_hidden_size=512, llm_intermediate_size=1408, llm_num_layers=4,
        llm_num_heads=8, llm_max_seq_len=256, lora_rank=8)
    fed, bundle, spec, _ = build_llm(args)
    provenance = getattr(fed, "provenance", "synthetic")
    opt = create_optimizer(args, spec)
    sim = TPUSimulator(args, fed, bundle, opt, spec)
    hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                       epochs=1)
    sim.run_round(0, hyper)  # compile warmup
    _force(sim.params)
    t0 = time.perf_counter()
    for round_idx in range(1, rounds):
        sim.run_round(round_idx, hyper)
        _force(sim.params)
    dt = (time.perf_counter() - t0) / (rounds - 1)
    adapters = lora_param_count(sim.params)
    print(json.dumps({
        "metric": "fedllm_lora_federated_round_s",
        "value": round(dt, 4),
        "unit": "s/round (2 silos, LoRA r8 adapters only on the wire, "
                "bf16 causal LM, seq 256)",
        "vs_baseline": None,
        "rounds_per_hour": round(3600.0 / dt, 1),
        "adapter_params": int(adapters),
        "data_provenance": provenance,
    }), flush=True)


def _llm_train_step_timing(seq_len: int, bs: int, steps: int, iters: int,
                           attention_impl: str):
    """Shared harness for the LLM train-step metrics: one causal-LM
    (Llama-style block, bf16) scan-of-steps under jit, timed after a
    compile warmup. ``attention_impl`` is EXPLICIT — the production
    default on TPU is the Pallas flash kernels (llm/federated.py), and a
    bench must name the code path it ran. Returns (s_per_step, n_params,
    flops_per_step)."""
    import jax
    import jax.numpy as jnp
    import optax

    from fedml_tpu.llm.model import LLMConfig, init_llm, count_params
    from fedml_tpu.llm.trainer import CausalLMTrainer

    cfg = LLMConfig(vocab_size=8192, hidden_size=1024,
                    intermediate_size=2816, num_layers=8, num_heads=8,
                    max_seq_len=seq_len, dtype="bfloat16",
                    attention_impl=attention_impl)
    rng = jax.random.PRNGKey(0)
    model, params = init_llm(cfg, rng)
    spec = CausalLMTrainer(
        lambda p, x, rng=None, train=False: model.apply(
            {"params": p}, x, train=train))
    batch = {
        "x": jax.random.randint(rng, (bs, seq_len), 0, cfg.vocab_size),
        "y": jax.random.randint(rng, (bs, seq_len), 0, cfg.vocab_size),
        "mask": jnp.ones((bs,), jnp.float32),
    }
    tx = optax.sgd(1e-3)

    def many_steps(params, batch, rng):
        opt_state = tx.init(params)

        def one(carry, i):
            params, opt_state = carry
            (_, aux), grads = jax.value_and_grad(
                spec.loss, has_aux=True)(params, batch,
                                         jax.random.fold_in(rng, i))
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), None

        (params, _), _ = jax.lax.scan(one, (params, opt_state),
                                      jnp.arange(steps))
        return params

    jfn = jax.jit(many_steps)
    _force(jfn(params, batch, rng))
    t0 = time.perf_counter()
    for _ in range(iters):
        _force(jfn(params, batch, rng))
    dt = (time.perf_counter() - t0) / iters / steps
    return dt, count_params(params), cfg.flops_per_token() * bs * seq_len


def bench_llm_mfu(steps=16):
    """Single-chip causal-LM train-step MFU: the FedLLM hot loop with
    MXU-sized matmuls (d_model 1024), through the PRODUCTION attention
    path (Pallas flash on TPU). Demonstrates the runtime's ceiling when
    operand shapes fit the hardware."""
    import jax

    bs, L = 8, 1024
    impl = "flash" if jax.default_backend() == "tpu" else "dense"
    dt, n_params, flops = _llm_train_step_timing(L, bs, steps, iters=2,
                                                 attention_impl=impl)
    achieved = flops / dt / 1e12
    peak = _peak_tflops(jax.devices()[0])
    mfu = achieved / peak if peak else None
    print(json.dumps({
        "metric": "llm_train_step_mfu",
        "value": round(mfu, 4) if mfu is not None else None,
        "unit": f"MFU (bf16, {n_params/1e6:.0f}M params, "
                f"bs{bs} x seq{L}, {impl} attention, single chip)",
        "vs_baseline": None,
        "step_time_s": round(dt, 4),
        "tflops": round(achieved, 2),
        "tokens_per_s": round(bs * L / dt, 0),
        "attention_impl": impl,
    }), flush=True)


def bench_long_context(seq_len=4096, steps=8, metric_suffix=""):
    """Long-context training throughput through the Pallas flash fwd+bwd
    kernels (a dense backward at s=4096 would materialize 64 MiB of
    scores per head per layer; flash trains in O(s·block) memory — the
    property test_flash_bwd_never_materializes_scores asserts on-chip;
    ring attention extends the same contract across chips,
    test_ring_bwd_residuals_stay_linear_in_s). Off-TPU falls back to
    dense and says so in the unit string."""
    import jax

    impl = "flash" if jax.default_backend() == "tpu" else "dense"
    dt, _, flops = _llm_train_step_timing(seq_len, 1, steps, iters=2,
                                          attention_impl=impl)
    peak = _peak_tflops(jax.devices()[0])
    mfu = (flops / dt / 1e12 / peak) if peak else None
    print(json.dumps({
        "metric": "llm_long_context_train_tokens_per_s" + metric_suffix,
        "value": round(seq_len / dt, 0),
        "unit": f"tokens/s (bf16, seq {seq_len}, bs 1, {impl} fwd+bwd, "
                "single chip)",
        "vs_baseline": None,
        "step_time_s": round(dt, 4),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "attention_impl": impl,
    }), flush=True)


def bench_llm_serving(concurrencies=(1, 8, 64), max_new=24):
    """Continuous-batching serving throughput (ISSUE 9): tokens/s and p99
    request latency at concurrency 1/8/64 through the paged-KV batched
    decode engine vs the original one-request-at-a-time full-forward
    loop, single-adapter vs a 64-adapter LoRA bank (every request routed
    to a different silo's personalization). The decode step must compile
    exactly once across the whole sweep — occupancy and adapter mix are
    data."""
    import concurrent.futures as cf

    import jax
    import numpy as np

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core import mlops
    from fedml_tpu.llm.federated import build_llm
    from fedml_tpu.serving.llm_template import CausalLMPredictor

    args = Arguments(
        dataset="llm_synthetic", model="causal_lm",
        client_num_in_total=2, client_num_per_round=2, comm_round=1,
        epochs=1, batch_size=4, learning_rate=1e-3, random_seed=0,
        llm_hidden_size=128, llm_num_layers=2, llm_num_heads=4,
        llm_intermediate_size=352, llm_max_seq_len=128, lora_rank=8)
    _, bundle, _, tok = build_llm(args)
    params = bundle.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))
    prompts = [f"request {i}: summarize federated round {i * 7}"
               for i in range(max(concurrencies))]

    def sweep(gen, conc):
        """gen(i) -> result dict; returns (tokens_per_s, p99_latency_s)
        with per-request latency measured from sweep start (what a queued
        user experiences)."""
        t0 = time.perf_counter()
        lats = [0.0] * conc
        toks = [0] * conc

        def one(i):
            out = gen(i)
            lats[i] = time.perf_counter() - t0
            toks[i] = out["completion_tokens"]

        with cf.ThreadPoolExecutor(conc) as ex:
            list(ex.map(one, range(conc)))
        wall = time.perf_counter() - t0
        p99 = sorted(lats)[min(conc - 1, int(0.99 * (conc - 1) + 0.5))]
        return sum(toks) / wall, p99

    legs = {}
    # --- sequential baseline: the original single-request path ----------
    seq_pred = CausalLMPredictor(bundle, params, tokenizer=tok)
    seq_pred.generate("warm", max_new_tokens=2)
    seq_lock = __import__("threading").Lock()

    def seq_gen(i):
        with seq_lock:  # the old loop serves one request at a time
            return seq_pred.generate(prompts[i], max_new_tokens=max_new)

    for c in concurrencies:
        tps, p99 = sweep(seq_gen, c)
        legs[f"sequential_c{c}"] = {"tokens_per_s": round(tps, 1),
                                    "p99_latency_s": round(p99, 3)}

    # --- batched: single-adapter bank, then 64-adapter bank -------------
    mlops.install_compile_counter()
    for bank_size, tag in ((1, "bank1"), (64, "bank64")):
        pred = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": max(concurrencies), "block_size": 16,
                        "prefill_chunk": 32, "max_adapters": 66})
        names = [None]
        if bank_size > 1:
            rng = jax.random.PRNGKey(1)
            leaves, treedef = jax.tree_util.tree_flatten(params)
            for a in range(bank_size):
                k = jax.random.fold_in(rng, a)
                tree = jax.tree_util.tree_unflatten(
                    treedef, [0.1 * jax.random.normal(
                        jax.random.fold_in(k, j), l.shape)
                        for j, l in enumerate(leaves)])
                pred.adapter_bank.add(f"silo_{a}", tree)
            names = [f"silo_{a}" for a in range(bank_size)]
        try:
            pred.generate("warm", max_new_tokens=2,
                          adapter=names[0])   # compile warmup
            compiles0 = mlops.compile_count()
            for c in concurrencies:
                tps, p99 = sweep(
                    lambda i: pred.generate(
                        prompts[i], max_new_tokens=max_new,
                        adapter=names[i % len(names)]), c)
                legs[f"batched_{tag}_c{c}"] = {
                    "tokens_per_s": round(tps, 1),
                    "p99_latency_s": round(p99, 3)}
            legs[f"batched_{tag}_recompiles"] = (mlops.compile_count()
                                                 - compiles0)
        finally:
            pred.close()

    top = max(concurrencies)
    speedup = (legs[f"batched_bank1_c{top}"]["tokens_per_s"]
               / max(legs[f"sequential_c{top}"]["tokens_per_s"], 1e-9))
    print(json.dumps({
        "metric": "llm_serving_tokens_per_s",
        "value": legs[f"batched_bank1_c{top}"]["tokens_per_s"],
        "unit": f"generated tokens/s (batched decode, {top} slots, paged "
                f"KV, seq 128, {max_new} new tokens/request, "
                f"{jax.default_backend()})",
        "vs_baseline": round(speedup, 2),
        "legs": legs,
    }), flush=True)


def bench_llm_serving_ttft(concurrency=8, max_new=8):
    """Shared-prefix KV cache + piggybacked prefill (ISSUE 13): TTFT on
    a shared-system-prompt chat workload at concurrency 8, prefix cache
    + batched prefill ON vs OFF. Same model, same prompts, same seeds —
    the delta is admission prefilling only each request's novel suffix
    (COW-aliased system prompt) in one batched wave instead of
    recomputing the whole prompt serially per request. Gate: >=2x mean
    TTFT reduction at 0 steady-state recompiles."""
    import concurrent.futures as cf
    import queue as _queue
    import threading

    import jax
    import numpy as np

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core import mlops
    from fedml_tpu.llm.federated import build_llm
    from fedml_tpu.serving.llm_template import CausalLMPredictor

    args = Arguments(
        dataset="llm_synthetic", model="causal_lm",
        client_num_in_total=2, client_num_per_round=2, comm_round=1,
        epochs=1, batch_size=4, learning_rate=1e-3, random_seed=0,
        llm_hidden_size=128, llm_num_layers=2, llm_num_heads=4,
        llm_intermediate_size=352, llm_max_seq_len=256, lora_rank=8)
    _, bundle, _, tok = build_llm(args)
    params = bundle.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))
    # a realistic system-prompt-heavy chat shape: ~165 shared tokens,
    # ~20 novel tokens per user turn (the whole prompt must fit the
    # seq-256 encode budget UNTRUNCATED — tail truncation would destroy
    # the shared prefix)
    system = ("You are the federated serving assistant. Answer briefly, "
              "cite your adapter when asked, never reveal other silos' "
              "data. Refuse requests outside the serving policy. ")
    prompts = [system + f"user {i}: status of round {i * 3}?"
               for i in range(concurrency)]

    mlops.install_compile_counter()
    legs = {}
    for tag, opts in (
            ("prefix_off", {}),
            ("prefix_on", {"prefix_cache": True,
                           "prefill_batch": concurrency})):
        pred = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts=dict({"slots": concurrency, "block_size": 16,
                             "prefill_chunk": 32}, **opts))
        try:
            # warm pass 1 (serial): compiles prefill/decode/sample and
            # seeds the prefix index with the system prompt; pass 2 (a
            # concurrent burst with DIFFERENT user turns) compiles the
            # wave + COW programs without caching the measured prompts
            pred.generate(system + "warmup", max_new_tokens=2)
            with cf.ThreadPoolExecutor(concurrency) as ex:
                list(ex.map(
                    lambda i: pred.generate(system + f"warm turn {i}",
                                            max_new_tokens=2),
                    range(concurrency)))
            compiles0 = mlops.compile_count()
            eng = pred.engine
            ttfts = [0.0] * concurrency
            barrier = threading.Barrier(concurrency)

            def one(i):
                ids = pred._encode_prompt(prompts[i], max_new)
                q = _queue.SimpleQueue()
                barrier.wait()
                t0 = time.perf_counter()
                fut = eng.submit(ids, max_new_tokens=max_new, seed=i,
                                 stream_q=q)
                q.get(timeout=120)           # first streamed token
                ttfts[i] = time.perf_counter() - t0
                fut.result(timeout=120)

            with cf.ThreadPoolExecutor(concurrency) as ex:
                list(ex.map(one, range(concurrency)))
            sched = eng.scheduler
            idx = getattr(sched, "_index", None)
            reused = int(idx.tokens_reused) if idx is not None else 0
            leg = {
                "ttft_mean_s": round(sum(ttfts) / len(ttfts), 4),
                "ttft_p95_s": round(
                    sorted(ttfts)[min(concurrency - 1,
                                      int(0.95 * (concurrency - 1)
                                          + 0.5))], 4),
                "steady_state_recompiles": mlops.compile_count()
                - compiles0,
                "kv_fragmentation":
                    sched.kv_pool_stats()["fragmentation"],
                "cached_tokens_reused": reused,
            }
            if idx is not None:
                lookups = idx.hits + idx.misses
                leg["prefix_hit_rate"] = round(
                    idx.hits / max(lookups, 1), 3)
            legs[tag] = leg
        finally:
            pred.close()

    on, off = legs["prefix_on"], legs["prefix_off"]
    speedup = off["ttft_mean_s"] / max(on["ttft_mean_s"], 1e-9)
    print(json.dumps({
        "metric": "llm_serving_ttft",
        "value": on["ttft_mean_s"],
        "unit": f"mean TTFT seconds (c{concurrency}, ~{len(system)} "
                f"shared system-prompt chars, seq 256, prefix cache + "
                f"prefill wave on, {jax.default_backend()})",
        "vs_baseline": round(speedup, 2),
        "legs": legs,
    }), flush=True)


def bench_llm_serving_chaos(concurrency=8, requests=24, max_new=12):
    """Serving-plane fault tolerance (ISSUE 11): tokens/s GOODPUT (tokens
    from successfully finished requests only) and request success rate
    under a seeded crash+stall+NaN serving fault plan, recovery ON
    (watchdog-driven engine resets + requeue) vs recovery OFF (the
    PR-10 behavior: first trip parks the engine unhealthy). Same plan,
    same seed, same requests — the delta is the recovery layer."""
    import concurrent.futures as cf

    import jax
    import numpy as np

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core import mlops
    from fedml_tpu.core.chaos import (FaultLedger, FaultPlan,
                                      ServingChaosInjector)
    from fedml_tpu.llm.federated import build_llm
    from fedml_tpu.serving.llm_template import CausalLMPredictor

    args = Arguments(
        dataset="llm_synthetic", model="causal_lm",
        client_num_in_total=2, client_num_per_round=2, comm_round=1,
        epochs=1, batch_size=4, learning_rate=1e-3, random_seed=0,
        llm_hidden_size=128, llm_num_layers=2, llm_num_heads=4,
        llm_intermediate_size=352, llm_max_seq_len=128, lora_rank=8)
    _, bundle, _, tok = build_llm(args)
    params = bundle.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))
    # deterministic at-step faults keep both legs time-bounded: the
    # recovery-off leg must not sit out a 30s stall, and the NaN must
    # land inside the session's step window on any machine
    plan_kw = dict(seed=13, serving_stall_at_step=12, serving_stall_s=5.0,
                   serving_nan_at_step=25)

    mlops.install_compile_counter()
    legs = {}
    for tag, max_resets in (("recovery_on", 64), ("recovery_off", 0)):
        ledger = FaultLedger()
        inj = ServingChaosInjector(FaultPlan(**plan_kw), ledger=ledger)
        pred = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": concurrency, "block_size": 16,
                        "prefill_chunk": 32, "watchdog_s": 0.3,
                        "max_resets": max_resets, "max_requeues": 8,
                        "chaos": inj})
        pred._request_timeout_s = 30.0
        try:
            pred.generate("warm", max_new_tokens=2)
            compiles0 = mlops.compile_count()
            t0 = time.perf_counter()
            good_tokens = [0] * requests
            ok = [False] * requests

            def one(i):
                try:
                    out = pred.generate(
                        f"chaos bench req {i}", max_new_tokens=max_new,
                        temperature=(0.0 if i % 2 else 1.1), seed=i)
                except Exception:
                    return   # recovery-off: parked engine rejects
                if out["finish_reason"] in ("stop", "length"):
                    ok[i] = True
                    good_tokens[i] = out["completion_tokens"]

            with cf.ThreadPoolExecutor(concurrency) as ex:
                list(ex.map(one, range(requests)))
            wall = time.perf_counter() - t0
            eng = pred.engine
            legs[tag] = {
                "goodput_tokens_per_s": round(sum(good_tokens) / wall, 1),
                "success_rate": round(sum(ok) / requests, 3),
                "injected_faults": len(ledger.serving_events()),
                "engine_resets": int(eng.resets_total),
                "watchdog_trips": int(eng.watchdog.trips),
                "steady_state_recompiles": mlops.compile_count()
                - compiles0,
            }
        finally:
            pred.close()

    on, off = legs["recovery_on"], legs["recovery_off"]
    ratio = (on["goodput_tokens_per_s"]
             / max(off["goodput_tokens_per_s"], 1e-9))
    print(json.dumps({
        "metric": "llm_serving_chaos_goodput",
        "value": on["goodput_tokens_per_s"],
        "unit": f"goodput tokens/s (c{concurrency}, {requests} requests, "
                f"{max_new} new tokens each, seeded stall+NaN plan, "
                f"watchdog 0.3s, {jax.default_backend()})",
        "vs_baseline": round(ratio, 2),
        "legs": legs,
    }), flush=True)


def bench_llm_serving_fleet(replicas=3, tenants=8, sessions=32, turns=3,
                            max_new=16, concurrency=256):
    """Fleet serving soak (ISSUE 17): aggregate tokens/s on a sustained
    mixed-tenant multi-turn workload (the seeded ``scripts/serving_load``
    generator, c256) across 3+ in-process replicas behind the Gateway,
    with seeded chaos connection drops on the gateway wire and one
    deliberate replica loss mid-soak. ON = cache-aware routing +
    generated-token suffix caching + SLO autoscaler; OFF = the PR-16
    fleet (round-robin routing, prompt-only prefix cache, same chaos,
    same loss). Same model, same seeded workload — the delta is the
    fleet layer. Gate: >=1.3x aggregate tokens/s or >=1.5x mean-TTFT
    reduction, nonzero suffix hits, 0 steady-state recompiles during the
    fixed-fleet window (the post-loss replacement/scale-up is a cold
    start by definition and is reported separately)."""
    import threading

    import jax
    import numpy as np

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core import mlops
    from fedml_tpu.core.chaos import (FaultLedger, FaultPlan,
                                      ServingChaosInjector)
    from fedml_tpu.llm.federated import build_llm
    from fedml_tpu.serving.autoscale import (Autoscaler, EWMPolicy,
                                             Gateway, ReplicaSet, SLOPolicy)
    from fedml_tpu.serving.llm_template import (CausalLMPredictor,
                                                ChatCompletionRunner)
    from scripts.serving_load import LoadSpec, run_load

    args = Arguments(
        dataset="llm_synthetic", model="causal_lm",
        client_num_in_total=2, client_num_per_round=2, comm_round=1,
        epochs=1, batch_size=4, learning_rate=1e-3, random_seed=0,
        llm_hidden_size=128, llm_num_layers=2, llm_num_heads=4,
        llm_intermediate_size=352, llm_max_seq_len=1024, lora_rank=8)
    _, bundle, _, tok = build_llm(args)
    params = bundle.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))
    # turn_chars=200: every user turn carries ~200 chars of
    # per-session-unique text (pasted-log traffic), so beyond the shared
    # per-tenant system prompt nothing aliases ACROSS sessions — turn-2/3
    # prefill is paid in full unless the follow-up lands on the replica
    # that served turn 1 (cache-aware routing) and the reply blocks were
    # indexed at release (suffix cache)
    spec = LoadSpec(tenants=tenants, sessions_per_tenant=sessions,
                    turns_per_session=turns, seed=0, mean_gap_s=0.002,
                    max_tokens=max_new, turn_chars=200)
    total_requests = spec.total_requests

    mlops.install_compile_counter()
    legs = {}
    for tag, fleet_on in (("fleet_off", False), ("fleet_on", True)):
        ledger = FaultLedger()
        chaos = ServingChaosInjector(
            FaultPlan(seed=17, serving_conn_drop_prob=0.04), ledger=ledger)
        # num_blocks: grow the KV pool past the slot default (slots x
        # max_blocks_per_slot = 1024) so per-session conversation chains
        # survive cascade eviction across 256 concurrent sessions; same
        # pool both legs — the delta stays the fleet layer, not memory
        opts = {"slots": 16, "block_size": 16, "prefill_chunk": 64,
                "prefix_cache": True, "prefill_batch": 8,
                "request_timeout_s": 600.0, "num_blocks": 8192,
                "suffix_cache": fleet_on}

        def factory(opts=opts):
            return CausalLMPredictor(bundle, params, tokenizer=tok,
                                     mode="batch", stream=True,
                                     batch_opts=dict(opts))

        rs = ReplicaSet(predictor_factory=factory, min_replicas=replicas,
                        max_replicas=replicas + 1,
                        runner_cls=ChatCompletionRunner,
                        drain_grace_s=2.0 if fleet_on else 0.0)
        gw = Gateway(rs, unhealthy_ttl_s=0.75, max_failovers=4,
                     backoff_seed=0, chaos=chaos,
                     cache_aware=fleet_on, heal_probe=fleet_on)
        # ON: the SLO policy may add the +1 burst replica under queue /
        # headroom breach. OFF: the PR-16 loop — health_check still
        # replaces the lost replica (both legs heal), but the legacy
        # policy never scales past min_replicas under this traffic.
        policy = (SLOPolicy(queue_depth_per_replica=32.0,
                            kv_headroom_min=1, cooldown_s=3.0)
                  if fleet_on
                  else EWMPolicy(target_qps_per_replica=1e9))
        asc = Autoscaler(gw, policy, interval_s=0.25)
        lock = threading.Lock()
        ttfts, tokens, oks = [], [], []
        post_loss_mark = [None]     # index into oks at the loss instant
        steady_recompiles = [None]
        done = threading.Event()

        def send(messages, meta):
            req = {"messages": messages, "stream": True,
                   "max_tokens": int(meta["max_tokens"]),
                   "temperature": float(meta["temperature"]),
                   "seed": int(meta["seed"])}
            t0 = time.perf_counter()
            first, parts, usage = None, [], None
            try:
                for data in gw.stream(req, timeout=600.0):
                    evt = json.loads(data)
                    ch = evt["choices"][0]
                    delta = ch.get("delta") or {}
                    if delta.get("content"):
                        if first is None:
                            first = time.perf_counter() - t0
                        parts.append(delta["content"])
                    if ch.get("finish_reason"):
                        usage = ch.get("usage") or {}
            except Exception:
                with lock:
                    oks.append(False)
                raise
            with lock:
                oks.append(True)
                if first is not None:
                    ttfts.append(first)
                tokens.append(int((usage or {}).get(
                    "completion_tokens", len(parts))))
            return "".join(parts)

        def disrupt():
            # wait out the fixed-fleet (steady-state) window, snapshot
            # the recompile count, then lose a replica and hand the
            # fleet to the SLO autoscaler for the rest of the soak
            while not done.is_set():
                with lock:
                    n = len(oks)
                if n >= int(0.4 * total_requests):
                    break
                time.sleep(0.05)
            if done.is_set():
                return
            steady_recompiles[0] = mlops.compile_count() - compiles0
            with rs._lock:
                victim = rs.replicas[-1] if rs.replicas else None
            if victim is not None:
                victim.stop()           # replica loss, mid-soak
            with lock:
                post_loss_mark[0] = len(oks)
            while not done.is_set():
                try:
                    asc.step()   # heal + replace + SLO scale
                except Exception:
                    pass
                done.wait(0.3)

        try:
            # warm every replica: compiles prefill/wave/COW/decode/sample
            # and seeds each prefix index with nothing the soak measures
            with rs._lock:
                runners = list(rs.replicas)
            import concurrent.futures as cf
            for r in runners:
                r.predictor.generate("fleet warmup", max_new_tokens=2)
                with cf.ThreadPoolExecutor(8) as ex:
                    list(ex.map(
                        lambda i, p=r.predictor: p.generate(
                            f"fleet warm turn {i}", max_new_tokens=2),
                        range(8)))
            compiles0 = mlops.compile_count()
            watcher = threading.Thread(target=disrupt, daemon=True)
            watcher.start()
            t0 = time.perf_counter()
            run_load(send, spec, concurrency=concurrency)
            wall = time.perf_counter() - t0
            done.set()
            watcher.join(timeout=10.0)

            with rs._lock:
                engines = [r.predictor.engine for r in rs.replicas
                           if getattr(r, "predictor", None) is not None
                           and r.predictor.engine is not None]
            sfx_hits = sfx_tokens = hits = misses = 0
            for eng in engines:
                idx = getattr(eng.scheduler, "_index", None)
                if idx is None:
                    continue
                sfx_hits += idx.suffix_hits
                sfx_tokens += idx.suffix_tokens_reused
                hits += idx.hits
                misses += idx.misses
            mark = post_loss_mark[0]
            with lock:
                n_ok = sum(oks)
                post = oks[mark:] if mark is not None else []
                ttft_sorted = sorted(ttfts)
                total_tokens = sum(tokens)
            leg = {
                "tokens_per_s": round(total_tokens / wall, 1),
                "ttft_mean_s": round(
                    sum(ttft_sorted) / max(len(ttft_sorted), 1), 4),
                "ttft_p99_s": round(
                    ttft_sorted[min(len(ttft_sorted) - 1,
                                    int(0.99 * (len(ttft_sorted) - 1)
                                        + 0.5))], 4) if ttft_sorted
                else 0.0,
                "success_rate": round(n_ok / max(len(oks), 1), 3),
                "post_loss_success_rate": round(
                    sum(post) / max(len(post), 1), 3),
                "suffix_hits": int(sfx_hits),
                "suffix_tokens_reused": int(sfx_tokens),
                "prefix_hit_rate": round(
                    hits / max(hits + misses, 1), 3),
                "steady_state_recompiles": steady_recompiles[0],
                "cold_start_compiles": mlops.compile_count() - compiles0
                - (steady_recompiles[0] or 0),
                "scale_events": int(asc.scale_events),
                "injected_conn_drops": len(ledger.serving_events()),
                "replicas_end": len(rs),
                "routes": dict(gw.route_counts),
            }
            legs[tag] = leg
        finally:
            done.set()
            rs.stop()

    on, off = legs["fleet_on"], legs["fleet_off"]
    ratio = on["tokens_per_s"] / max(off["tokens_per_s"], 1e-9)
    ttft_ratio = off["ttft_mean_s"] / max(on["ttft_mean_s"], 1e-9)
    print(json.dumps({
        "metric": "llm_serving_fleet_tokens_per_s",
        "value": on["tokens_per_s"],
        "unit": f"aggregate tokens/s (c{concurrency}, {tenants} tenants x "
                f"{sessions} sessions x {turns} turns, {replicas} "
                f"replicas, chaos conn-drops + mid-soak replica loss, "
                f"{jax.default_backend()})",
        "vs_baseline": round(ratio, 2),
        "ttft_reduction": round(ttft_ratio, 2),
        "legs": legs,
    }), flush=True)


def bench_llm_serving_adapter_churn(concurrency=64, rounds=4, max_new=12,
                                    bank_size=8):
    """Sustained adapter churn (ISSUE 14 satellite, the ROADMAP's
    in-but-unmeasured leg): c64 traffic flows through the batched engine
    while ONE adapter per round is re-exported into the watched dir and
    hot-swapped live through the PR 12 watcher/pin machinery. The
    numbers that matter: tokens/s under churn vs a churn-free round on
    the same engine (the swap is a host→device stack refresh, so the
    gap should be noise) and ZERO recompiles across the whole run."""
    import concurrent.futures as cf
    import os
    import tempfile

    import jax
    import numpy as np

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core import mlops
    from fedml_tpu.llm.federated import build_llm, save_adapter_artifacts
    from fedml_tpu.serving.batch import AdapterBank
    from fedml_tpu.serving.llm_template import CausalLMPredictor

    args = Arguments(
        dataset="llm_synthetic", model="causal_lm",
        client_num_in_total=2, client_num_per_round=2, comm_round=1,
        epochs=1, batch_size=4, learning_rate=1e-3, random_seed=0,
        llm_hidden_size=128, llm_num_layers=2, llm_num_heads=4,
        llm_intermediate_size=352, llm_max_seq_len=128, lora_rank=8)
    _, bundle, _, tok = build_llm(args)
    params = bundle.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))

    def rand_adapter(seed):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        key = jax.random.PRNGKey(seed)
        return jax.tree_util.tree_unflatten(
            treedef, [0.1 * jax.random.normal(jax.random.fold_in(key, j),
                                              l.shape)
                      for j, l in enumerate(leaves)])

    export_dir = tempfile.mkdtemp(prefix="churn_adapters_")
    names = [f"silo_{a}" for a in range(bank_size)]
    save_adapter_artifacts({n: rand_adapter(a)
                            for a, n in enumerate(names)}, export_dir)
    # capacity: bank rows + a fresh row per swap (retired rows rejoin
    # the pool once their last in-flight pin drops)
    bank = AdapterBank.from_artifacts(export_dir,
                                      capacity=bank_size + rounds + 4)
    pred = CausalLMPredictor(
        bundle, params, tokenizer=tok, mode="batch",
        batch_opts={"slots": concurrency, "block_size": 16,
                    "prefill_chunk": 32},
        adapter_bank=bank)
    prompts = [f"request {i}: summarize federated round {i * 7}"
               for i in range(concurrency)]

    def sweep():
        t0 = time.perf_counter()
        lats = [0.0] * concurrency
        toks = [0] * concurrency

        def one(i):
            out = pred.generate(prompts[i], max_new_tokens=max_new,
                                adapter=names[i % len(names)])
            lats[i] = time.perf_counter() - t0
            toks[i] = out["completion_tokens"]

        with cf.ThreadPoolExecutor(concurrency) as ex:
            list(ex.map(one, range(concurrency)))
        wall = time.perf_counter() - t0
        p99 = sorted(lats)[min(concurrency - 1,
                               int(0.99 * (concurrency - 1) + 0.5))]
        return sum(toks) / wall, p99

    legs = {}
    try:
        mlops.install_compile_counter()
        pred.generate("warm", max_new_tokens=2, adapter=names[0])
        sweep()                                    # warm the sweep path
        tps0, p99_0 = sweep()                      # churn-free reference
        legs["no_churn"] = {"tokens_per_s": round(tps0, 1),
                            "p99_latency_s": round(p99_0, 3)}
        bank.watch_dir(export_dir, poll_s=0.1)
        time.sleep(0.15)                           # initial scan settles
        compiles0 = mlops.compile_count()
        churn_tps, churn_p99 = [], []
        for r in range(rounds):
            victim = names[r % len(names)]
            with cf.ThreadPoolExecutor(1) as swapper:
                # one hot-swap per round, landing MID-TRAFFIC: the
                # exporter thread re-writes the artifact while the c64
                # sweep decodes against the bank
                fut = swapper.submit(
                    save_adapter_artifacts,
                    {victim: rand_adapter(1000 + r)}, export_dir)
                tps, p99 = sweep()
                fut.result()
            churn_tps.append(tps)
            churn_p99.append(p99)
        deadline = time.time() + 10                # let the last swap land
        while time.time() < deadline and bank.swaps < rounds:
            time.sleep(0.05)
        recompiles = mlops.compile_count() - compiles0
        legs["churn"] = {
            "tokens_per_s": round(sum(churn_tps) / len(churn_tps), 1),
            "tokens_per_s_best": round(max(churn_tps), 1),
            "p99_latency_s": round(max(churn_p99), 3),
            "swaps": int(bank.swaps),
            "recompiles": int(recompiles)}
    finally:
        pred.close()
    ratio = legs["churn"]["tokens_per_s"] / max(
        legs["no_churn"]["tokens_per_s"], 1e-9)
    print(json.dumps({
        "metric": "llm_serving_adapter_churn_tokens_per_s",
        "value": legs["churn"]["tokens_per_s"],
        "unit": f"generated tokens/s (c{concurrency}, {bank_size}-adapter "
                f"bank, one watched hot-swap per round x{rounds}, "
                f"{max_new} new tokens/request, "
                f"{jax.default_backend()})",
        "vs_baseline": round(ratio, 3),
        "legs": legs,
    }), flush=True)


def bench_cohort_assembly(populations=(10_000, 100_000, 1_000_000),
                          rounds=8, k=128):
    """Million-client control plane (core/selection, ISSUE 15): per-round
    cohort-assembly cost over synthetic populations of 10k/100k/1M
    devices — streaming eligibility scan (hash-derived charging/idle/
    unmetered flags, ~51% eligible) + Oort-utility scoring over the
    SPARSE stats store + chunked partial top-k + the deadline pacer —
    and, on the same populations, the selection strategies' per-round
    ``select()`` cost with seeded candidate pools (``oort``) vs the
    uniform stream. The headline is the 1M-client assembly wall; the leg
    table carries the selection-overhead-vs-population column and the
    sublinearity ratio (1M ÷ 10k — linear scaling would read ~100x)."""
    import numpy as np

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.selection import (DeadlinePacer, SelectionManager,
                                          StreamingCohortAssembler,
                                          make_stats_store,
                                          population_chunks)
    from fedml_tpu.core.selection.cohort import _seeded_jitter

    def leg(n: int):
        args = Arguments(
            dataset="synthetic_mnist", model="lr", client_num_in_total=n,
            client_num_per_round=k, random_seed=7,
            sampling_stream="seeded", selection_store="sparse",
            cohort_require_charging=True, allow_synthetic=True)
        store = make_stats_store(args, n)
        # realistic warm history: a few thousand previously-seen devices
        rng = np.random.default_rng(0)
        touched = rng.choice(n, size=min(4096, n // 2), replace=False)
        for i, cid in enumerate(touched):
            store.record_selected(i % 64, [int(cid)])
            store.record_loss(int(cid), float(rng.gamma(2.0, 1.0)))
            store.record_latency(int(cid), float(rng.gamma(2.0, 5.0)))
            store.record_availability(int(cid),
                                      participated=bool(i % 5),
                                      work=1.0)
        asm = StreamingCohortAssembler(args, store, n)
        pacer = DeadlinePacer.from_args(args)

        def elig(ids):  # ~51% "charging" via the seeded hash
            return _seeded_jitter(ids, 99, 0) < 0.51

        walls = []
        for r in range(rounds):
            t0 = time.perf_counter()
            res = asm.assemble(r, pacer.target_cohort(k),
                               population_chunks(n, asm.chunk),
                               eligible_fn=elig)
            walls.append((time.perf_counter() - t0) * 1e3)
            pacer.observe_round(completed=int(0.9 * len(res.cohort)),
                                expected=len(res.cohort),
                                wall_s=pacer.deadline_s * 0.4)
        # strategy select() overhead on the same population (oort rides
        # a seeded candidate pool above the threshold; uniform rides the
        # streaming sampler)
        sel = {}
        for strat in ("uniform", "oort"):
            mgr = SelectionManager(
                Arguments(dataset="synthetic_mnist", model="lr",
                          client_num_in_total=n, client_num_per_round=k,
                          random_seed=7, sampling_stream="seeded",
                          selection_store="sparse",
                          client_selection=strat, allow_synthetic=True),
                n)
            t0 = time.perf_counter()
            for r in range(rounds):
                mgr.select(r, k)
            sel[strat] = (time.perf_counter() - t0) * 1e3 / rounds
        return {"assembly_ms": round(float(np.median(walls)), 3),
                "select_oort_ms": round(sel["oort"], 3),
                "select_uniform_ms": round(sel["uniform"], 3),
                "touched_rows": store.num_touched()}

    legs = {f"pop_{n//1000}k" if n < 1_000_000 else "pop_1m": leg(n)
            for n in populations}
    lo = legs[next(iter(legs))]
    hi = legs[list(legs)[-1]]
    ratio = hi["assembly_ms"] / max(lo["assembly_ms"], 1e-9)
    sel_ratio = hi["select_oort_ms"] / max(lo["select_oort_ms"], 1e-9)
    print(json.dumps({
        "metric": "cross_device_cohort_assembly_ms",
        "value": hi["assembly_ms"],
        "unit": f"median ms to assemble a {k}-cohort from 1M synthetic "
                f"devices (streaming eligibility + oort utility + "
                f"partial top-k, sparse store; legs: per-population "
                f"assembly and strategy-select overhead)",
        # ratios ride legs so bench_diff gates them (probe "overhead"
        # reads lower-is-better: selection must stay sublinear)
        "legs": dict(legs, scaling={
            "overhead_ratio_1m_vs_10k": round(ratio, 2),
            "select_overhead_ratio_1m_vs_10k": round(sel_ratio, 2)}),
        "population_scaling": f"{populations[-1] // populations[0]}x "
                              f"population -> {ratio:.1f}x assembly cost",
    }), flush=True)


def bench_cross_device_multitenant(n=100_000, rounds=6):
    """Durable multi-tenant fleet plane (core/fleet, ISSUE 18): 100k
    devices in a sqlite ``DeviceRegistry``, a ``TaskPlane`` running 3
    concurrent tasks (train k=256, federated analytics k=128, LLM-LoRA
    k=64) against that one population under a per-device fairness cap.
    Each timed round is a full plane step — per-task streaming assembly
    over the registry's id pages, atomic claims, release + participation
    records — under a logical clock. The headline is control-plane
    rounds/hour; the legs pin the ISOLATION and FAIRNESS columns
    (``overlap_devices`` and ``fairness_violations`` must read 0) plus
    the per-task cohort sizes and the assign wall."""
    import tempfile

    import numpy as np

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.fleet import DeviceRegistry, TaskPlane

    tasks = (("train", 256, "training"), ("fa", 128, "analytics"),
             ("lora", 64, "llm"))
    cap, window_s = 3, 3600.0
    with tempfile.TemporaryDirectory() as td:
        reg = DeviceRegistry(f"{td}/fleet.db")
        t0 = time.perf_counter()
        ids = np.arange(n)
        for lo in range(0, n, 10_000):
            reg.register_many(ids[lo:lo + 10_000], now=0.0)
        register_s = time.perf_counter() - t0
        args = Arguments(dataset="synthetic_mnist", model="lr",
                         client_num_in_total=n, random_seed=7,
                         selection_store="sparse", oort_alpha=0.0,
                         pacer_over_sample=1.0,
                         fleet_max_rounds_per_window=cap,
                         fleet_fairness_window_s=window_s,
                         allow_synthetic=True)
        plane = TaskPlane(args, reg, population=n)
        for tid, k, kind in tasks:
            plane.add_task(tid, cohort_k=k, kind=kind)
        walls, assign_ms, sizes = [], [], {t[0]: [] for t in tasks}
        for r in range(rounds):
            now = 60.0 * (r + 1)
            t0 = time.perf_counter()
            cohorts = plane.assign_round(now=now)
            t_assign = time.perf_counter() - t0
            for tid, cohort in cohorts.items():
                plane.observe_round(tid, cohort, wall_s=30.0,
                                    now=now + 30.0)
                sizes[tid].append(len(cohort))
            walls.append(time.perf_counter() - t0)
            assign_ms.append(t_assign * 1e3)
        audit = reg.audit(cap=cap, window_s=window_s)
        round_s = float(np.median(walls))
        print(json.dumps({
            "metric": "cross_device_multitenant_rounds_per_hour",
            "value": round(3600.0 / round_s, 1),
            "unit": f"full fleet-plane rounds/hour (3 concurrent tasks, "
                    f"{n // 1000}k-device sqlite registry, fairness cap "
                    f"{cap}/{window_s:.0f}s; isolation and fairness "
                    f"columns must read 0)",
            "legs": {
                "assign_ms": round(float(np.median(assign_ms)), 1),
                "round_s": round(round_s, 3),
                "register_100k_s": round(register_s, 2),
                "cohort_train": int(np.median(sizes["train"])),
                "cohort_fa": int(np.median(sizes["fa"])),
                "cohort_lora": int(np.median(sizes["lora"])),
                "overlap_devices": audit["overlap"],
                "fairness_violations": audit["cap_violations"],
                "denied_busy": plane.denied_busy,
                "denied_cap": plane.denied_cap,
            },
        }), flush=True)


def _sum_collective_kinds(colls, block):
    """Per-(op, group) wire bytes per round — SUMMED across distinct
    operand shapes (the roofline rows key on shape too; collapsing by
    overwrite would understate any kind with >1 payload shape)."""
    out = {}
    for c in colls:
        key = f"{c['op']}_g{c['group']}"
        out[key] = round(out.get(key, 0.0) + c["wire_bytes"] / block, 1)
    return out


def bench_robust_rfa_weak_scaling(device_counts=(1, 4, 8),
                                  rounds_per_leg=16, block=8,
                                  clients_per_device=2):
    """Weak scaling of the fused defended round (ISSUE 14 satellite —
    the missing BASELINE leg): `fedavg_robust_rfa_rounds_per_hour` at
    1/4/8 devices with CONSTANT per-device work (2 clients/device), so
    ideal scaling is a flat rounds/hour line. Each leg also captures the
    program's roofline (obs_roofline) and reports the predicted
    per-device collective wire bytes per round — the column that tells
    the multi-chip item whether a scaling cliff is the defense's
    psum/all_to_all traffic or something else. On the CPU host mesh the
    times are shape-comparable, the collective bytes exact, and a TPU
    re-run is the real verdict (BASELINE.md measurement-honesty note)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.constants import AXIS_CLIENT
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.core.obs import roofline as obs_roofline
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    devs = jax.devices()
    counts = [k for k in device_counts if k <= len(devs)]
    legs = {}
    # ISSUE 16: a second leg family with the int8-quantized all_to_all
    # re-layout (robust_relayout_quant) — same schedule, 4x fewer
    # re-layout wire bytes; its efficiency column is measured against its
    # OWN single-device base so the two families stay comparable
    for quant, suffix in ((None, ""), ("int8", "_int8")):
        base_rph = None
        for k in counts:
            n_clients = clients_per_device * k
            n_byz = max(1, n_clients // 8)
            args = Arguments(
                dataset="synthetic_mnist", model="lr",
                client_num_in_total=n_clients,
                client_num_per_round=n_clients,
                comm_round=rounds_per_leg, epochs=1, batch_size=32,
                learning_rate=0.1, frequency_of_the_test=10_000,
                random_seed=0, enable_attack=True,
                attack_type="byzantine_flip", byzantine_client_num=n_byz,
                attack_scale=5.0, enable_defense=True, defense_type="rfa",
                robust_relayout_quant=quant, obs_roofline=True)
            fed, output_dim = load(args)
            bundle = create(args, output_dim)
            spec = ClassificationTrainer(bundle.apply)
            mesh = Mesh(np.asarray(devs[:k]), (AXIS_CLIENT,))
            sim = TPUSimulator(args, fed, bundle,
                               create_optimizer(args, spec), spec,
                               mesh=mesh)
            hyper = TrainHyper(
                learning_rate=jnp.float32(args.learning_rate), epochs=1)
            r = [0]

            def leg_block():
                sim.run_rounds_fused(r[0], block, hyper)
                r[0] += block

            leg_block()                       # compile warmup + capture
            _force(sim.params)
            trials = []
            for _ in range(max(rounds_per_leg // block, 2)):
                t0 = time.perf_counter()
                leg_block()
                _force(sim.params)
                trials.append((time.perf_counter() - t0) / block)
            step_s = min(trials)
            rph = 3600.0 / step_s
            if base_rph is None:
                base_rph = rph
            rep = obs_roofline.report("robust_rounds_fused") or {}
            coll = rep.get("collective_wire_bytes")
            legs[f"d{k}{suffix}"] = {
                "rounds_per_hour": round(rph, 1),
                "step_time_s": round(step_s, 4),
                "clients": n_clients,
                "weak_scaling_efficiency": round(rph / base_rph, 3),
                "collective_wire_bytes_per_round": (
                    round(coll / block, 1) if coll is not None else None),
                "collective_kinds": _sum_collective_kinds(
                    rep.get("collectives", []), block),
            }
    top = f"d{counts[-1]}"
    print(json.dumps({
        "metric": "fedavg_robust_rfa_weak_scaling_efficiency",
        "value": legs[top]["weak_scaling_efficiency"],
        "unit": f"rounds/hour at {counts[-1]} devices ÷ at 1 device, "
                f"{clients_per_device} clients/device, byzantine-flip + "
                f"RFA fused {block}-round dispatch "
                f"({jax.default_backend()})",
        "vs_baseline": None,
        "legs": legs,
    }), flush=True)


def bench_fused_block(iters=12, batch=32):
    """Fused conv->GroupNorm->residual->ReLU block step (ISSUE 16
    tentpole): one resnet56 narrow-stage BasicBlock fwd+bwd at the
    flagship 32x32x16 geometry, Pallas kernel vs the unfused flax path.
    CPU-honest: off-TPU the kernel runs in Pallas INTERPRET mode, so the
    CPU ``fused_ms`` measures plumbing, not the kernel — the speedup leg
    is only a perf verdict on a TPU capture (BASELINE.md
    measurement-honesty note). The headline is the fused step time
    (lower is better); ``speedup`` = reference_ms / fused_ms."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.model.cv.resnet import BasicBlock

    x = jax.random.normal(jax.random.PRNGKey(0), (batch, 32, 32, 16))

    def leg(fused):
        m = BasicBlock(16, 1, fused=fused)
        variables = m.init(jax.random.PRNGKey(1), x)
        step = jax.jit(jax.grad(
            lambda v: jnp.sum(m.apply(v, x) ** 2)))
        _force(step(variables))           # compile warmup
        trials = []
        for _ in range(iters):
            t0 = time.perf_counter()
            _force(step(variables))
            trials.append(time.perf_counter() - t0)
        return min(trials) * 1e3

    reference_ms = leg("")
    fused_ms = leg("pallas")
    print(json.dumps({
        "metric": "fedavg_resnet56_fused_block_step_ms",
        "value": round(fused_ms, 3),
        "unit": f"ms/step, BasicBlock(16) fwd+bwd batch {batch} at "
                f"32x32x16, fused pallas"
                f"{'-interpret' if jax.default_backend() != 'tpu' else ''}"
                f" vs flax ({jax.default_backend()})",
        "vs_baseline": None,
        "legs": {
            "reference_ms": round(reference_ms, 3),
            "fused_ms": round(fused_ms, 3),
            "speedup": round(reference_ms / fused_ms, 3),
        },
    }), flush=True)


def run():
    bench_flagship()
    for name, fn in (
            ("fedavg_resnet56_fused_block_step_ms", bench_fused_block),
            ("fedavg_resnet18_engine_mfu", bench_engine_mfu_resnet18),
            ("fedavg_robust_krum_rounds_per_hour", bench_robust_krum),
            ("fedavg_robust_rfa_rounds_per_hour", bench_robust_rfa),
            ("fedavg_robust_rfa_weak_scaling_efficiency",
             bench_robust_rfa_weak_scaling),
            ("fedavg_contribution_loo_rounds_per_hour",
             bench_contribution_fused),
            ("hierarchical_femnist_mobilenet_rounds_per_hour",
             bench_hierarchical_femnist),
            ("fedavg_digits_time_to_90pct_s", bench_time_to_acc),
            ("fedavg_cross_silo_wire_bytes_per_round",
             bench_cross_silo_wire),
            ("fedavg_chaos_dropout_rounds_to_target", bench_chaos_dropout),
            ("fedavg_async_chaos_updates_per_hour", bench_async_chaos),
            ("fedavg_async_robust_updates_per_hour", bench_async_robust),
            ("fedavg_chaos_selection_rounds_to_target",
             bench_chaos_selection),
            ("cross_device_cohort_assembly_ms", bench_cohort_assembly),
            ("cross_device_multitenant_rounds_per_hour",
             bench_cross_device_multitenant),
            ("fedopt_shakespeare_rnn_rounds_per_hour",
             bench_shakespeare_fedopt),
            ("fedllm_lora_federated_round_s", bench_federated_lora),
            ("llm_serving_tokens_per_s", bench_llm_serving),
            ("llm_serving_adapter_churn_tokens_per_s",
             bench_llm_serving_adapter_churn),
            ("llm_serving_ttft", bench_llm_serving_ttft),
            ("llm_serving_chaos_goodput", bench_llm_serving_chaos),
            ("llm_serving_fleet_tokens_per_s", bench_llm_serving_fleet),
            ("llm_train_step_mfu", bench_llm_mfu),
            ("llm_long_context_train_tokens_per_s", bench_long_context),
            ("llm_long_context_train_tokens_per_s_seq8192",
             lambda: bench_long_context(seq_len=8192, steps=4,
                                        metric_suffix="_seq8192"))):
        try:  # a broken line must never mask the others
            fn()
        except Exception as e:
            print(json.dumps({"metric": name,
                              "error": f"{type(e).__name__}: {e}"}),
                  flush=True)


if __name__ == "__main__":
    run()
