"""Benchmark: FL round throughput of the jitted mesh engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no benchmark numbers (BASELINE.md), so the baseline
here is the reference's own *architecture* on identical hardware: the
single-process golden loop (per-client dispatch + host-side aggregation —
the shape of ``sp/fedavg/fedavg_api.py``) vs our fused whole-round SPMD
program. ``vs_baseline`` = mesh rounds/hour ÷ golden-loop rounds/hour.

Workload: FedAvg ResNet-20/CIFAR-10-shaped, 8 clients/round, 1 local epoch —
a scaled-down sibling of the BASELINE.md north-star (ResNet-56, 128 clients).
"""

from __future__ import annotations

import json
import time


def run():
    import jax
    import jax.numpy as jnp

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.sp.simulator import SPSimulator
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    args = Arguments(
        dataset="cifar10", model="resnet20",
        client_num_in_total=8, client_num_per_round=8,
        comm_round=1, epochs=1, batch_size=32, learning_rate=0.1,
        frequency_of_the_test=10_000, random_seed=0,
    )
    fed, output_dim = load(args)
    bundle = create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate), epochs=1)

    def force(params):
        # NB: block_until_ready does not reliably synchronize on the tunneled
        # TPU platform — force a scalar readback to time actual execution.
        return float(jax.tree_util.tree_leaves(params)[0].sum())

    def time_rounds(run_one, params_of, warmup=1, iters=3):
        for _ in range(warmup):
            run_one()
        force(params_of())
        t0 = time.perf_counter()
        for _ in range(iters):
            run_one()
            force(params_of())
        return (time.perf_counter() - t0) / iters

    # --- mesh engine (ours): whole round = one jitted SPMD program
    opt = create_optimizer(args, spec)
    tpu_sim = TPUSimulator(args, fed, bundle, opt, spec)
    r = [0]

    def tpu_round():
        tpu_sim.run_round(r[0], hyper)
        r[0] += 1

    tpu_round_s = time_rounds(tpu_round, lambda: tpu_sim.params)

    # --- baseline: golden per-client loop (reference SP architecture)
    sp_sim = SPSimulator(args, fed, bundle, create_optimizer(args, spec), spec)

    def sp_round():
        sp_sim.run(comm_round=1)

    sp_round_s = time_rounds(sp_round, lambda: sp_sim.params)

    rounds_per_hour = 3600.0 / tpu_round_s
    vs_baseline = sp_round_s / tpu_round_s
    print(json.dumps({
        "metric": "fedavg_resnet20_cifar10_rounds_per_hour",
        "value": round(rounds_per_hour, 1),
        "unit": "rounds/hour (8 clients/round, 1 local epoch)",
        "vs_baseline": round(vs_baseline, 3),
    }))


if __name__ == "__main__":
    run()
