"""Typed flat configuration.

Reproduces the load-bearing semantics of the reference's config system
(``python/fedml/arguments.py:36,75,187,193``): a YAML file with sections
(``common_args``, ``data_args``, ``model_args``, ``train_args``, ...) is
flattened into ONE attribute namespace so every component reads ``args.X``.
Differences from the reference, by design:

* a dataclass-backed schema with defaults + type coercion instead of a
  free-form attribute bag (unknown keys are still kept, so user extensions
  and reference YAMLs work unchanged);
* per-silo override files (``data_silo_config``) are resolved here, mirroring
  ``__init__.py:188-212`` of the reference.
"""

from __future__ import annotations

import argparse
import os
from typing import Any, Dict, List, Optional

import yaml

from .constants import (
    FEDML_SIMULATION_BACKEND_ALIASES,
    FEDML_TRAINING_PLATFORM_SIMULATION,
)

# Schema of known fields: (default, type). Everything else is passed through
# untyped. Types are used for coercion when values arrive as strings (CLI).
_SCHEMA: Dict[str, Any] = {
    # common_args
    "training_type": FEDML_TRAINING_PLATFORM_SIMULATION,
    "random_seed": 0,
    "scenario": "horizontal",
    "config_version": "release",
    "run_id": "0",
    "using_mlops": False,
    # data_args
    "dataset": "synthetic_mnist",
    "data_cache_dir": "~/.cache/fedml_tpu/data",
    "partition_method": "hetero",
    "partition_alpha": 0.5,
    "allow_synthetic": False,    # opt-in gate for synthetic stand-ins
    # model_args
    "model": "lr",
    # train_args
    "federated_optimizer": "FedAvg",
    "client_id_list": None,
    "client_num_in_total": 8,
    "client_num_per_round": 8,
    "comm_round": 10,
    "epochs": 1,
    "batch_size": 32,
    "client_optimizer": "sgd",
    "learning_rate": 0.03,
    "weight_decay": 0.0,
    "momentum": 0.0,
    "server_optimizer": "sgd",
    "server_lr": 1.0,
    "server_momentum": 0.9,
    "fedprox_mu": 0.1,
    "feddyn_alpha": 0.01,
    # validation_args
    "frequency_of_the_test": 5,
    # device_args / tpu_args
    "worker_num": None,          # devices used; defaults to local device count
    "using_gpu": True,
    "device_type": "tpu",
    "mesh_shape": None,          # e.g. {"client": 8} or {"client": 4, "fsdp": 2}
    "clients_per_device": None,  # schedule width; derived if None
    "precision": "float32",      # or "bfloat16" for the compute path
    "rounds_per_dispatch": 8,    # fused-block length (rounds per dispatch)
    # opt-in persistent XLA compilation cache: repeat runs skip the fused-
    # program compile that dominates short-run wall time (time-to-accuracy
    # benches). Off (None) by default — identical behavior to before.
    "compile_cache_dir": None,
    # auto: defended rounds fuse train->attack->defense->CDP->server into
    # ONE dispatch whenever the sharded defense path applies; host forces
    # the 3-dispatch host-orchestrated pipeline; fused refuses configs
    # that cannot fuse instead of silently degrading
    "robust_fused": "auto",
    # auto: feature-sharded (no host materialization) defense whenever the
    # configured defense supports it; false/host forces the host kernels
    "sharded_defense": "auto",
    # perf knobs (ISSUE 16) — all off by default, off = bit-identical to
    # the pre-knob programs:
    # fused conv->GroupNorm->residual->ReLU Pallas kernel for the narrow
    # (<= 64 channel) ResNet stages; true/pallas = the VMEM-resident
    # kernel (interpret mode off-TPU), reference = same math via XLA.
    # A mode STRING (bool coercion would eat "reference"); bools work too
    "fused_conv_block": "",
    # fold the [S] client-slot axis into the conv batch axis (FedSGD-style
    # optimizers that evaluate shared params only); refuses configs that
    # need per-client updates (robust/DP/tracking selection)
    "client_slot_fold": False,
    # quantize the fused robust path's all_to_all re-layout rows across
    # the mesh: int8 (per-row scales, ~4x fewer re-layout wire bytes) or
    # bf16 (~2x); None keeps the dense f32 re-layout byte-identical
    "robust_relayout_quant": None,
    # donate params/server_state/client_states buffers to the round
    # programs (outputs replace them 1:1) — halves model-state HBM peak;
    # off-switch for debugging aliasing suspicions only
    "donate_buffers": True,
    # comm_args
    "backend": "tpu",
    "grpc_ipconfig_path": None,
    "mqtt_config_path": None,
    # wire-efficiency for cross-silo updates (utils/compression.py). Off by
    # default: the wire stays byte-identical to the dense float32 path.
    "comm_compression": None,            # topk|randk|qsgd|topk_qsgd|randk_qsgd
    "comm_compression_ratio": 0.1,       # sparsifier keep-ratio in (0, 1]
    "comm_quantize_levels": 127,         # QSGD levels (int8 wire, <= 127)
    "comm_compression_broadcast": "full",  # server->client: full|bf16|compress
    # unified wire pipeline (core/wire, ISSUE 19). ALL off by default:
    # every transport's wire stays byte-identical.
    "comm_compression_adaptive": False,  # stats-driven per-round keep-ratio
    "comm_compression_ratio_min": None,  # adaptive bounds (None -> ratio/4)
    "comm_compression_ratio_max": None,  # adaptive bounds (None -> ratio)
    "comm_compression_latency_budget_s": None,  # uplink s == full pressure
    "secagg_compress_bits": 0,           # 0=dense field; 4|8|16-bit lanes
    "secagg_compress_clip": 4.0,         # round-0 clip (auto-scaled after)
    "gossip_compression": None,          # decentralized neighbor deltas
    "device_wire_compression": None,     # cross-device uplink artifacts
    # chaos_args — deterministic fault injection (core/chaos). ALL off by
    # default: a default run injects nothing, the simulator programs and
    # the cross-silo wire stay byte/bit-identical.
    "chaos_seed": None,              # falls back to random_seed
    "chaos_dropout_prob": 0.0,       # per-(round, client) dropout
    "chaos_straggler_prob": 0.0,     # per-(round, client) straggler
    "chaos_straggler_work": 0.5,     # fraction of local work a straggler runs
    "chaos_link_loss_prob": 0.0,     # per-message loss at the send seam
    "chaos_link_dup_prob": 0.0,      # per-message duplication
    "chaos_link_delay_prob": 0.0,    # per-message delay probability
    "chaos_link_delay_s": 0.0,       # delay applied when it fires
    "chaos_crash_at_round": None,    # raise ChaosCrash after this round
    # fault TOLERANCE (on by default — it is the correct behavior; the
    # off-switch exists so the bench can demonstrate what dropout does to
    # an intolerant aggregator): dropped clients are renormalized out of
    # the weighted average instead of diluting it with zero updates
    "chaos_tolerance": True,
    # sample ceil(client_num_per_round * (1 + frac)) clients so that after
    # expected dropout the surviving cohort still hits the target size
    "chaos_over_sample": 0.0,
    # selection_args — adaptive participant selection & client reputation
    # (core/selection). Defaults are a strict no-op: uniform selection on
    # the legacy sampling stream produces bit-identical schedules.
    "client_selection": "uniform",   # uniform|power_of_choice|oort|reputation
    # legacy: reference-parity per-round stream (ignores random_seed, like
    # the reference's np.random.seed(round_idx) — but via a private
    # RandomState, no longer clobbering the process-global RNG);
    # seeded: default_rng((random_seed, round_idx)) — the fixed stream
    "sampling_stream": "legacy",
    # size the sampled cohort from the OBSERVED Beta-posterior dropout
    # rate (ceil(k / (1 - p))) instead of the static chaos_over_sample
    # factor; capped by selection_max_over_sample so the canonical
    # schedule width (and the compile-once invariant) never moves
    "selection_adaptive_oversample": False,
    "selection_max_over_sample": 1.0,
    "selection_loss_window": 8,      # last-K training losses per client
    "selection_ema_alpha": 0.2,      # latency / work-fraction EMA weight
    # reputation: a client's normalized inclusion posterior over defense
    # verdicts (its Beta-posterior keep-rate relative to the cohort mean,
    # in [0, 1]); clients below rep_threshold are benched as renormalized
    # in-program dropout, never benching past min_keep_frac of the cohort
    "selection_rep_threshold": 0.3,
    "selection_min_keep_frac": 0.5,
    "poc_d_factor": 2.0,             # power-of-choice candidate multiplier
    "oort_explore_frac": 0.1,        # cohort fraction exploring new clients
    "oort_alpha": 2.0,               # system-utility latency exponent
    "oort_pref_latency_s": 0.0,      # 0 = observed median latency
    # fleet_args — durable multi-tenant fleet plane (core/fleet; ISSUE
    # 18). ALL off by default: no registry file is opened and the
    # single-tenant cohort path stays bit-identical.
    # sqlite registry path (None = in-memory only, the amnesiac PR 15
    # behavior); servers sharing one path are tenants of one fleet
    "fleet_registry": None,
    # this server's task name in the registry (None = "train" for the
    # FL server, "fa" for the analytics server)
    "fleet_task_id": None,
    # per-device fairness: at most this many participations (any task)
    # in the trailing window (0 = uncapped); one-task-per-round is
    # always enforced by the registry's claims table
    "fleet_max_rounds_per_window": 0,
    "fleet_fairness_window_s": 3600.0,
    # pacer-driven cohort sizing (Oort: grow k when the cohort's
    # aggregate statistical utility saturates; off = k never moves)
    "pacer_adapt_cohort": False,
    "pacer_util_window": 4,          # rounds per utility comparison window
    "pacer_util_saturation": 0.05,   # relative improvement below = plateau
    "pacer_min_cohort_scale": 1.0,   # k multiplier bounds
    "pacer_max_cohort_scale": 4.0,
    # cross-silo: a timed-out round aggregates only if at least
    # ceil(frac * expected) silos reported; below quorum the server keeps
    # waiting (another timeout interval) instead of averaging a sliver
    "round_quorum_frac": 0.0,
    # cross-silo DATA-index assignment: legacy = the reference's
    # round-robin (rank i gets sampled index i mod k, bit-identical);
    # scored = the stats store ranks silos by availability/latency and
    # the first-sampled indices go to the most deliverable silos
    "silo_index_assignment": "legacy",
    # async_args — buffered-async rounds (core/async_rounds, FedBuff +
    # FedAsync staleness decay). Default `sync` keeps every path
    # bit-identical: the round barrier, FSM, and engine programs are
    # untouched until the knob flips.
    "round_mode": "sync",            # sync | async_buffered
    "async_buffer_k": 0,             # pour trigger; 0 = half the cohort
    "async_alpha": 0.6,              # FedAsync mixing rate for each pour
    "async_staleness_weighting": "polynomial",  # constant|polynomial|hinge
    "async_staleness_poly": 0.5,     # poly decay exponent / hinge slope
    "async_hinge_b": 4,              # hinge: free staleness up to b versions
    # staleness clamp before weighting (stale uploads are DOWN-WEIGHTED,
    # never dropped); 0 = adaptive from observed arrival-rate posteriors
    "async_staleness_cap": 16,
    # cross-silo: pour whatever is buffered (>= 1 update) after this many
    # seconds without reaching K; 0 falls back to round_timeout_s, then
    # to a 30 s default — the liveness valve is never OFF in async mode
    # (a decimated fleet must not stall the pour forever)
    "async_pour_timeout_s": 0.0,
    # simulated-arrival heterogeneity (async engine + SP toy durations)
    "async_duration_sigma": 0.6,
    # comm retry policy (exponential backoff + jitter at the transport
    # send seam; 0 attempts = fail fast like the pre-chaos transports).
    # deadline_s caps the TOTAL retry budget in wall seconds — without it
    # a long per-try timeout times max_attempts can stall an async pour
    # far past usefulness; 0 = attempt-count bound only (legacy)
    "comm_retry_max_attempts": 4,
    "comm_retry_base_s": 0.2,
    "comm_retry_max_s": 2.0,
    "comm_retry_deadline_s": 0.0,
    # serving_args — LLM serving (serving/llm_template + serving/batch).
    # Default `single` keeps the original one-request-at-a-time compiled
    # full-forward loop; `batch` turns on continuous batching (paged KV
    # cache, fixed [serving_slots] slot matrix, per-request multi-LoRA
    # adapter selection from llm_adapter_dir).
    "llm_serving_mode": "single",      # single | batch
    "serving_slots": 8,                # in-flight decode slots [S]
    "serving_kv_block_size": 16,       # KV-cache block (must divide
                                       # llm_max_seq_len)
    "serving_prefill_chunk": 32,       # chunked-prefill program width
    "serving_max_adapters": 64,        # adapter-bank capacity [A]
    "serving_deadline_s": 0.0,         # per-request decode deadline;
                                       # past it the request is evicted
                                       # with finish_reason: length (0=off)
    "serving_request_timeout_s": 120.0,
    # serving-plane observability: the engine's stall/NaN watchdog (0 =
    # off) and the black-box flight recorder (ring of the last N
    # request-lifecycle + engine-step records, dumped as JSONL on crash,
    # SIGTERM, or watchdog trip; dir None = next to the run logs)
    "serving_watchdog_s": 30.0,
    "serving_flight_records": 256,
    "serving_flight_dir": None,
    # serving fault tolerance (crash-only recovery; ISSUE 11). A watchdog
    # trip (decode stall / NaN logits) triggers a controlled reset:
    # in-flight requests are snapshotted, the slot matrix + paged KV pool
    # rebuilt (same geometry — zero recompiles), and the snapshots
    # requeued for deterministic recompute-from-prompt. The reset budget
    # is serving_max_resets per serving_reset_window_s; exhausted, the
    # engine stays unhealthy (/healthz 503) and dumps its flight ring.
    "serving_max_resets": 3,
    "serving_reset_window_s": 300.0,
    # per-request requeue cap: past it the request resolves with
    # finish_reason "preempted" (partial output) instead of looping
    "serving_max_requeues": 2,
    # graceful degradation: preempt-and-requeue the YOUNGEST slot when
    # the queue head has starved this long without admission (0 = off)
    "serving_preempt_after_s": 0.0,
    # load shedding: submit fails fast with 503 + Retry-After once the
    # queue is this deep (0 = off — the pre-ISSUE-11 unbounded queue)
    "serving_shed_queue_depth": 0,
    # chaos_serving_* — seeded serving-plane fault injection (core/chaos
    # serving kinds; all OFF by default). *_prob knobs draw per-index
    # from the (chaos_seed, kind, index) stream; *_at_step/_at_request
    # are the deterministic single-shot variants tests pin.
    "chaos_serving_stall_prob": 0.0,     # per-decode-step stall draw
    "chaos_serving_stall_s": 0.0,        # injected stall length
    "chaos_serving_stall_at_step": None,  # stall exactly at this step
    "chaos_serving_nan_prob": 0.0,       # per-step NaN-logit poison draw
    "chaos_serving_nan_at_step": None,   # poison exactly at this step
    "chaos_serving_conn_drop_prob": 0.0,  # gateway->replica connect drop
    "chaos_serving_crash_at_request": None,  # replica dies on request N
    # serving perf levers (ISSUE 13) — ALL off by default: wire bytes and
    # decode tokens stay bit-identical to the pre-ISSUE-13 path.
    # shared-prefix KV cache: refcounted copy-on-write aliasing of
    # fully-matched read-only prompt blocks — a system-prompt-heavy chat
    # workload prefills only its novel suffix (aliasing changes where KV
    # lives, never its values: greedy decode stays bit-identical)
    "llm_prefix_cache": False,
    # piggybacked prefill: batch an admission wave's chunks through one
    # [B, C] program (B = this width; 0/1 = serial) so K admits cost
    # ~one pass over the longest novel suffix instead of K serial passes
    "llm_prefill_batch": 0,
    # SSE token streaming on /v1/chat/completions for requests carrying
    # "stream": true (off = the flag is ignored, byte-identical wire)
    "llm_stream": False,
    # adapter hot-swap: poll llm_adapter_dir every this-many seconds and
    # swap changed/new exports live (0 = off); in-flight requests keep
    # the adapter version they started with
    "llm_adapter_watch_s": 0.0,
    "llm_adapter_dir": None,           # adapter-bank manifest dir to serve
    # fleet-serving levers (ISSUE 17) — ALL off by default: wire bytes
    # and decode tokens stay bit-identical to the pre-ISSUE-17 path.
    # generated-token suffix caching (RadixAttention-style): index full
    # decode blocks into the prefix index at slot release under the same
    # refcount/COW discipline as prompt blocks, so a requeued or
    # follow-up request (prior prompt + generated reply + new user turn)
    # aliases the whole conversation prefix instead of re-prefilling
    # tokens the engine itself produced. Implies the prefix index.
    "llm_suffix_cache": False,
    # cache-aware gateway routing: hash each request's leading prompt
    # bytes (~ leading token blocks under the byte tokenizer) into a
    # routing digest and stick same-digest traffic to the replica whose
    # prefix cache is warm, with KV-headroom-aware spill to round-robin
    # when the warm replica is saturated
    "serving_cache_aware_routing": False,
    # serving_slo_* — SLO-driven autoscaling (SLOPolicy): close the loop
    # from the serving SLO instruments (TTFT/ITL percentiles, queue
    # depth, KV headroom scraped from each replica's /healthz) to
    # ReplicaSet scaling. Targets of 0 disable that signal; with both
    # latency targets 0 the policy never scales on latency.
    "serving_slo_ttft_p99_s": 0.0,     # scale up while p99 TTFT exceeds
    "serving_slo_itl_p99_s": 0.0,      # scale up while p99 ITL exceeds
    "serving_slo_queue_per_replica": 4.0,  # queue-depth bound per replica
    "serving_slo_kv_headroom_min": 1,  # min KV admission headroom (reqs)
    "serving_slo_cooldown_s": 5.0,     # min seconds between scale moves
    # drain-before-kill on scale-down: give the victim replica this long
    # to finish in-flight streams before stop (0 = legacy immediate stop)
    "serving_drain_grace_s": 0.0,
    # federated-LoRA adapter export: after run_federated_llm, write the
    # global + per-silo personalized adapters as named artifacts the
    # serving adapter bank loads (None = off)
    "llm_adapter_export_dir": None,
    "llm_adapter_personalize_steps": 4,
    # tracking_args
    "enable_wandb": False,
    "enable_tracking": True,     # master switch for the JSONL sink
    "log_server_url": None,      # remote log shipper endpoint (log_daemon)
    "sys_perf_profiling": False,  # host/device sampler thread (mlops)
    # observability (core/obs): tracing + metrics are default-on-cheap
    # (spans are dicts + one JSONL line; metric hooks are dict lookups);
    # device profiling is OPT-IN because it blocks on dispatch results,
    # defeating the engines' host/device overlap
    "obs_tracing": True,          # spans + traceparent wire propagation
    "obs_metrics": True,          # typed counter/gauge/histogram registry
    "obs_metrics_flush_rounds": 10,  # metrics_snapshot JSONL cadence
    # wall-clock metrics_snapshot cadence (seconds; 0 = off) for
    # workloads that never cross a round boundary — serving, the
    # cross-device handshake, agents; skips when nothing changed
    "obs_metrics_flush_s": 60.0,
    "obs_profile_device": False,  # host/device split + per-round MFU
    # compute-plane roofline capture (core/obs/roofline): AOT-compiles
    # each dispatched program once per abstract-shape signature and
    # emits the per-op roofline + collective-traffic record — OPT-IN
    # because the extra backend compile would trip the compile-once
    # counters (recompile FORENSICS is always on and compile-free)
    "obs_roofline": False,
    "log_file_dir": "~/.cache/fedml_tpu/logs",
    "save_model_path": None,     # persist final params (serving artifact)
    "checkpoint_dir": None,
    "checkpoint_every_rounds": 0,  # 0 = off
    # security/privacy (consulted by hook chain; parity with L4 singletons)
    "enable_attack": False,
    "attack_type": None,
    "enable_defense": False,
    "defense_type": None,
    "rfa_iters": 8,              # Weiszfeld iterations for the RFA defense
    # rfa_tol > 0: convergence-based early exit — rfa_iters becomes a
    # budget, the loop stops once the estimate moves < tol. 0 (default)
    # keeps the exact fixed trip count, bit-parity-tested host vs sharded
    "rfa_tol": 0.0,
    "enable_dp": False,
    "dp_mechanism": "gaussian",
    "enable_dp_ldp": False,
    "enable_secure_agg": False,
    "enable_fhe": False,
}


class Arguments:
    """Flat config namespace. Known keys get defaults from ``_SCHEMA``;
    unknown keys from the YAML are attached as-is (reference
    ``set_attr_from_config`` ``arguments.py:187-190``)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None, **overrides: Any):
        for key, default in _SCHEMA.items():
            setattr(self, key, default)
        merged: Dict[str, Any] = {}
        if config:
            merged.update(_flatten_sections(config))
        merged.update(overrides)
        for key, value in merged.items():
            setattr(self, key, _coerce(key, value))
        self._finalize()

    def _finalize(self) -> None:
        backend = str(getattr(self, "backend", "tpu")).lower()
        self.backend = FEDML_SIMULATION_BACKEND_ALIASES.get(backend, backend)
        if self.client_num_per_round > self.client_num_in_total:
            self.client_num_per_round = self.client_num_in_total
        for key in ("data_cache_dir", "log_file_dir", "checkpoint_dir"):
            val = getattr(self, key, None)
            if isinstance(val, str):
                setattr(self, key, os.path.expanduser(val))

    # dict-style helpers used across the framework
    def get(self, key: str, default: Any = None) -> Any:
        return getattr(self, key, default)

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items() if not k.startswith("_")}

    def __repr__(self) -> str:  # keep logs readable
        keys = sorted(self.to_dict())
        return "Arguments(" + ", ".join(f"{k}={getattr(self, k)!r}" for k in keys) + ")"


_SECTION_SUFFIX = "_args"


def _flatten_sections(config: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten ``{section_args: {k: v}}`` into ``{k: v}``; non-section keys
    pass through. Later sections win on duplicate keys, matching the
    reference's setattr order."""
    flat: Dict[str, Any] = {}
    for key, value in config.items():
        if key.endswith(_SECTION_SUFFIX) and isinstance(value, dict):
            flat.update(value)
        else:
            flat[key] = value
    return flat


def _coerce(key: str, value: Any) -> Any:
    default = _SCHEMA.get(key)
    if default is None or value is None:
        return value
    ty = type(default)
    if isinstance(value, ty):
        return value
    try:
        if ty is bool and isinstance(value, str):
            return value.strip().lower() in ("1", "true", "yes", "on")
        return ty(value)
    except (TypeError, ValueError):
        return value


def load_arguments(
    config_path: Optional[str] = None,
    rank: int = 0,
    role: Optional[str] = None,
    **overrides: Any,
) -> Arguments:
    """Load YAML config (if given) → flat ``Arguments``.

    Mirrors ``load_arguments`` (reference ``arguments.py:193``) including the
    per-silo override files: if the YAML names ``data_silo_config`` (a list of
    YAML paths) and ``rank >= 1``, the rank-specific file is merged on top
    (reference ``__init__.py:188-212``).
    """
    config: Dict[str, Any] = {}
    if config_path:
        with open(config_path, "r") as f:
            config = yaml.safe_load(f) or {}
    args = Arguments(config, **overrides)
    args.rank = rank
    if role is not None:
        args.role = role
    silo_configs: Optional[List[str]] = getattr(args, "data_silo_config", None)
    if silo_configs and rank >= 1 and rank - 1 < len(silo_configs):
        base = os.path.dirname(os.path.abspath(config_path)) if config_path else "."
        silo_path = os.path.join(base, silo_configs[rank - 1])
        with open(silo_path, "r") as f:
            silo_cfg = yaml.safe_load(f) or {}
        for key, value in _flatten_sections(silo_cfg).items():
            setattr(args, key, _coerce(key, value))
        args._finalize()
    return args


def add_args() -> argparse.Namespace:
    """Bootstrap CLI flags (reference ``arguments.py:36-72``)."""
    parser = argparse.ArgumentParser(description="fedml_tpu")
    parser.add_argument("--cf", "--config_file", dest="yaml_config_file",
                        type=str, default=None, help="yaml configuration file")
    parser.add_argument("--rank", type=int, default=0)
    parser.add_argument("--role", type=str, default="client")
    parser.add_argument("--run_id", type=str, default="0")
    parser.add_argument("--run_device_id", type=str, default="0")
    known, _ = parser.parse_known_args()
    return known
