"""CLI package: ``python -m fedml_tpu.cli <command>`` (or console-script
``fedml_tpu`` when installed).

Parity target: the reference's click command group ``cli/cli.py:11-77``
(``fedml login/launch/run/build/logs/env/version/diagnosis/...``). Commands
here wrap :mod:`fedml_tpu.api` — the same local-first platform the Python
API exposes — plus ``train`` (run a training config in-process) and
``serve`` (serve a saved model artifact).
"""

from .main import cli  # noqa: F401
