"""The click command group (reference ``cli/cli.py:11-77``)."""

from __future__ import annotations

import json
import sys

import click


@click.group()
@click.help_option("--help", "-h")
def cli():
    """fedml_tpu — TPU-native federated & distributed ML."""


@cli.command("login", help="Record a local platform profile (local-first; "
                           "no network)")
@click.argument("api_key", required=False)
def login(api_key):
    from .. import api
    rc = api.fedml_login(api_key)
    click.echo("login OK" if rc == 0 else f"login failed ({rc})")
    sys.exit(rc)


def _parse_hostport(value, flag):
    host, _, port = value.partition(":")
    if not host or not port or not port.isdigit():
        click.echo(f"{flag} must be HOST:PORT, got {value!r}", err=True)
        sys.exit(2)
    return host, int(port)


@cli.command("launch", help="Launch a job yaml (task job or training "
                            "config) as a local run, or dispatch it to a "
                            "remote agent over the broker with --remote")
@click.argument("yaml_file")
@click.option("--blocking", is_flag=True, default=False,
              help="wait for the job instead of detaching")
@click.option("--remote", default=None, metavar="HOST:PORT",
              help="dispatch via the pub/sub broker to an agent daemon")
@click.option("--device-id", type=int, default=None,
              help="target agent device id (required with --remote)")
def launch(yaml_file, blocking, remote, device_id):
    from .. import api
    if remote:
        if device_id is None:
            click.echo("--remote requires --device-id", err=True)
            sys.exit(2)
        from ..agents import MasterAgent, launch_job_remote
        host, port = _parse_hostport(remote, "--remote")
        master = MasterAgent(host, port)
        master.start()
        try:
            info = launch_job_remote(yaml_file, device_id, master)
        finally:
            master.stop()
        click.echo(f"{info.get('run_id', '?')} {info['status']}")
        sys.exit(0 if info["status"] == "FINISHED" else 1)
    res = api.launch_job(yaml_file, detach=not blocking)
    if res.result_code != 0:
        click.echo(f"launch failed: {res.result_message}", err=True)
        sys.exit(1)
    click.echo(res.run_id)


@cli.command("agent", help="Run the compute-agent daemon: binds to the "
                           "broker, executes start-train commands, streams "
                           "status back (reference slave agent)")
@click.option("--broker", required=True, metavar="HOST:PORT")
@click.option("--device-id", type=int, required=True)
@click.option("--insecure-open", is_flag=True, default=False,
              help="accept UNAUTHENTICATED job dispatch (no bind token); "
                   "without this flag the daemon refuses to start unless "
                   "FEDML_TPU_AGENT_SECRET is set")
def agent(broker, device_id, insecure_open):
    import signal
    import threading
    from ..agents import SlaveAgent
    host, port = _parse_hostport(broker, "--broker")
    try:
        daemon = SlaveAgent(device_id, host, port,
                            insecure_open=insecure_open)
    except RuntimeError as e:
        click.echo(str(e), err=True)
        sys.exit(2)
    daemon.start()
    # banner reflects the EFFECTIVE mode: with a secret in the env the
    # daemon authenticates even if --insecure-open was passed
    click.echo(f"agent {device_id} bound to {broker}"
               + (" [INSECURE-OPEN]" if daemon._secret is None else ""))
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    daemon.stop()


@cli.command("broker", help="Run a standalone pub/sub broker (the MQTT "
                            "analogue agents and launch --remote bind to)")
@click.option("--port", type=int, default=0, help="0 = pick a free port")
@click.option("--insecure-open", is_flag=True, default=False,
              help="skip connection authentication; without this flag a "
                   "secret is taken from FEDML_TPU_BROKER_SECRET or "
                   "GENERATED and printed once at startup")
def broker_cmd(port, insecure_open):
    import secrets as _secrets
    import signal
    import threading
    from ..core.distributed.communication.pubsub import (PubSubBroker,
                                                         broker_secret)
    import os as _os
    if insecure_open:
        # PubSubBroker(secret=None) falls back to the env secret, which
        # would silently re-arm auth under an "[INSECURE-OPEN]" banner —
        # drop it from this process so the flag means what it says
        _os.environ.pop("FEDML_TPU_BROKER_SECRET", None)
        secret = None
    else:
        secret = broker_secret()
        if secret is None:
            token = _secrets.token_hex(16)
            secret = token.encode()
            click.echo("no FEDML_TPU_BROKER_SECRET configured — generated "
                       f"one for this broker:\n  {token}\nexport it as "
                       "FEDML_TPU_BROKER_SECRET on every peer.")
    b = PubSubBroker(port=port, secret=secret)
    click.echo(f"broker listening on :{b.port}"
               + (" [INSECURE-OPEN]" if b.secret is None else ""))
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    b.stop()


@cli.command("monitor", help="Run the job monitor daemon: detects runs "
                             "whose process died without an exit record, "
                             "releases their resource allocations, and "
                             "restarts jobs that opted in (restart: true)")
@click.option("--interval", type=float, default=2.0,
              help="seconds between registry scans")
@click.option("--max-restarts", type=int, default=3,
              help="restart cap per job lineage")
def monitor_cmd(interval, max_restarts):
    import signal
    import threading
    from ..api.scheduler import JobMonitor
    mon = JobMonitor(interval_s=interval, max_restarts=max_restarts).start()
    click.echo(f"job monitor running (interval {interval}s, "
               f"max_restarts {max_restarts})")
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    mon.stop()


@cli.group("device", help="Device-binding account registry (enroll, "
                          "list, revoke)")
def device_group():
    pass


@device_group.command("bind")
@click.argument("api_key")
@click.option("--device-id", default=None, help="explicit device id")
def device_bind_cmd(api_key, device_id):
    """Enroll a device under the API key's account; prints the device id
    and its ONE-TIME token (export as FEDML_TPU_DEVICE_TOKEN on the
    agent)."""
    from ..agents.accounts import AccountRegistry
    did, token = AccountRegistry().register_device(api_key,
                                                  device_id=device_id)
    click.echo(f"device_id: {did}")
    click.echo(f"device_token: {token}")
    click.echo("export FEDML_TPU_DEVICE_TOKEN on the agent host; the "
               "token is not stored and cannot be shown again.")


@device_group.command("list")
def device_list_cmd():
    from ..agents.accounts import AccountRegistry
    for d in AccountRegistry().devices():
        click.echo(f"{d['device_id']}  account={d['account_id']} "
                   f"revoked={d['revoked']} version={d['version'] or '-'}")


@device_group.command("revoke")
@click.argument("device_id")
def device_revoke_cmd(device_id):
    from ..agents.accounts import AccountRegistry
    ok = AccountRegistry().revoke_device(device_id)
    click.echo("revoked" if ok else "unknown device")
    sys.exit(0 if ok else 1)


@cli.group("run", help="Inspect and control runs")
def run_group():
    pass


@run_group.command("list")
def run_list_cmd():
    from .. import api
    for meta in api.run_list():
        click.echo(f"{meta['run_id']}  {meta.get('status'):<9} "
                   f"{meta.get('kind', '?'):<6} {meta.get('yaml', '')}")


@run_group.command("status")
@click.argument("run_id")
def run_status_cmd(run_id):
    from .. import api
    status = api.run_status(run_id)
    if status is None:
        click.echo("unknown run", err=True)
        sys.exit(1)
    click.echo(status)


@run_group.command("logs")
@click.argument("run_id")
@click.option("--tail", type=int, default=None, help="last N lines only")
def run_logs_cmd(run_id, tail):
    from .. import api
    for line in api.run_logs(run_id, tail=tail):
        click.echo(line)


@run_group.command("stop")
@click.argument("run_id")
def run_stop_cmd(run_id):
    from .. import api
    ok = api.run_stop(run_id)
    click.echo("stopped" if ok else "unknown run")
    sys.exit(0 if ok else 1)


@cli.command("build", help="Package a job workspace into a zip")
@click.argument("source_dir")
@click.option("--dest", default=None, help="output zip path")
@click.option("--config", default=None, help="config yaml to embed")
def build_cmd(source_dir, dest, config):
    from .. import api
    click.echo(api.build(source_dir, dest, config))


@cli.command("train", help="Run a training config yaml in-process")
@click.option("--cf", "yaml_file", required=True, help="config yaml")
@click.option("--rank", type=int, default=0)
@click.option("--role", default=None)
def train_cmd(yaml_file, rank, role):
    import fedml_tpu
    from ..arguments import load_arguments
    from ..constants import (FEDML_TRAINING_PLATFORM_CROSS_DEVICE,
                             FEDML_TRAINING_PLATFORM_CROSS_SILO,
                             FEDML_TRAINING_PLATFORM_CROSS_CLOUD)
    args = load_arguments(yaml_file, rank=rank,
                          **({"role": role} if role else {}))
    ttype = str(getattr(args, "training_type", "simulation"))
    if ttype in (FEDML_TRAINING_PLATFORM_CROSS_SILO,
                 FEDML_TRAINING_PLATFORM_CROSS_CLOUD):
        if str(getattr(args, "role", "client")) == "server":
            result = fedml_tpu.run_cross_silo_server(args)
        else:
            result = fedml_tpu.run_cross_silo_client(args)
    else:
        result = fedml_tpu.run_simulation(
            backend=str(getattr(args, "backend", "tpu")), args=args)
    if isinstance(result, dict):
        summary = {k: result[k] for k in
                   ("final_test_acc", "final_test_loss", "rounds",
                    "wall_time_s") if k in result}
        click.echo(json.dumps(summary))


@cli.command("serve", help="Serve a saved model artifact over HTTP")
@click.argument("params_path")
@click.option("--model", required=True, help="model name (e.g. resnet20)")
@click.option("--output-dim", type=int, required=True)
@click.option("--port", type=int, default=8890)
@click.option("--dataset", default="", help="dataset name (shapes some "
                                            "model variants)")
def serve_cmd(params_path, model, output_dim, port, dataset):
    from .. import api
    click.echo(f"serving {params_path} on :{port} (POST /predict)")
    api.model_serve(params_path, model, output_dim, port=port,
                    dataset=dataset, block=True)


@cli.command("env", help="Print environment info (versions, devices)")
def env_cmd():
    from ..utils.collect_env import collect_env
    click.echo(collect_env())


@cli.command("diagnosis", help="Check local comm backends end-to-end")
def diagnosis_cmd():
    from ..utils.diagnosis import run_diagnosis
    report = run_diagnosis()
    for name, (ok, detail) in report.items():
        click.echo(f"{name:<10} {'OK' if ok else 'FAIL'}  {detail}")
    sys.exit(0 if all(ok for ok, _ in report.values()) else 1)


@cli.command("version", help="Display fedml_tpu version")
def version_cmd():
    import fedml_tpu
    click.echo(f"fedml_tpu version: {fedml_tpu.__version__}")


if __name__ == "__main__":
    cli()
