from .main import cli

cli()
