"""Per-round client sampling and device schedules.

Parity targets: ``_client_sampling`` (reference ``sp/fedavg/fedavg_api.py:127``
— seeded ``np.random.choice`` per round, deterministic given round index) and
the NCCL simulator's ``client_schedule`` (``nccl/base_framework/Server.py:111``
— ``np.array_split`` of sampled clients over workers). Here the schedule is a
*tensor* ([n_devices, n_slots] local indices + active mask) consumed inside
the jitted round, replacing the broadcast ``client_schedule{i}`` params.

RNG streams: the reference (and this repo's seed state) sampled via
``np.random.seed(round_idx)`` + global ``np.random.choice`` — which clobbers
the PROCESS-GLOBAL NumPy RNG every round and ignores ``args.random_seed``
(every run samples identically). ``stream="legacy"`` reproduces that exact
sequence WITHOUT touching global state (a fresh ``RandomState(round_idx)``
is bit-compatible with the global-seed path) and stays the default so
existing schedules are bit-identical; ``stream="seeded"`` is the fixed
stream — a ``np.random.default_rng((random_seed, round_idx))`` Generator, so
different seeds sample different cohorts and the draw is still a pure
function of ``(seed, round)``.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

SAMPLING_STREAMS = ("legacy", "seeded")

# population size past which the seeded stream stops materializing and
# permuting a full [N] id array per draw (np.random's replace=False path)
# and samples ids via the generator directly. The two paths draw
# DIFFERENT (equally valid, equally deterministic) cohorts, so the
# switch is pinned to a fixed threshold — small-N draws stay
# bit-identical to every recorded schedule.
FAST_SAMPLE_MIN_N = 65536


def sample_ids_streaming(gen: np.random.Generator, n: int,
                         k: int) -> np.ndarray:
    """Uniform k-of-n id sample WITHOUT materializing the population.

    Floyd's algorithm: k draws from the generator, O(k) memory, exact
    uniform subset — then a k-element shuffle so the placement order is
    also uniform (callers treat sample order as schedule order). A pure
    function of the generator's state, so draws stay replayable."""
    k = min(int(k), int(n))
    if k <= 0:
        return np.empty(0, np.int64)
    chosen: set = set()
    order = []
    for j in range(n - k, n):
        t = int(gen.integers(0, j + 1))
        pick = t if t not in chosen else j
        chosen.add(pick)
        order.append(pick)
    out = np.asarray(order, np.int64)
    gen.shuffle(out)
    return out


def sampling_stream_from_args(args) -> str:
    """The ``sampling_stream`` knob, validated. ``legacy`` (default) keeps
    the reference's per-round stream bit-identical; ``seeded`` folds
    ``random_seed`` in."""
    stream = str(getattr(args, "sampling_stream", "legacy")
                 or "legacy").lower()
    if stream not in SAMPLING_STREAMS:
        raise ValueError(f"sampling_stream {stream!r} unknown; choose from "
                         f"{SAMPLING_STREAMS}")
    return stream


def client_sampling(round_idx: int, client_num_in_total: int,
                    client_num_per_round: int, random_seed: int = 0,
                    stream: str = "legacy") -> List[int]:
    if stream not in SAMPLING_STREAMS:  # same contract as the args knob
        raise ValueError(f"sampling_stream {stream!r} unknown; choose from "
                         f"{SAMPLING_STREAMS}")
    if client_num_in_total == client_num_per_round:
        return list(range(client_num_in_total))
    num = min(client_num_per_round, client_num_in_total)
    if stream == "legacy":
        # bit-compatible with the reference's np.random.seed(round_idx) +
        # global np.random.choice, but via a PRIVATE RandomState — the
        # process-global RNG is no longer clobbered every round
        rng = np.random.RandomState(round_idx)
        return list(rng.choice(range(client_num_in_total), num,
                               replace=False))
    gen = np.random.default_rng((int(random_seed), int(round_idx)))
    if client_num_in_total >= FAST_SAMPLE_MIN_N:
        # huge-population fast path: O(k) draws via the generator, no
        # [N] permutation (Generator.choice with replace=False builds
        # one) — still a pure function of (seed, round)
        return [int(c) for c in
                sample_ids_streaming(gen, client_num_in_total, num)]
    return [int(c) for c in gen.choice(client_num_in_total, num,
                                       replace=False)]


def build_schedule(
    sampled: List[int],
    n_devices: int,
    clients_per_device: int,
    max_slots: int = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Map sampled *global* client ids to per-device slots.

    Clients are owned by device ``cid // clients_per_device`` (their data
    shard lives there), so a sampled client trains where its data is — no
    cross-device data motion. Returns ``(local_idx[n_devices, S] int32,
    active[n_devices, S] float32)`` with padded slots masked out.

    The slot count S is bucketed to a power of two (capped at ``max_slots``)
    so the jitted round function sees at most log2 distinct schedule shapes
    across training instead of recompiling whenever the per-round max
    clients-on-one-device changes.
    """
    per_dev: List[List[int]] = [[] for _ in range(n_devices)]
    for cid in sampled:
        d = cid // clients_per_device
        per_dev[d].append(cid % clients_per_device)
    need = max(1, max(len(p) for p in per_dev))
    n_slots = 1
    while n_slots < need:
        n_slots *= 2
    if max_slots is not None:
        n_slots = min(max(n_slots, need), max(max_slots, need))
    idx = np.zeros((n_devices, n_slots), np.int32)
    active = np.zeros((n_devices, n_slots), np.float32)
    for d, locs in enumerate(per_dev):
        for s, li in enumerate(locs):
            idx[d, s] = li
            active[d, s] = 1.0
    return idx, active
