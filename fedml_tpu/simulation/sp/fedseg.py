"""FedSeg — federated semantic segmentation.

Parity target: reference ``simulation/mpi/fedseg/`` (DeepLab/U-Net-style
encoder-decoder trained per client with pixel-wise CE, FedAvg aggregation,
mIoU evaluation — ``fedseg/utils.py`` Evaluator). TPU-native design: the
standard FedAvg machinery is reused wholesale; segmentation is "just" a
TrainerSpec whose loss/eval are pixel-dense, plus a compact conv
encoder-decoder in the model zoo — the protocol needs nothing new, which
is exactly the point of the algframe split.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ...core.collectives import stack_trees, tree_weighted_average

logger = logging.getLogger(__name__)


class SegNet(nn.Module):
    """Compact encoder-decoder: 2x down, bottleneck, 2x up, per-pixel
    classifier."""
    num_classes: int
    width: int = 16

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = self.width
        h1 = nn.relu(nn.Conv(w, (3, 3))(x))
        d1 = nn.relu(nn.Conv(w * 2, (3, 3), strides=(2, 2))(h1))
        d2 = nn.relu(nn.Conv(w * 4, (3, 3), strides=(2, 2))(d1))
        b = nn.relu(nn.Conv(w * 4, (3, 3))(d2))
        u1 = nn.relu(nn.ConvTranspose(w * 2, (3, 3), strides=(2, 2))(b))
        u1 = jnp.concatenate([u1, d1], axis=-1)
        u2 = nn.relu(nn.ConvTranspose(w, (3, 3), strides=(2, 2))(u1))
        u2 = jnp.concatenate([u2, h1], axis=-1)
        return nn.Conv(self.num_classes, (1, 1))(u2)


def _pixel_ce(logits, y, mask):
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    m = mask[..., None, None] * jnp.ones_like(ce)
    return jnp.sum(ce * m) / jnp.maximum(jnp.sum(m), 1.0)


def miou(logits, y, mask, num_classes: int) -> jnp.ndarray:
    """Mean intersection-over-union (the reference Evaluator's headline
    metric)."""
    pred = jnp.argmax(logits, -1)
    m = (mask[..., None, None] * jnp.ones_like(y)).astype(bool)
    ious = []
    for c in range(num_classes):
        pc = (pred == c) & m
        yc = (y == c) & m
        inter = jnp.sum(pc & yc)
        union = jnp.sum(pc | yc)
        ious.append(jnp.where(union > 0, inter / jnp.maximum(union, 1), 1.0))
    return jnp.mean(jnp.asarray(ious))


class FedSegSimulator:
    def __init__(self, args, fed_dataset, bundle=None, optimizer=None,
                 spec=None):
        self.args = args
        self.fed = fed_dataset
        k = fed_dataset.num_classes
        self.net = SegNet(k, width=int(getattr(args, "seg_width", 16) or 16))
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        kinit, self.rng = jax.random.split(rng)
        sample = fed_dataset.train.x[0, 0]
        self.params = self.net.init(kinit, sample)["params"]
        self.lr = float(getattr(args, "learning_rate", 0.05))
        self._client_round = jax.jit(self._client_round_impl)
        self._eval_batch = jax.jit(self._eval_batch_impl)
        self.history: List[Dict[str, Any]] = []

    def _client_round_impl(self, params, cdata):
        opt = optax.sgd(self.lr, momentum=0.9)
        state = opt.init(params)

        def step(carry, inp):
            params, state = carry
            x, y, mask = inp
            loss, grads = jax.value_and_grad(
                lambda p: _pixel_ce(self.net.apply({"params": p}, x), y,
                                    mask))(params)
            up, state = opt.update(grads, state, params)
            return (optax.apply_updates(params, up), state), loss

        (params, _), losses = jax.lax.scan(
            step, (params, state), (cdata.x, cdata.y, cdata.mask))
        return params, jnp.mean(losses)

    def _eval_batch_impl(self, params, x, y, mask):
        logits = self.net.apply({"params": params}, x)
        return miou(logits, y, mask, self.fed.num_classes)

    def _evaluate(self) -> float:
        test = self.fed.test
        vals = [float(self._eval_batch(self.params, test["x"][i],
                                       test["y"][i], test["mask"][i]))
                for i in range(test["x"].shape[0])]
        return float(np.mean(vals))

    def run(self, comm_round=None) -> Dict[str, Any]:
        rounds = int(comm_round if comm_round is not None
                     else self.args.comm_round)
        n_per_round = int(getattr(self.args, "client_num_per_round",
                                  self.fed.num_clients))
        t0 = time.time()
        for r in range(rounds):
            rs = np.random.RandomState(200 + r)
            sampled = rs.choice(self.fed.num_clients,
                                min(n_per_round, self.fed.num_clients),
                                replace=False)
            ps, weights, losses = [], [], []
            for cid in sampled:
                cdata = jax.tree_util.tree_map(lambda a: a[cid],
                                               self.fed.train)
                p, loss = self._client_round(self.params, cdata)
                ps.append(p)
                weights.append(float(cdata.num_samples))
                losses.append(float(loss))
            w = jnp.asarray(weights, jnp.float32)
            self.params = tree_weighted_average(stack_trees(ps), w)
            score = self._evaluate()
            rec = {"round": r, "train_loss": float(np.mean(losses)),
                   "miou": score, "test_acc": score}
            logger.info("fedseg round %d: %s", r, rec)
            self.history.append(rec)
        return {"params": self.params, "history": self.history,
                "final_miou": self.history[-1]["miou"],
                "final_test_acc": self.history[-1]["miou"],
                "wall_time_s": time.time() - t0, "rounds": rounds}
