"""Split learning (SplitNN) — the model is partitioned at a cut layer:
clients own the bottom (feature extractor), the server owns the top (head).

Parity target: reference ``simulation/mpi/split_nn/`` (``SplitNNAPI.py:10``,
client/server managers exchanging activations forward and gradients
backward, clients trained round-robin). TPU-native design: one jitted step
computes the end-to-end loss but with params held as two separate trees
(client_k's bottom, shared top), so the privacy boundary of the protocol —
only activations/grads cross it — is structurally explicit, and per-party
gradients fall out of one backward pass instead of a hand-rolled
send-activation/recv-grad exchange.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

logger = logging.getLogger(__name__)


class _Bottom(nn.Module):
    hidden: int

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        return nn.relu(nn.Dense(self.hidden)(x))


class _Top(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, h):
        return nn.Dense(self.num_classes)(nn.relu(nn.Dense(64)(h)))


class SplitNNSimulator:
    """Round-robin split training: each round, every client takes its local
    epochs against the shared server head."""

    def __init__(self, args, fed_dataset, bundle, optimizer=None, spec=None):
        self.args = args
        self.fed = fed_dataset
        hidden = int(getattr(args, "splitnn_hidden", 128) or 128)
        self.bottom = _Bottom(hidden)
        self.top = _Top(fed_dataset.num_classes)
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        kb, kt, self.rng = jax.random.split(rng, 3)
        sample = fed_dataset.train.x[0, 0]
        h0 = self.bottom.init(kb, sample)
        self.client_bottoms: List[Any] = [h0 for _ in
                                          range(fed_dataset.num_clients)]
        probe = self.bottom.apply(h0, sample)
        self.top_params = self.top.init(kt, probe)
        self.lr = float(args.learning_rate)
        self._step = jax.jit(self._step_impl)
        self._eval = jax.jit(self._eval_impl)
        self.history: List[Dict[str, Any]] = []

    def _loss(self, bottom_params, top_params, batch):
        h = self.bottom.apply(bottom_params, batch["x"])  # activation crossing
        logits = self.top.apply(top_params, h)
        labels = batch["y"].astype(jnp.int32)
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        mask = batch["mask"].astype(per_ex.dtype)
        loss = jnp.sum(per_ex * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        correct = jnp.sum((jnp.argmax(logits, -1) == labels) * mask)
        return loss, (correct, jnp.sum(mask))

    def _step_impl(self, bottom_params, top_params, cdata):
        def epoch_body(carry, batch):
            bp, tp = carry
            (loss, aux), grads = jax.value_and_grad(
                self._loss, argnums=(0, 1), has_aux=True)(bp, tp, batch)
            gb, gt = grads
            is_real = jnp.sum(batch["mask"]) > 0
            upd = lambda p, g: jax.tree_util.tree_map(
                lambda w, gg: jnp.where(is_real, w - self.lr * gg, w), p, g)
            return (upd(bp, gb), upd(tp, gt)), aux

        (bp, tp), _ = jax.lax.scan(
            epoch_body, (bottom_params, top_params),
            {"x": cdata.x, "y": cdata.y, "mask": cdata.mask})
        return bp, tp

    def _eval_impl(self, bottom_params, top_params, x, y, mask):
        def body(carry, batch):
            _, (correct, count) = self._loss(bottom_params, top_params, batch)
            return carry, {"correct": correct, "count": count}

        _, stats = jax.lax.scan(body, None, {"x": x, "y": y, "mask": mask})
        return {k: jnp.sum(v) for k, v in stats.items()}

    def run(self, comm_round: Optional[int] = None) -> Dict[str, Any]:
        args = self.args
        rounds = comm_round if comm_round is not None else int(args.comm_round)
        t0 = time.time()
        for round_idx in range(rounds):
            for cid in range(self.fed.num_clients):
                cdata = jax.tree_util.tree_map(lambda a: a[cid],
                                               self.fed.train)
                for _ in range(int(args.epochs)):
                    self.client_bottoms[cid], self.top_params = self._step(
                        self.client_bottoms[cid], self.top_params, cdata)
            rec: Dict[str, Any] = {"round": round_idx}
            freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
            if round_idx % freq == 0 or round_idx == rounds - 1:
                # evaluate with client 0's bottom (reference evaluates the
                # last-trained pair; any single pair is a valid split model)
                stats = self._eval(self.client_bottoms[0], self.top_params,
                                   self.fed.test["x"], self.fed.test["y"],
                                   self.fed.test["mask"])
                n = max(float(stats["count"]), 1.0)
                rec["test_acc"] = float(stats["correct"]) / n
                logger.info("splitnn round %d: acc=%.4f", round_idx,
                            rec["test_acc"])
            self.history.append(rec)
        last_eval = next(r for r in reversed(self.history) if "test_acc" in r)
        return {"params": {"bottom": self.client_bottoms[0],
                           "top": self.top_params},
                "history": self.history, "wall_time_s": time.time() - t0,
                "final_test_acc": last_eval["test_acc"], "rounds": rounds}
