"""Hierarchical FL — two-level (edge -> cloud) aggregation.

Parity target: reference ``simulation/sp/hierarchical_fl/`` (``trainer.py:10``
global rounds over groups, ``group.py:7,43`` per-group FedAvg sub-rounds):
clients are partitioned into groups; each global round runs
``group_comm_round`` local FedAvg rounds *within* each group, then averages
the group models — the pattern of cross-silo hierarchical where a silo is a
group. The TPU mapping (SURVEY §2.8) is a two-level psum: ``client`` axis
then ``group`` axis; this engine-agnostic implementation reuses the jitted
per-client local step and keeps both aggregations as weighted tree averages.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.algframe.types import TrainHyper
from ...core.algframe.local_training import evaluate
from ...core.collectives import tree_weighted_average
from ..sampling import client_sampling, sampling_stream_from_args

logger = logging.getLogger(__name__)


# Module-level jitted helpers (NOT methods with a static self: jit's cache
# would strongly retain every simulator instance — dataset and all — for
# process lifetime, and share no compilations between instances).
@jax.jit
def _apply_updates(params, updates, weights):
    """Stack + weighted-average + apply as ONE compiled program: done
    eagerly this is 3 device ops per leaf, and on the tunneled TPU
    platform each first-seen eager op costs a remote compile — a deep
    model (MobileNet: ~150 leaves) turned the first round into minutes."""
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *updates)
    agg = tree_weighted_average(stacked, jnp.stack(weights))
    return (jax.tree_util.tree_map(jnp.add, params, agg),
            jnp.sum(jnp.stack(weights)))


@jax.jit
def _average_groups(group_params, group_weights):
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                     *group_params)
    return tree_weighted_average(stacked, group_weights)


class HierarchicalSimulator:
    """``group_num`` edge aggregators, ``group_comm_round`` edge rounds per
    global round."""

    def __init__(self, args, fed_dataset, bundle, optimizer, spec):
        self.args = args
        self.fed = fed_dataset
        self.bundle = bundle
        self.opt = optimizer
        self.spec = spec
        self.group_num = int(getattr(args, "group_num", 2) or 2)
        self.group_comm_round = int(getattr(args, "group_comm_round", 1) or 1)
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        init_rng, self.rng = jax.random.split(self.rng)
        self.params = bundle.init(init_rng, fed_dataset.train.x[0, 0])
        self._local_train = jax.jit(self.opt.local_train)
        self._evaluate = jax.jit(lambda p, x, y, m: evaluate(spec, p, x, y, m))
        # static partition of clients into groups (reference partitions by
        # index; group g owns clients g, g+G, g+2G, ...)
        self.groups: List[List[int]] = [
            [c for c in range(fed_dataset.num_clients)
             if c % self.group_num == g]
            for g in range(self.group_num)]
        self.history: List[Dict[str, Any]] = []

    def _train_clients(self, params, client_ids, round_key, hyper):
        updates, weights = [], []
        for cid in client_ids:
            key = jax.random.fold_in(round_key, cid)
            out = self._local_train(params, {}, {},  # stateless optimizers
                                    jax.tree_util.tree_map(
                                        lambda a: a[cid], self.fed.train),
                                    key, hyper)
            updates.append(out.update)
            weights.append(out.weight)
        new_params, total_w = _apply_updates(params, updates, weights)
        return new_params, float(total_w)

    def run(self, comm_round: Optional[int] = None) -> Dict[str, Any]:
        args = self.args
        rounds = comm_round if comm_round is not None else int(args.comm_round)
        hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                           epochs=int(args.epochs))
        per_round = int(args.client_num_per_round)
        t0 = time.time()
        for round_idx in range(rounds):
            sampled = set(client_sampling(
                round_idx, self.fed.num_clients, per_round,
                random_seed=int(getattr(args, "random_seed", 0) or 0),
                stream=sampling_stream_from_args(args)))
            group_params, group_weights = [], []
            for g, members in enumerate(self.groups):
                active = [c for c in members if c in sampled]
                if not active:
                    continue
                gp = self.params
                gw = 0.0
                for edge_round in range(self.group_comm_round):
                    key = jax.random.fold_in(
                        jax.random.fold_in(self.rng, round_idx),
                        g * 1000 + edge_round)
                    gp, gw = self._train_clients(
                        gp, active, key,
                        hyper.replace(round_idx=jnp.int32(round_idx)))
                group_params.append(gp)
                group_weights.append(gw)
            self.params = _average_groups(
                group_params, jnp.asarray(group_weights, jnp.float32))
            rec: Dict[str, Any] = {"round": round_idx}
            freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
            # freq < 0: never evaluate in-loop (bench timing mode —
            # a per-round full-test eval would pollute round_s)
            if freq > 0 and (round_idx % freq == 0
                             or round_idx == rounds - 1):
                stats = self._evaluate(self.params, self.fed.test["x"],
                                       self.fed.test["y"], self.fed.test["mask"])
                n = max(float(stats["count"]), 1.0)
                rec["test_acc"] = float(stats["correct"]) / n
                logger.info("hierarchical round %d: acc=%.4f", round_idx,
                            rec["test_acc"])
            self.history.append(rec)
        last_eval = next((r for r in reversed(self.history)
                          if "test_acc" in r), {})
        return {"params": self.params, "history": self.history,
                "wall_time_s": time.time() - t0,
                "final_test_acc": last_eval.get("test_acc"),
                "rounds": rounds}
