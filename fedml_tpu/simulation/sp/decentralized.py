"""Decentralized (gossip) FL — no server; neighbors mix via a topology.

Parity target: reference ``simulation/mpi/decentralized_framework/`` (topology
gossip over MPI) + ``core/distributed/topology/``. TPU-native design: all
node models are stacked on a leading [K] axis and the ENTIRE gossip round —
vmapped per-node local SGD followed by the mixing step ``P <- W @ P`` (the
row-stochastic topology matrix contracted against the stacked params) — is
one jitted program. On a mesh this mixing is a ``ppermute`` per directed
edge (``collectives.ppermute_tree``); the einsum form here is the
single-host equivalent that XLA maps to one matmul per leaf.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.algframe.types import TrainHyper
from ...core.algframe.local_training import evaluate
from ...core.distributed.topology import SymmetricTopologyManager

logger = logging.getLogger(__name__)


class DecentralizedSimulator:
    def __init__(self, args, fed_dataset, bundle, optimizer, spec):
        self.args = args
        self.fed = fed_dataset
        self.opt = optimizer
        self.spec = spec
        self.n = fed_dataset.num_clients
        tm = SymmetricTopologyManager(
            self.n, neighbor_num=int(getattr(args, "topology_neighbors", 2)
                                     or 2))
        tm.generate_topology()
        self.mixing = jnp.asarray(tm.mixing_matrix(), jnp.float32)
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        init_rng, self.rng = jax.random.split(self.rng)
        p0 = bundle.init(init_rng, fed_dataset.train.x[0, 0])
        # every node starts from the same init (reference does likewise)
        self.node_params = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a[None], (self.n,) + a.shape), p0)
        self._evaluate = jax.jit(lambda p, x, y, m: evaluate(spec, p, x, y, m))
        self._round = jax.jit(self._round_impl)
        self.history: List[Dict[str, Any]] = []

    def _round_impl(self, node_params, round_key, hyper):
        def one_node(params, cdata, cid):
            key = jax.random.fold_in(round_key, cid)
            out = self.opt.local_train(params, {}, {}, cdata, key, hyper)
            return jax.tree_util.tree_map(jnp.add, params, out.update)

        trained = jax.vmap(one_node)(
            node_params, self.fed.train, jnp.arange(self.n))
        # gossip mixing: P <- W @ P per leaf
        mixed = jax.tree_util.tree_map(
            lambda leaf: jnp.einsum(
                "ij,j...->i...", self.mixing, leaf.astype(jnp.float32)
            ).astype(leaf.dtype), trained)
        return mixed

    def consensus_distance(self) -> float:
        """Mean L2 distance of node models to their average — gossip should
        drive this toward 0."""
        mean = jax.tree_util.tree_map(lambda a: jnp.mean(a, axis=0),
                                      self.node_params)
        sq = jax.tree_util.tree_map(
            lambda a, m: jnp.sum((a - m[None]) ** 2, axis=tuple(
                range(1, a.ndim))), self.node_params, mean)
        total = sum(jax.tree_util.tree_leaves(sq))
        return float(jnp.mean(jnp.sqrt(total)))

    def run(self, comm_round: Optional[int] = None) -> Dict[str, Any]:
        args = self.args
        rounds = comm_round if comm_round is not None else int(args.comm_round)
        hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                           epochs=int(args.epochs))
        t0 = time.time()
        for round_idx in range(rounds):
            round_key = jax.random.fold_in(self.rng, round_idx)
            self.node_params = self._round(
                self.node_params, round_key,
                hyper.replace(round_idx=jnp.int32(round_idx)))
            rec: Dict[str, Any] = {"round": round_idx}
            freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
            if round_idx % freq == 0 or round_idx == rounds - 1:
                avg = jax.tree_util.tree_map(lambda a: jnp.mean(a, axis=0),
                                             self.node_params)
                stats = self._evaluate(avg, self.fed.test["x"],
                                       self.fed.test["y"],
                                       self.fed.test["mask"])
                n = max(float(stats["count"]), 1.0)
                rec["test_acc"] = float(stats["correct"]) / n
                rec["consensus_dist"] = self.consensus_distance()
                logger.info("gossip round %d: acc=%.4f consensus=%.4f",
                            round_idx, rec["test_acc"], rec["consensus_dist"])
            self.history.append(rec)
        last_eval = next(r for r in reversed(self.history) if "test_acc" in r)
        avg = jax.tree_util.tree_map(lambda a: jnp.mean(a, axis=0),
                                     self.node_params)
        return {"params": avg, "node_params": self.node_params,
                "history": self.history, "wall_time_s": time.time() - t0,
                "final_test_acc": last_eval["test_acc"], "rounds": rounds}
