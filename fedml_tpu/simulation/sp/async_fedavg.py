"""Asynchronous FedAvg — staleness-weighted server merges.

Parity target: reference ``simulation/mpi/async_fedavg/AsyncFedAVGAggregator.py:14``
(server merges each arriving client model immediately, down-weighted by
staleness; clients are re-dispatched with the current global model). The
simulation models heterogeneous client speeds with seeded per-client
durations and drives an event queue; local training stays the shared jitted
step (SURVEY §2.8: async dispatch is host-side, outside jit, by design).

Merge rule (FedAsync, Xie et al.): w <- (1-a_t) w + a_t w_k with
a_t = alpha * s(t - t_k), where s(.) is the shared staleness-decay family
from ``core/async_rounds`` (polynomial by default — the toy's historical
``(1 + staleness)^(-poly_a)``; constant/hinge ride the same knobs as the
production ``round_mode: async_buffered`` paths). One staleness
implementation for the SP toy, the TPU engine, and the cross-silo server —
their decay curves can no longer drift apart.
"""

from __future__ import annotations

import heapq
import logging
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.algframe.types import TrainHyper
from ...core.algframe.local_training import evaluate
from ...core.async_rounds import (durations_from_args,
                                  merge_alpha_from_args,
                                  staleness_fn_from_args)

logger = logging.getLogger(__name__)


class AsyncFedAvgSimulator:
    def __init__(self, args, fed_dataset, bundle, optimizer, spec):
        self.args = args
        self.fed = fed_dataset
        self.opt = optimizer
        self.spec = spec
        self.alpha = merge_alpha_from_args(args)
        self.staleness_fn = staleness_fn_from_args(args)
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        init_rng, self.rng = jax.random.split(self.rng)
        self.params = bundle.init(init_rng, fed_dataset.train.x[0, 0])
        self._local_train = jax.jit(self.opt.local_train)
        self._evaluate = jax.jit(lambda p, x, y, m: evaluate(spec, p, x, y, m))
        # per-client simulated round duration: heterogeneous, drawn from
        # the shared seeded arrival model (PR 5 stream discipline —
        # default_rng((random_seed, tag)), a pure function of the seed)
        self.durations = durations_from_args(fed_dataset.num_clients, args)
        self.history: List[Dict[str, Any]] = []

    def run(self, comm_round: Optional[int] = None) -> Dict[str, Any]:
        args = self.args
        total_merges = (comm_round if comm_round is not None
                        else int(args.comm_round))
        hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                           epochs=int(args.epochs))
        concurrency = min(int(args.client_num_per_round),
                          self.fed.num_clients)
        t0 = time.time()
        # event queue: (finish_time, client_id, version_at_dispatch,
        # params_snapshot) — clients must train on the model they were
        # HANDED, not the current one, or staleness is fictitious
        queue: List = []
        version = 0
        for cid in range(concurrency):
            heapq.heappush(queue,
                           (self.durations[cid], cid, version, self.params))
        next_cid = concurrency
        merges = 0
        while merges < total_merges and queue:
            now, cid, dispatched_version, dispatched_params = heapq.heappop(
                queue)
            key = jax.random.fold_in(jax.random.fold_in(self.rng, merges), cid)
            out = self._local_train(
                dispatched_params, {}, {},
                jax.tree_util.tree_map(lambda a: a[cid], self.fed.train),
                key, hyper.replace(round_idx=jnp.int32(merges)))
            staleness = version - dispatched_version
            a_t = self.alpha * float(self.staleness_fn(staleness))
            self.params = jax.tree_util.tree_map(
                lambda w, u: w + jnp.float32(a_t).astype(w.dtype) * u,
                self.params, out.update)
            version += 1
            merges += 1
            # redispatch: round-robin over all clients
            cid2 = next_cid % self.fed.num_clients
            next_cid += 1
            heapq.heappush(queue, (now + self.durations[cid2], cid2, version,
                                   self.params))
            rec: Dict[str, Any] = {"round": merges - 1,
                                   "staleness": int(staleness)}
            freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
            if (merges - 1) % freq == 0 or merges == total_merges:
                stats = self._evaluate(self.params, self.fed.test["x"],
                                       self.fed.test["y"],
                                       self.fed.test["mask"])
                n = max(float(stats["count"]), 1.0)
                rec["test_acc"] = float(stats["correct"]) / n
                logger.info("async merge %d (staleness %d): acc=%.4f",
                            merges - 1, staleness, rec["test_acc"])
            self.history.append(rec)
        last_eval = next(r for r in reversed(self.history) if "test_acc" in r)
        return {"params": self.params, "history": self.history,
                "wall_time_s": time.time() - t0,
                "final_test_acc": last_eval["test_acc"],
                "rounds": merges}
