"""FedGKT — Group Knowledge Transfer.

Parity target: reference ``simulation/mpi/fedgkt/`` (GKTTrainer/GKTServer:
edge devices train a small feature extractor + local classifier; they ship
extracted FEATURES + LOGITS + labels to the server; the server trains a
large head on those features with CE + KL-distillation from client logits,
then ships its own per-sample logits back; clients distill from the server
logits next round). Model exchange never happens — the protocol's payload
is the feature/logit tensors, which is what makes it fit memory-poor edges.

TPU-native design: client epoch and server epoch are each one jitted scan;
the feature tensors cross as stacked arrays (the S3 payload analogue).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

logger = logging.getLogger(__name__)


class _EdgeNet(nn.Module):
    """Small client model: feature extractor + auxiliary classifier."""
    feat_dim: int
    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        h = nn.relu(nn.Dense(self.feat_dim)(x))
        logits = nn.Dense(self.num_classes)(h)
        return h, logits


class _ServerHead(nn.Module):
    """Larger server model consuming client features."""
    num_classes: int
    hidden: int = 256

    @nn.compact
    def __call__(self, h, train: bool = False):
        h = nn.relu(nn.Dense(self.hidden)(h))
        h = nn.relu(nn.Dense(self.hidden)(h))
        return nn.Dense(self.num_classes)(h)


def _masked_ce(logits, y, mask):
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def _masked_kl(student_logits, teacher_logits, mask, temp: float):
    t = jax.nn.softmax(teacher_logits / temp)
    s = jax.nn.log_softmax(student_logits / temp)
    kl = jnp.sum(t * (jnp.log(jnp.maximum(t, 1e-9)) - s), axis=-1)
    return jnp.sum(kl * mask) / jnp.maximum(jnp.sum(mask), 1.0)


class FedGKTSimulator:
    def __init__(self, args, fed_dataset, bundle=None, optimizer=None,
                 spec=None):
        self.args = args
        self.fed = fed_dataset
        self.temp = float(getattr(args, "gkt_temperature", 3.0) or 3.0)
        self.alpha = float(getattr(args, "gkt_kd_alpha", 1.0) or 1.0)
        self.feat_dim = int(getattr(args, "gkt_feat_dim", 64) or 64)
        k = fed_dataset.num_classes
        self.edge = _EdgeNet(self.feat_dim, k)
        self.head = _ServerHead(k)
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        ke, kh, self.rng = jax.random.split(rng, 3)
        sample = fed_dataset.train.x[0, 0]
        self.edge_params = [
            self.edge.init(jax.random.fold_in(ke, c), sample)["params"]
            for c in range(fed_dataset.num_clients)]
        h0 = jnp.zeros((2, self.feat_dim), jnp.float32)
        self.head_params = self.head.init(kh, h0)["params"]
        # CE+KL on raw features diverges at classification lr defaults;
        # the protocol carries its own tuned rate (reference uses per-
        # protocol optimizer configs in fedgkt/GKTTrainer)
        self.lr = float(getattr(args, "gkt_lr", 0.01) or 0.01)
        self._client_epoch = jax.jit(self._client_epoch_impl)
        self._server_epoch = jax.jit(self._server_epoch_impl)
        self._extract = jax.jit(self._extract_impl)
        self.history: List[Dict[str, Any]] = []

    # --- client side --------------------------------------------------------
    def _client_epoch_impl(self, params, cdata, server_logits, use_kd):
        opt = optax.sgd(self.lr, momentum=0.9)
        state = opt.init(params)

        def step(carry, inp):
            params, state = carry
            x, y, mask, slog = inp

            def loss_fn(p):
                _, logits = self.edge.apply({"params": p}, x)
                ce = _masked_ce(logits, y, mask)
                kd = _masked_kl(logits, slog, mask, self.temp)
                return ce + self.alpha * use_kd * kd

            loss, grads = jax.value_and_grad(loss_fn)(params)
            up, state = opt.update(grads, state, params)
            return (optax.apply_updates(params, up), state), loss

        (params, _), losses = jax.lax.scan(
            step, (params, state),
            (cdata.x, cdata.y, cdata.mask, server_logits))
        return params, jnp.mean(losses)

    def _extract_impl(self, params, cdata):
        def body(_, inp):
            x, _y = inp
            h, logits = self.edge.apply({"params": params}, x)
            return None, (h, logits)

        _, (feats, logits) = jax.lax.scan(body, None, (cdata.x, cdata.y))
        return feats, logits

    # --- server side --------------------------------------------------------
    def _server_epoch_impl(self, head_params, feats, logits, ys, masks):
        opt = optax.sgd(self.lr, momentum=0.9)
        state = opt.init(head_params)

        def step(carry, inp):
            params, state = carry
            h, clog, y, mask = inp

            def loss_fn(p):
                slog = self.head.apply({"params": p}, h)
                return (_masked_ce(slog, y, mask)
                        + self.alpha * _masked_kl(slog, clog, mask,
                                                  self.temp))

            loss, grads = jax.value_and_grad(loss_fn)(params)
            up, state = opt.update(grads, state, params)
            return (optax.apply_updates(params, up), state), loss

        (head_params, _), losses = jax.lax.scan(
            step, (head_params, state), (feats, logits, ys, masks))

        def back(_, inp):
            h, _ = inp
            return None, self.head.apply({"params": head_params}, h)

        _, server_logits = jax.lax.scan(back, None, (feats, logits))
        return head_params, server_logits, jnp.mean(losses)

    # --- evaluation: edge features -> server head ---------------------------
    def _evaluate(self) -> float:
        correct = total = 0.0
        test = self.fed.test
        # evaluate with client 0's extractor (reference evaluates the
        # server model fed by the edge extractor)
        p = self.edge_params[0]
        for i in range(test["x"].shape[0]):
            h, _ = self.edge.apply({"params": p}, test["x"][i])
            slog = self.head.apply({"params": self.head_params}, h)
            pred = jnp.argmax(slog, -1)
            m = test["mask"][i]
            correct += float(jnp.sum((pred == test["y"][i]) * m))
            total += float(jnp.sum(m))
        return correct / max(total, 1.0)

    def run(self, comm_round=None) -> Dict[str, Any]:
        rounds = int(comm_round if comm_round is not None
                     else self.args.comm_round)
        n_clients = self.fed.num_clients
        t0 = time.time()
        # per-client cached server logits (zeros -> KD off in round 0)
        nb, bs = self.fed.train.x.shape[1], self.fed.train.x.shape[2]
        k = self.fed.num_classes
        server_logits = [jnp.zeros((nb, bs, k), jnp.float32)
                         for _ in range(n_clients)]
        for r in range(rounds):
            use_kd = jnp.float32(0.0 if r == 0 else 1.0)
            feats_all, logits_all, ys, masks = [], [], [], []
            losses = []
            for c in range(n_clients):
                cdata = jax.tree_util.tree_map(lambda a: a[c],
                                               self.fed.train)
                self.edge_params[c], loss = self._client_epoch(
                    self.edge_params[c], cdata, server_logits[c], use_kd)
                losses.append(float(loss))
                f, lg = self._extract(self.edge_params[c], cdata)
                feats_all.append(f)
                logits_all.append(lg)
                ys.append(cdata.y)
                masks.append(cdata.mask)
            # server trains on the concatenated feature stream
            feats = jnp.concatenate(feats_all)
            logits = jnp.concatenate(logits_all)
            y = jnp.concatenate(ys)
            mask = jnp.concatenate(masks)
            self.head_params, slog, sloss = self._server_epoch(
                self.head_params, feats, logits, y, mask)
            # route the server logits back per client
            off = 0
            for c in range(n_clients):
                n_b = feats_all[c].shape[0]
                server_logits[c] = slog[off:off + n_b]
                off += n_b
            acc = self._evaluate()
            rec = {"round": r, "client_loss": float(np.mean(losses)),
                   "server_loss": float(sloss), "test_acc": acc}
            logger.info("fedgkt round %d: %s", r, rec)
            self.history.append(rec)
        return {"params": self.head_params,
                "edge_params": self.edge_params,
                "history": self.history,
                "final_test_acc": self.history[-1]["test_acc"],
                "wall_time_s": time.time() - t0, "rounds": rounds}
