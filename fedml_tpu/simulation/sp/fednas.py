"""FedNAS — federated neural architecture search (DARTS-style).

Parity target: reference ``simulation/mpi/fednas/`` (+ ``model/cv/darts``):
clients hold a DARTS supernet — every edge computes a softmax-weighted MIX
of candidate ops — and alternate updates of model weights w (train split)
and architecture parameters alpha (search split); the server FedAvg-
averages BOTH w and alpha each round; after searching, the discrete
architecture is derived by argmax over alpha.

TPU-native design: the supernet's op mix is a dense einsum over a stacked
op dimension (all candidate ops computed, weighted by softmax(alpha)) — no
dynamic graph surgery, so the whole bilevel round jits. The search space
here is a compact MLP cell (op choices: linear / relu-linear / identity-ish
projection / zero) sized for simulation-scale parity, not ImageNet DARTS.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ...core.collectives import stack_trees, tree_weighted_average

logger = logging.getLogger(__name__)

OPS = ("linear", "relu_linear", "proj_skip", "zero")


class _MixedCell(nn.Module):
    """One DARTS edge: softmax(alpha)-weighted sum of candidate ops."""
    width: int

    @nn.compact
    def __call__(self, x, alpha):
        outs = [
            nn.Dense(self.width, name="op_linear")(x),
            nn.relu(nn.Dense(self.width, name="op_relu")(x)),
            nn.Dense(self.width, use_bias=False, name="op_proj")(x),
            jnp.zeros(x.shape[:-1] + (self.width,), x.dtype),
        ]
        w = jax.nn.softmax(alpha)
        return sum(w[i] * o for i, o in enumerate(outs))


class _SuperNet(nn.Module):
    num_classes: int
    width: int = 64
    cells: int = 2

    @nn.compact
    def __call__(self, x, alphas, train: bool = False):
        h = x.reshape((x.shape[0], -1))
        for i in range(self.cells):
            h = _MixedCell(self.width, name=f"cell{i}")(h, alphas[i])
        return nn.Dense(self.num_classes)(h)


class FedNASSimulator:
    def __init__(self, args, fed_dataset, bundle=None, optimizer=None,
                 spec=None):
        self.args = args
        self.fed = fed_dataset
        self.cells = int(getattr(args, "nas_cells", 2) or 2)
        self.net = _SuperNet(fed_dataset.num_classes,
                             width=int(getattr(args, "nas_width", 64) or 64),
                             cells=self.cells)
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        kinit, self.rng = jax.random.split(rng)
        sample = fed_dataset.train.x[0, 0]
        alphas0 = jnp.zeros((self.cells, len(OPS)), jnp.float32)
        self.params = self.net.init(kinit, sample, alphas0)["params"]
        self.alphas = alphas0
        self.lr = float(getattr(args, "learning_rate", 0.05))
        self.alpha_lr = float(getattr(args, "nas_alpha_lr", 3e-2) or 3e-2)
        self._client_round = jax.jit(self._client_round_impl)
        self.history: List[Dict[str, Any]] = []

    def _loss(self, params, alphas, x, y, mask):
        logits = self.net.apply({"params": params}, x, alphas)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        loss = jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        correct = jnp.sum((jnp.argmax(logits, -1) == y) * mask)
        return loss, correct

    def _client_round_impl(self, params, alphas, cdata):
        """Alternating bilevel epoch: even batches update w, odd batches
        update alpha (the reference alternates train/search loaders)."""
        wopt = optax.sgd(self.lr, momentum=0.9)
        aopt = optax.adam(self.alpha_lr)
        wstate = wopt.init(params)
        astate = aopt.init(alphas)

        def step(carry, inp):
            params, alphas, ws, as_, i = carry
            x, y, mask = inp

            def wloss(p):
                return self._loss(p, alphas, x, y, mask)[0]

            def aloss(a):
                return self._loss(params, a, x, y, mask)[0]

            is_w = (i % 2) == 0
            wg = jax.grad(wloss)(params)
            ag = jax.grad(aloss)(alphas)
            wup, ws2 = wopt.update(wg, ws, params)
            aup, as2 = aopt.update(ag, as_, alphas)
            new_p = optax.apply_updates(params, wup)
            new_a = optax.apply_updates(alphas, aup)
            sel = lambda nw, old: jax.tree_util.tree_map(
                lambda a_, b_: jnp.where(is_w, a_, b_), nw, old)
            seln = lambda nw, old: jax.tree_util.tree_map(
                lambda a_, b_: jnp.where(is_w, b_, a_), nw, old)
            params = sel(new_p, params)
            ws = sel(ws2, ws)
            alphas = seln(new_a, alphas)
            as_ = seln(as2, as_)
            loss, _ = self._loss(params, alphas, x, y, mask)
            return (params, alphas, ws, as_, i + 1), loss

        (params, alphas, _, _, _), losses = jax.lax.scan(
            step, (params, alphas, wstate, astate, jnp.int32(0)),
            (cdata.x, cdata.y, cdata.mask))
        return params, alphas, jnp.mean(losses)

    def derive_architecture(self) -> List[str]:
        """Discretize: argmax over alpha per cell (reference genotype)."""
        return [OPS[int(np.argmax(np.asarray(self.alphas[i])))]
                for i in range(self.cells)]

    def _evaluate(self) -> float:
        test = self.fed.test
        correct = total = 0.0
        for i in range(test["x"].shape[0]):
            _, c = self._loss(self.params, self.alphas, test["x"][i],
                              test["y"][i], test["mask"][i])
            correct += float(c)
            total += float(jnp.sum(test["mask"][i]))
        return correct / max(total, 1.0)

    def run(self, comm_round=None) -> Dict[str, Any]:
        rounds = int(comm_round if comm_round is not None
                     else self.args.comm_round)
        n_per_round = int(getattr(self.args, "client_num_per_round",
                                  self.fed.num_clients))
        t0 = time.time()
        for r in range(rounds):
            rs = np.random.RandomState(100 + r)
            sampled = rs.choice(self.fed.num_clients,
                                min(n_per_round, self.fed.num_clients),
                                replace=False)
            ps, als, weights, losses = [], [], [], []
            for cid in sampled:
                cdata = jax.tree_util.tree_map(lambda a: a[cid],
                                               self.fed.train)
                p, a, loss = self._client_round(self.params, self.alphas,
                                                cdata)
                ps.append(p)
                als.append(a)
                weights.append(float(cdata.num_samples))
                losses.append(float(loss))
            w = jnp.asarray(weights, jnp.float32)
            self.params = tree_weighted_average(stack_trees(ps), w)
            self.alphas = tree_weighted_average(jnp.stack(als), w)
            acc = self._evaluate()
            rec = {"round": r, "train_loss": float(np.mean(losses)),
                   "test_acc": acc,
                   "architecture": self.derive_architecture()}
            logger.info("fednas round %d: %s", r, rec)
            self.history.append(rec)
        return {"params": self.params, "alphas": self.alphas,
                "architecture": self.derive_architecture(),
                "history": self.history,
                "final_test_acc": self.history[-1]["test_acc"],
                "wall_time_s": time.time() - t0, "rounds": rounds}
