"""Classical vertical FL — parties hold disjoint FEATURE subsets of the same
samples; the label party coordinates.

Parity target: reference ``simulation/sp/classical_vertical_fl/``
(``vfl_api.py`` — party models split by features, logit contributions
summed, only gradients w.r.t. its own logit flow back to each party) and the
finance VFL models (``model/finance/vfl_*``). TPU-native: the joint step is
one jitted program over the tuple of party parameter trees; per-party
gradients come from one backward pass, preserving the "each party updates
only its own slice" boundary structurally.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

logger = logging.getLogger(__name__)


class _PartyNet(nn.Module):
    """Per-party bottom model producing a logit contribution."""
    num_classes: int
    hidden: int = 32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        h = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.num_classes)(h)


class VerticalFLSimulator:
    """``party_num`` parties; features split contiguously among them."""

    def __init__(self, args, fed_dataset, bundle=None, optimizer=None,
                 spec=None):
        self.args = args
        self.fed = fed_dataset
        self.party_num = int(getattr(args, "party_num", 2) or 2)
        self.lr = float(args.learning_rate)
        # pool all clients' data: VFL has one logical dataset, feature-split
        x = np.asarray(fed_dataset.train.x)
        y = np.asarray(fed_dataset.train.y)
        m = np.asarray(fed_dataset.train.mask)
        # [clients, n_batches, batch, ...feat] -> [N, ...feat]
        self.x = jnp.asarray(x.reshape((-1,) + x.shape[3:]))
        self.y = jnp.asarray(y.reshape(-1))
        self.mask = jnp.asarray(m.reshape(-1))
        feat = int(np.prod(self.x.shape[1:]))
        self.x = self.x.reshape(self.x.shape[0], feat)
        # contiguous feature split
        splits = np.linspace(0, feat, self.party_num + 1).astype(int)
        self.slices: List[Tuple[int, int]] = [
            (int(splits[i]), int(splits[i + 1]))
            for i in range(self.party_num)]
        self.nets = [_PartyNet(fed_dataset.num_classes)
                     for _ in range(self.party_num)]
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        keys = jax.random.split(rng, self.party_num + 1)
        self.rng = keys[-1]
        self.party_params = [
            net.init(k, self.x[:2, s:e])
            for net, k, (s, e) in zip(self.nets, keys[:-1], self.slices)]
        tx, ty, tm = fed_dataset.test["x"], fed_dataset.test["y"], \
            fed_dataset.test["mask"]
        self.test_x = jnp.asarray(np.asarray(tx).reshape(
            (-1,) + np.asarray(tx).shape[2:])).reshape(-1, feat)
        self.test_y = jnp.asarray(np.asarray(ty).reshape(-1))
        self.test_mask = jnp.asarray(np.asarray(tm).reshape(-1))
        self.batch_size = int(args.batch_size)
        self._step = jax.jit(self._step_impl)
        self._eval = jax.jit(self._eval_impl)
        self.history: List[Dict[str, Any]] = []

    def _logits(self, party_params, x):
        total = None
        for net, p, (s, e) in zip(self.nets, party_params, self.slices):
            contrib = net.apply(p, x[:, s:e])  # the only value crossing
            total = contrib if total is None else total + contrib
        return total

    def _loss(self, party_params, x, y, mask):
        logits = self._logits(party_params, x)
        per_ex = optax.softmax_cross_entropy_with_integer_labels(
            logits, y.astype(jnp.int32))
        mask = mask.astype(per_ex.dtype)
        loss = jnp.sum(per_ex * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        correct = jnp.sum((jnp.argmax(logits, -1) == y) * mask)
        return loss, (correct, jnp.sum(mask))

    def _step_impl(self, party_params, x, y, mask):
        (loss, aux), grads = jax.value_and_grad(self._loss, has_aux=True)(
            party_params, x, y, mask)
        new = [jax.tree_util.tree_map(lambda w, g: w - self.lr * g, p, gp)
               for p, gp in zip(party_params, grads)]
        return new, loss, aux

    def _eval_impl(self, party_params, x, y, mask):
        _, (correct, count) = self._loss(party_params, x, y, mask)
        return correct, count

    def run(self, comm_round: Optional[int] = None) -> Dict[str, Any]:
        args = self.args
        rounds = comm_round if comm_round is not None else int(args.comm_round)
        n = self.x.shape[0]
        bs = self.batch_size
        steps = max(n // bs, 1)
        t0 = time.time()
        rng = np.random.RandomState(int(getattr(args, "random_seed", 0)))
        for round_idx in range(rounds):
            perm = rng.permutation(n)
            for s in range(steps):
                idx = perm[s * bs:(s + 1) * bs]
                self.party_params, loss, _ = self._step(
                    self.party_params, self.x[idx], self.y[idx],
                    self.mask[idx])
            rec: Dict[str, Any] = {"round": round_idx}
            freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
            if round_idx % freq == 0 or round_idx == rounds - 1:
                correct, count = self._eval(self.party_params, self.test_x,
                                            self.test_y, self.test_mask)
                rec["test_acc"] = float(correct) / max(float(count), 1.0)
                logger.info("vfl round %d: acc=%.4f", round_idx,
                            rec["test_acc"])
            self.history.append(rec)
        last_eval = next(r for r in reversed(self.history) if "test_acc" in r)
        return {"params": self.party_params, "history": self.history,
                "wall_time_s": time.time() - t0,
                "final_test_acc": last_eval["test_acc"], "rounds": rounds}
