"""TurboAggregate — group-ring secure aggregation protocol.

Parity target: reference ``simulation/mpi/fedavg_robust``-adjacent
``turboaggregate`` stack (``simulation/sp/turboaggregate`` in the optimizer
list): clients are partitioned into L groups arranged in a ring; group l
adds its (masked) partial sum onto the running aggregate received from
group l-1 and forwards it — aggregation cost grows O(N log N) instead of
the star topology's O(N^2) masking pairs.

TPU-native design: the additive masking rides the same GF(2^31-1)
fixed-point field as the SecAgg stack (``core/mpc``); each group's members
mask their quantized updates with pairwise-cancelling PRG streams INSIDE
the group, so the forwarded partial sums never expose an individual
update, and the final ring output de-quantizes to exactly the FedAvg
aggregate (asserted against the plain weighted average in tests).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ...core.algframe.types import TrainHyper
from ...core.algframe.local_training import evaluate
from ...core.collectives import (tree_flatten_to_vector,
                                 vector_to_tree_like)

logger = logging.getLogger(__name__)

PRIME = np.uint64(2147483647)  # 2^31 - 1, shared with core/mpc/field_ops


def _quantize(v: np.ndarray, scale: float) -> np.ndarray:
    half = np.int64(int(PRIME) // 2)
    q = np.clip(np.rint(v.astype(np.float64) * scale), -half, half - 1)
    return ((q + half) % np.int64(int(PRIME))).astype(np.uint64)


def _dequantize_sum(f: np.ndarray, n_terms: int, scale: float) -> np.ndarray:
    p = np.int64(int(PRIME))
    half = p // 2
    shifted = (f.astype(np.int64) - (n_terms * half) % p) % p
    signed = np.where(shifted > half, shifted - p, shifted)
    return signed.astype(np.float64) / scale


def _prg_mask(n: int, seed: int) -> np.ndarray:
    return np.random.RandomState(seed).randint(
        0, int(PRIME), size=n, dtype=np.uint64)


class TurboAggregateSimulator:
    """FedAvg whose aggregation runs through the group-ring protocol."""

    def __init__(self, args, fed_dataset, bundle, optimizer, spec):
        self.args = args
        self.fed = fed_dataset
        self.bundle = bundle
        self.opt = optimizer
        self.spec = spec
        self.groups = int(getattr(args, "turbo_groups", 2) or 2)
        self.scale = float(getattr(args, "secagg_scale", 2 ** 16))
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        init_rng, self.rng = jax.random.split(rng)
        sample = fed_dataset.train.x[0, 0]
        self.params = bundle.init(init_rng, sample)
        self.server_state = self.opt.server_init(self.params)
        self._local_train = jax.jit(
            lambda p, ss, cs, cd, key, hyper: self.opt.local_train(
                p, ss, cs, cd, key, hyper))
        self._evaluate = jax.jit(
            lambda p, x, y, m: evaluate(spec, p, x, y, m))
        self.history: List[Dict[str, Any]] = []

    def _ring_aggregate(self, updates: List[np.ndarray],
                        weights: List[float], round_idx: int) -> np.ndarray:
        """Group-ring masked aggregation. Masks cancel within each group;
        the ring carries only partial sums."""
        n = len(updates)
        dim = updates[0].size
        group_of = [i % self.groups for i in range(n)]
        total_w = sum(weights) or 1.0
        p = np.uint64(int(PRIME))
        running = np.zeros(dim, np.uint64)
        n_terms = 0
        for g in range(self.groups):
            members = [i for i in range(n) if group_of[i] == g]
            partial = np.zeros(dim, np.uint64)
            for idx, i in enumerate(members):
                scaled = updates[i] * (weights[i] / total_w)
                q = _quantize(scaled, self.scale)
                # pairwise-cancelling masks inside the group: member j adds
                # +mask(j,j+1) and -mask(j-1,j) (ring within the group)
                nxt = members[(idx + 1) % len(members)]
                prv = members[(idx - 1) % len(members)]
                if len(members) > 1:
                    m_add = _prg_mask(dim, 7919 * round_idx + 13 * i + nxt)
                    m_sub = _prg_mask(dim, 7919 * round_idx + 13 * prv + i)
                    q = (q + m_add) % p
                    q = (q + p - m_sub) % p
                partial = (partial + q) % p
            running = (running + partial) % p
            n_terms += len(members)
        return _dequantize_sum(running, n_terms, self.scale)

    def run(self, comm_round=None) -> Dict[str, Any]:
        rounds = int(comm_round if comm_round is not None
                     else self.args.comm_round)
        n_per_round = int(getattr(self.args, "client_num_per_round",
                                  self.fed.num_clients))
        hyper = TrainHyper(
            learning_rate=jnp.float32(self.args.learning_rate),
            epochs=int(self.args.epochs))
        cstate0 = self.opt.client_state_init(self.params)
        t0 = time.time()
        for r in range(rounds):
            rs = np.random.RandomState(300 + r)
            sampled = rs.choice(self.fed.num_clients,
                                min(n_per_round, self.fed.num_clients),
                                replace=False)
            updates, weights = [], []
            metrics_sum = {"loss_sum": 0.0, "correct": 0.0, "count": 0.0}
            hyper_r = hyper.replace(round_idx=jnp.int32(r))
            for cid in sampled:
                cdata = jax.tree_util.tree_map(lambda a: a[cid],
                                               self.fed.train)
                key = jax.random.fold_in(jax.random.fold_in(self.rng, r),
                                         int(cid))
                out = self._local_train(self.params, self.server_state,
                                        cstate0, cdata, key, hyper_r)
                vec = np.asarray(tree_flatten_to_vector(out.update),
                                 np.float64)
                updates.append(vec)
                weights.append(float(out.weight))
                for k in metrics_sum:
                    metrics_sum[k] += float(out.metrics[k])
            agg_vec = self._ring_aggregate(updates, weights, r)
            agg = vector_to_tree_like(jnp.asarray(agg_vec, jnp.float32),
                                      self.params)
            self.params, self.server_state = self.opt.server_update(
                self.params, self.server_state, agg,
                self.opt.server_extras_zero(self.params), jnp.int32(r))
            cnt = max(metrics_sum["count"], 1.0)
            rec = {"round": r,
                   "train_loss": metrics_sum["loss_sum"] / cnt,
                   "train_acc": metrics_sum["correct"] / cnt}
            freq = int(getattr(self.args, "frequency_of_the_test", 5) or 5)
            if r % freq == 0 or r == rounds - 1:
                stats = self._evaluate(self.params, self.fed.test["x"],
                                       self.fed.test["y"],
                                       self.fed.test["mask"])
                n = max(float(stats["count"]), 1.0)
                rec["test_acc"] = float(stats["correct"]) / n
                logger.info("turbo round %d: acc=%.4f", r, rec["test_acc"])
            self.history.append(rec)
        last = next((h for h in reversed(self.history) if "test_acc" in h),
                    {})
        return {"params": self.params, "history": self.history,
                "final_test_acc": last.get("test_acc"),
                "wall_time_s": time.time() - t0, "rounds": rounds}
