"""FedGAN — federated GAN training.

Parity target: reference ``simulation/mpi/fedgan/`` (clients train the
(G, D) pair on local data; server FedAvg-averages both networks each
round). TPU-native design: one jitted per-client round alternates D and G
steps inside a ``lax.scan`` over batches, and the (G, D) aggregation is a
single weighted tree-average — the whole round is two pytrees in, two out.

Metric: discriminator's ability to distinguish real from generated data
should *decline* toward 0.5 accuracy as G learns (plus G loss should fall),
which is what the learning test asserts.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import optax

from ...core.collectives import stack_trees, tree_weighted_average

logger = logging.getLogger(__name__)


def _bce_logits(logits, targets):
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, targets))


class FedGANSimulator:
    """Clients = data shards; each trains the shared (G, D) locally; server
    averages both."""

    def __init__(self, args, fed_dataset, bundles, optimizer=None,
                 spec=None):
        if not isinstance(bundles, tuple) or len(bundles) != 2:
            raise ValueError("FedGAN needs the (generator, discriminator) "
                             "bundle pair (model='gan')")
        self.args = args
        self.fed = fed_dataset
        self.gen_bundle, self.disc_bundle = bundles
        self.latent = int(getattr(args, "gan_latent_dim", 100) or 100)
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        kg, kd, self.rng = jax.random.split(rng, 3)
        img_dim = int(jnp.prod(jnp.asarray(fed_dataset.input_shape)))
        self.img_dim = img_dim
        z0 = jnp.zeros((2, self.latent), jnp.float32)
        x0 = jnp.zeros((2, img_dim), jnp.float32)
        self.gen_params = self.gen_bundle.module.init(kg, z0)["params"]
        self.disc_params = self.disc_bundle.module.init(kd, x0)["params"]
        self.lr = float(getattr(args, "learning_rate", 2e-4))
        self._client_round = jax.jit(self._client_round_impl)
        self.history: List[Dict[str, Any]] = []

    def _client_round_impl(self, gen_params, disc_params, cdata, rng):
        gopt = optax.adam(self.lr, b1=0.5)
        dopt = optax.adam(self.lr, b1=0.5)
        gstate = gopt.init(gen_params)
        dstate = dopt.init(disc_params)
        gen_apply = self.gen_bundle.module.apply
        disc_apply = self.disc_bundle.module.apply

        def step(carry, inp):
            gp, dp, gs, ds, rng = carry
            x, mask = inp
            rng, kz1, kz2 = jax.random.split(rng, 3)
            bs = x.shape[0]
            x = x.reshape(bs, -1)
            m = mask.reshape(bs, 1)

            def d_loss(dparams):
                z = jax.random.normal(kz1, (bs, self.latent))
                fake = gen_apply({"params": gp}, z)
                real_logit = disc_apply({"params": dparams}, x)
                fake_logit = disc_apply({"params": dparams}, fake)
                lr_ = _bce_logits(real_logit * m, m)  # real -> 1 (masked)
                lf_ = _bce_logits(fake_logit, jnp.zeros_like(fake_logit))
                return lr_ + lf_

            dl, dgrads = jax.value_and_grad(d_loss)(dp)
            dup, ds = dopt.update(dgrads, ds, dp)
            dp = optax.apply_updates(dp, dup)

            def g_loss(gparams):
                z = jax.random.normal(kz2, (bs, self.latent))
                fake = gen_apply({"params": gparams}, z)
                fake_logit = disc_apply({"params": dp}, fake)
                return _bce_logits(fake_logit, jnp.ones_like(fake_logit))

            gl, ggrads = jax.value_and_grad(g_loss)(gp)
            gup, gs = gopt.update(ggrads, gs, gp)
            gp = optax.apply_updates(gp, gup)
            return (gp, dp, gs, ds, rng), {"d_loss": dl, "g_loss": gl}

        (gp, dp, _, _, _), losses = jax.lax.scan(
            step, (gen_params, disc_params, gstate, dstate, rng),
            (cdata.x, cdata.mask))
        return gp, dp, {k: jnp.mean(v) for k, v in losses.items()}

    def _disc_real_vs_fake_acc(self, n: int = 256) -> float:
        """How well D separates real/generated — approaches 0.5 as G wins."""
        key1, key2, self.rng = jax.random.split(self.rng, 3)
        z = jax.random.normal(key1, (n, self.latent))
        fake = self.gen_bundle.module.apply({"params": self.gen_params}, z)
        xr = self.fed.test["x"].reshape(-1, self.img_dim)[:n]
        rl = self.disc_bundle.module.apply({"params": self.disc_params}, xr)
        fl = self.disc_bundle.module.apply({"params": self.disc_params}, fake)
        acc = 0.5 * (jnp.mean(rl > 0) + jnp.mean(fl <= 0))
        return float(acc)

    def run(self, comm_round=None) -> Dict[str, Any]:
        rounds = int(comm_round if comm_round is not None
                     else self.args.comm_round)
        n_per_round = int(getattr(self.args, "client_num_per_round",
                                  self.fed.num_clients))
        t0 = time.time()
        for r in range(rounds):
            import numpy as np
            rs = np.random.RandomState(r)
            sampled = rs.choice(self.fed.num_clients,
                                min(n_per_round, self.fed.num_clients),
                                replace=False)
            gps, dps, weights = [], [], []
            d_losses, g_losses = [], []
            for cid in sampled:
                cdata = jax.tree_util.tree_map(lambda a: a[cid],
                                               self.fed.train)
                key = jax.random.fold_in(jax.random.fold_in(self.rng, r),
                                         int(cid))
                gp, dp, losses = self._client_round(
                    self.gen_params, self.disc_params, cdata, key)
                gps.append(gp)
                dps.append(dp)
                weights.append(float(cdata.num_samples))
                d_losses.append(float(losses["d_loss"]))
                g_losses.append(float(losses["g_loss"]))
            w = jnp.asarray(weights, jnp.float32)
            self.gen_params = tree_weighted_average(stack_trees(gps), w)
            self.disc_params = tree_weighted_average(stack_trees(dps), w)
            rec = {"round": r, "d_loss": sum(d_losses) / len(d_losses),
                   "g_loss": sum(g_losses) / len(g_losses),
                   "disc_acc": self._disc_real_vs_fake_acc()}
            logger.info("fedgan round %d: %s", r, rec)
            self.history.append(rec)
        return {"gen_params": self.gen_params,
                "disc_params": self.disc_params,
                "params": self.gen_params,
                "history": self.history,
                "final_disc_acc": self.history[-1]["disc_acc"],
                "final_test_acc": self.history[-1]["disc_acc"],
                "wall_time_s": time.time() - t0,
                "rounds": rounds}
