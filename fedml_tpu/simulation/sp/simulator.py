"""Single-process golden simulator.

Parity target: the canonical SP FedAvg loop
(``simulation/sp/fedavg/fedavg_api.py:14`` — train loop :66-125, sampling
:127, ``_aggregate`` :144) generalized over every federated optimizer. This
backend is the *semantic reference*: the TPU mesh backend must match it
numerically (SURVEY §4: "same algorithm, three backends" is the strongest
testability idea in the reference — here it is a first-class parity test).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.algframe.types import ClientData, TrainHyper
from ...core.algframe.local_training import evaluate
from ...core.collectives import tree_weighted_average, vector_to_tree_like
from ...core.dp import FedMLDifferentialPrivacy
from ...core import mlops
from ...core.checkpoint import RoundCheckpointer
from ...core.contribution import ContributionAssessorManager
from ...core.security import FedMLAttacker, FedMLDefender, stack_to_matrix
from ...core.selection import SelectionManager
from ..tpu.engine import (ATTACK_FOLD, DEFENSE_FOLD, DP_CDP_FOLD,
                          DP_LDP_FOLD)

logger = logging.getLogger(__name__)


class SPSimulator:
    """Python round loop over jitted per-client local training."""

    def __init__(self, args, fed_dataset, bundle, optimizer, spec):
        self.args = args
        self.fed = fed_dataset
        self.bundle = bundle
        self.opt = optimizer
        self.spec = spec
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        init_rng, self.rng = jax.random.split(self.rng)
        sample = self.fed.train.x[0, 0]  # [bs, ...]
        self.params = bundle.init(init_rng, sample)
        self.server_state = self.opt.server_init(self.params)
        self.client_states = [self.opt.client_state_init(self.params)
                              for _ in range(self.fed.num_clients)]
        self._local_train = jax.jit(self.opt.local_train)
        self._server_update = jax.jit(self.opt.server_update)
        self._evaluate = jax.jit(lambda p, x, y, m: evaluate(spec, p, x, y, m))
        self.attacker = FedMLAttacker(args)
        self.defender = FedMLDefender(args)
        self.dp = FedMLDifferentialPrivacy(args)
        if self.attacker.is_data_attack():
            from ..poisoning import poison_dataset
            self.fed = poison_dataset(self.fed, self.attacker)
        from ..tpu.engine import _check_extras_compat
        _check_extras_compat(
            self.opt, self.params, self.dp,
            self.attacker.is_model_attack()
            or self.defender.is_defense_enabled())
        self.contribution = ContributionAssessorManager(args)
        # participant selection (the engine's subsystem, same knobs):
        # passive at defaults — uniform + legacy stream delegates to the
        # reference draw, trajectories stay bit-identical
        self.selection = SelectionManager(args, self.fed.num_clients)
        # pacer-driven cohort sizing (pacer_adapt_cohort; off = the
        # configured client_num_per_round, bit-identical): Oort's rule —
        # grow k once the sampled cohort's summed loss utility saturates
        self.pacer = None
        if bool(getattr(args, "pacer_adapt_cohort", False)):
            from ...core.selection import DeadlinePacer
            self.pacer = DeadlinePacer.from_args(args)
        self.ckpt = RoundCheckpointer(
            getattr(args, "checkpoint_dir", None),
            int(getattr(args, "checkpoint_every_rounds", 0) or 0))
        self.history: List[Dict[str, Any]] = []

    def _ckpt_state(self):
        st = {"params": self.params, "server_state": self.server_state,
              "client_states": self.client_states, "rng": self.rng,
              "dp": self.dp.state_dict()}
        if self.selection.stateful:
            # selection history rides the checkpoint so crash-resume
            # replays IDENTICAL cohorts (same contract as the engine)
            st["selection"] = self.selection.state_dict()
        if self.pacer is not None:
            # pacer posture too: a resumed run keeps its learned cohort
            # scale instead of re-learning the saturation point
            st["pacer"] = self.pacer.state_dict()
        return st

    def _ckpt_latest(self):
        """Tolerant restore (mirrors the engine): the optional
        ``selection``/``pacer`` leaves' presence can flip between save
        and resume (knob change, version skew) — retry without them
        rather than refusing a valid checkpoint."""
        template = self._ckpt_state()
        optional = [k for k in ("selection", "pacer") if k in template]
        try:
            return self.ckpt.latest(template)
        except Exception as e:
            if not optional:
                raise
            restored = self.ckpt.latest(
                {k: v for k, v in template.items() if k not in optional})
            if restored is not None:
                logger.warning(
                    "checkpoint restore succeeded only without the "
                    "optional %s leaves (%s: %s) — their history resumes "
                    "cold", optional, type(e).__name__, e)
            return restored

    def _load_ckpt_state(self, st):
        self.params = st["params"]
        self.server_state = st["server_state"]
        self.client_states = st["client_states"]
        self.rng = st["rng"]
        self.dp.load_state_dict(st["dp"])
        if "selection" in st and self.selection.stateful:
            self.selection.load_state_dict(st["selection"])
        if "pacer" in st and self.pacer is not None:
            self.pacer.load_state_dict(st["pacer"])

    def _client_data(self, cid: int) -> ClientData:
        return jax.tree_util.tree_map(lambda a: a[cid], self.fed.train)

    def _aggregate_robust(self, stacked, w, sampled, round_key, round_idx):
        """FedAvg weighted average, or the attack->defense->contribution
        pipeline when enabled (reference ServerAggregator
        on_before_aggregation / aggregate hooks,
        ``core/alg_frame/server_aggregator.py:44-103``). Contribution is
        assessed on the post-attack matrix — the server can only ever see
        what clients actually sent — matching the TPU path row-for-row."""
        if not (self.attacker.is_model_attack()
                or self.defender.is_defense_enabled()
                or self.contribution.enabled):
            return tree_weighted_average(stacked, w)
        ids = np.asarray(sampled)
        template = jax.tree_util.tree_map(lambda l: l[0], stacked)
        mat = stack_to_matrix(stacked)
        if self.attacker.is_model_attack():
            mat = self.attacker.poison_updates(
                mat, ids, jax.random.fold_in(round_key, ATTACK_FOLD))
        if self.contribution.enabled:
            self._assess_contribution(mat, w, sampled, round_idx)
        if self.defender.is_defense_enabled():
            vec, info = self.defender.defend_matrix(
                mat, w, jax.random.fold_in(round_key, DEFENSE_FOLD), ids)
            if self.selection.track and info:
                # defense verdicts feed reputation here too (the engine's
                # mask-vs-index validation applies unchanged)
                from ..tpu.engine import _verdict_from_info
                v = _verdict_from_info(info, len(sampled))
                if v is not None:
                    self.selection.store.record_verdict(
                        [int(c) for c in sampled], v)
        else:
            from ...core.security.defense.robust_agg import weighted_mean
            vec = weighted_mean(mat, jnp.asarray(w, jnp.float32))
        return vector_to_tree_like(vec, template)

    def _assess_contribution(self, mat, w, sampled, round_idx):
        from ...core.collectives import tree_flatten_to_vector
        spec, fed, params = self.spec, self.fed, self.params
        pvec = tree_flatten_to_vector(params)

        def eval_fn(p):
            cand = vector_to_tree_like(p["v"], params)
            stats = evaluate(spec, cand, fed.test["x"], fed.test["y"],
                             fed.test["mask"])
            return stats["correct"] / jnp.maximum(stats["count"], 1.0)

        self.contribution.assess({"v": pvec}, {"v": mat}, w, eval_fn,
                                 client_ids=sampled, round_idx=round_idx)

    def run(self, comm_round: Optional[int] = None) -> Dict[str, Any]:
        args = self.args
        rounds = comm_round if comm_round is not None else int(args.comm_round)
        hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                           epochs=int(args.epochs))
        t0 = time.time()
        start_round = 0
        restored = self._ckpt_latest()
        if restored is not None:
            step, st = restored
            self._load_ckpt_state(st)
            start_round = step + 1
            logger.info("resumed from checkpoint at round %d", step)
        for round_idx in range(start_round, rounds):
            # selection subsystem (uniform default = the reference's
            # client_sampling draw, bit-identical); a reputation
            # strategy's benched clients are simply not trained here —
            # the SP loop has no work-0 slot channel to renormalize
            k_round = int(args.client_num_per_round)
            if self.pacer is not None:
                k_round = min(self.pacer.paced_cohort(k_round),
                              self.fed.num_clients)
            full_sampled, excluded = self.selection.select(
                round_idx, k_round)
            excl = set(excluded)
            sampled = [c for c in full_sampled if c not in excl]
            self.selection.note_schedule(
                round_idx, full_sampled, excluded,
                {int(c): 1.0 for c in sampled},
                target_n=len(full_sampled))
            round_key = jax.random.fold_in(self.rng, round_idx)
            updates, weights, extras_list, states, metrics = [], [], [], [], []
            for cid in sampled:
                key = jax.random.fold_in(round_key, cid)
                out = self._local_train(
                    self.params, self.server_state, self.client_states[cid],
                    self._client_data(cid), key,
                    hyper.replace(round_idx=jnp.int32(round_idx)))
                upd = out.update
                if self.dp.is_local_dp_enabled():
                    upd = self.dp.add_local_noise(
                        upd, jax.random.fold_in(key, DP_LDP_FOLD))
                elif self.dp.is_global_dp_enabled():
                    upd = self.dp.clip_update(upd)
                updates.append(upd)
                weights.append(out.weight)
                extras_list.append(out.extras)
                metrics.append(out.metrics)
                if self.opt.has_client_state:
                    self.client_states[cid] = out.client_state
            if self.selection.track:
                # per-client losses feed the loss ring (SP materializes
                # round metrics host-side anyway — no extra transfer
                # pressure, unlike the engine's lazy queue)
                for cid, m in zip(sampled, metrics):
                    c = float(m["count"])
                    if c > 0:
                        self.selection.store.record_loss(
                            int(cid), float(m["loss_sum"]) / c)
            if self.pacer is not None:
                # summed per-client mean loss = the round's aggregate
                # statistical utility (Oort); saturation moves k
                util = sum(float(m["loss_sum"]) / max(float(m["count"]), 1.0)
                           for m in metrics)
                self.pacer.observe_utility(util)
            w = jnp.stack(weights)
            stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *updates)
            agg_update = self._aggregate_robust(stacked, w, sampled,
                                                round_key, round_idx)
            if self.dp.is_global_dp_enabled():
                agg_update = self.dp.add_global_noise(
                    agg_update, jax.random.fold_in(round_key, DP_CDP_FOLD))
            self.dp.record_round(len(sampled) / max(self.fed.num_clients, 1))
            if extras_list[0]:
                stacked_ex = jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *extras_list)
                agg_extras = tree_weighted_average(stacked_ex, w)
            else:
                agg_extras = {}
            self.params, self.server_state = self._server_update(
                self.params, self.server_state, agg_update, agg_extras,
                jnp.int32(round_idx))
            rec: Dict[str, Any] = {"round": round_idx}
            tm = jax.tree_util.tree_map(lambda *xs: sum(xs), *metrics)
            cnt = max(float(tm["count"]), 1.0)
            rec["train_loss"] = float(tm["loss_sum"]) / cnt
            rec["train_acc"] = float(tm["correct"]) / cnt
            freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
            # freq < 0: never evaluate in-loop (bench timing mode —
            # a per-round full-test eval would pollute round_s)
            if freq > 0 and (round_idx % freq == 0
                             or round_idx == rounds - 1):
                stats = self._evaluate(self.params, self.fed.test["x"],
                                       self.fed.test["y"], self.fed.test["mask"])
                n = max(float(stats["count"]), 1.0)
                rec["test_acc"] = float(stats["correct"]) / n
                rec["test_loss"] = float(stats["loss_sum"]) / n
                logger.info("round %d: test_acc=%.4f test_loss=%.4f",
                            round_idx, rec["test_acc"], rec["test_loss"])
            self.history.append(rec)
            self.ckpt.maybe_save(round_idx, self._ckpt_state())
            mlops.log_round_info(rounds, round_idx)
            mlops.log({k: v for k, v in rec.items() if k != "round"},
                      step=round_idx)
        # saves are async now; make them durable before the run returns
        self.ckpt.flush()
        wall = time.time() - t0
        last_eval = next((r for r in reversed(self.history) if "test_acc" in r),
                         None)
        if last_eval is None:
            if int(getattr(self.args, "frequency_of_the_test", 5) or 5) <= 0:
                # bench timing mode (freq < 0): no eval, in-loop or here —
                # an implicit final eval would pollute the timed call
                last_eval = {"test_acc": None}
            else:
                # resumed past the final round: evaluate the restored params
                stats = self._evaluate(self.params, self.fed.test["x"],
                                       self.fed.test["y"],
                                       self.fed.test["mask"])
                n = max(float(stats["count"]), 1.0)
                last_eval = {"test_acc": float(stats["correct"]) / n,
                             "test_loss": float(stats["loss_sum"]) / n}
        result = {"params": self.params, "history": self.history,
                  "wall_time_s": wall, "final_test_acc": last_eval["test_acc"],
                  "final_test_loss": last_eval.get("test_loss"),
                  "rounds": rounds}
        if self.dp.is_dp_enabled():
            result["dp_epsilon_spent"] = self.dp.get_epsilon_spent()
        return result
