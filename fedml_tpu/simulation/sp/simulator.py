"""Single-process golden simulator.

Parity target: the canonical SP FedAvg loop
(``simulation/sp/fedavg/fedavg_api.py:14`` — train loop :66-125, sampling
:127, ``_aggregate`` :144) generalized over every federated optimizer. This
backend is the *semantic reference*: the TPU mesh backend must match it
numerically (SURVEY §4: "same algorithm, three backends" is the strongest
testability idea in the reference — here it is a first-class parity test).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.algframe.types import ClientData, TrainHyper
from ...core.algframe.local_training import evaluate
from ...core.collectives import tree_weighted_average
from ..sampling import client_sampling

logger = logging.getLogger(__name__)


class SPSimulator:
    """Python round loop over jitted per-client local training."""

    def __init__(self, args, fed_dataset, bundle, optimizer, spec):
        self.args = args
        self.fed = fed_dataset
        self.bundle = bundle
        self.opt = optimizer
        self.spec = spec
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        init_rng, self.rng = jax.random.split(self.rng)
        sample = self.fed.train.x[0, 0]  # [bs, ...]
        self.params = bundle.init(init_rng, sample)
        self.server_state = self.opt.server_init(self.params)
        self.client_states = [self.opt.client_state_init(self.params)
                              for _ in range(self.fed.num_clients)]
        self._local_train = jax.jit(self.opt.local_train)
        self._server_update = jax.jit(self.opt.server_update)
        self._evaluate = jax.jit(lambda p, x, y, m: evaluate(spec, p, x, y, m))
        self.history: List[Dict[str, Any]] = []

    def _client_data(self, cid: int) -> ClientData:
        return jax.tree_util.tree_map(lambda a: a[cid], self.fed.train)

    def run(self, comm_round: Optional[int] = None) -> Dict[str, Any]:
        args = self.args
        rounds = comm_round if comm_round is not None else int(args.comm_round)
        hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                           epochs=int(args.epochs))
        t0 = time.time()
        for round_idx in range(rounds):
            sampled = client_sampling(round_idx, self.fed.num_clients,
                                      int(args.client_num_per_round))
            round_key = jax.random.fold_in(self.rng, round_idx)
            updates, weights, extras_list, states, metrics = [], [], [], [], []
            for cid in sampled:
                key = jax.random.fold_in(round_key, cid)
                out = self._local_train(
                    self.params, self.server_state, self.client_states[cid],
                    self._client_data(cid), key,
                    hyper.replace(round_idx=jnp.int32(round_idx)))
                updates.append(out.update)
                weights.append(out.weight)
                extras_list.append(out.extras)
                metrics.append(out.metrics)
                if self.opt.has_client_state:
                    self.client_states[cid] = out.client_state
            w = jnp.stack(weights)
            stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *updates)
            agg_update = tree_weighted_average(stacked, w)
            if extras_list[0]:
                stacked_ex = jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *extras_list)
                agg_extras = tree_weighted_average(stacked_ex, w)
            else:
                agg_extras = {}
            self.params, self.server_state = self._server_update(
                self.params, self.server_state, agg_update, agg_extras,
                jnp.int32(round_idx))
            rec: Dict[str, Any] = {"round": round_idx}
            tm = jax.tree_util.tree_map(lambda *xs: sum(xs), *metrics)
            cnt = max(float(tm["count"]), 1.0)
            rec["train_loss"] = float(tm["loss_sum"]) / cnt
            rec["train_acc"] = float(tm["correct"]) / cnt
            freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
            if round_idx % freq == 0 or round_idx == rounds - 1:
                stats = self._evaluate(self.params, self.fed.test["x"],
                                       self.fed.test["y"], self.fed.test["mask"])
                n = max(float(stats["count"]), 1.0)
                rec["test_acc"] = float(stats["correct"]) / n
                rec["test_loss"] = float(stats["loss_sum"]) / n
                logger.info("round %d: test_acc=%.4f test_loss=%.4f",
                            round_idx, rec["test_acc"], rec["test_loss"])
            self.history.append(rec)
        wall = time.time() - t0
        last_eval = next(r for r in reversed(self.history) if "test_acc" in r)
        return {"params": self.params, "history": self.history,
                "wall_time_s": wall, "final_test_acc": last_eval["test_acc"],
                "rounds": rounds}
