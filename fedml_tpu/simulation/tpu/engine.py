"""The TPU mesh simulator — an FL round as ONE jitted SPMD program.

This is the TPU-native endpoint of the reference's SP → MPI → NCCL
evolution (``simulation/nccl/base_framework/``): where the NCCL simulator
broadcasts the state-dict, trains scheduled clients per GPU, pre-scales by
the average weight and ``dist.reduce(SUM)``s to the server
(``Server.py:155-198``, ``LocalAggregator.py:69-96``, ``common.py:180-228``),
here the *entire round* — per-chip sequential client training (``lax.scan``
over schedule slots), weighted ``psum`` aggregation over the ``client`` mesh
axis, and the server transform — is a single ``jax.jit(shard_map(...))``
call. No host round-trips, no pickled state-dicts, collectives ride ICI.
"""

from __future__ import annotations

import logging
import time
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...constants import AXIS_CLIENT
from ...core.algframe.types import ClientData, TrainHyper
from ...core.algframe.local_training import evaluate
from ...core.collectives import (
    psum_tree, tree_scale, tree_zeros_like)
from ...core.mesh import build_mesh
from ..sampling import client_sampling, build_schedule

logger = logging.getLogger(__name__)
PyTree = Any


def _pad_clients(fed_train: ClientData, num_clients: int, n_devices: int):
    """Pad the stacked client axis to a multiple of n_devices with zero-weight
    dummy clients (they can be scheduled but contribute weight 0)."""
    cpd = -(-num_clients // n_devices)
    total = cpd * n_devices
    pad = total - num_clients
    if pad:
        def padleaf(a):
            pads = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return jnp.pad(a, pads)
        fed_train = jax.tree_util.tree_map(padleaf, fed_train)
    return fed_train, cpd, total


class TPUSimulator:
    """Parrot on a TPU mesh: clients sharded over the ``client`` axis,
    multiple clients per chip via the schedule tensor."""

    def __init__(self, args, fed_dataset, bundle, optimizer, spec,
                 mesh: Optional[Mesh] = None):
        self.args = args
        self.fed = fed_dataset
        self.bundle = bundle
        self.opt = optimizer
        self.spec = spec
        self.mesh = mesh if mesh is not None else build_mesh(
            getattr(args, "mesh_shape", None))
        self.n_devices = self.mesh.shape[AXIS_CLIENT]
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        init_rng, self.rng = jax.random.split(self.rng)

        # ---- place data: [num_clients, ...] -> [D, cpd, ...] sharded on D.
        train, self.cpd, self.total_clients = _pad_clients(
            fed_dataset.train, fed_dataset.num_clients, self.n_devices)
        self.client_sharding = NamedSharding(self.mesh, P(AXIS_CLIENT))
        self.repl_sharding = NamedSharding(self.mesh, P())

        def shard_clients(a):
            a = a.reshape((self.n_devices, self.cpd) + a.shape[1:])
            return jax.device_put(a, self.client_sharding)
        self.train_data = jax.tree_util.tree_map(shard_clients, train)

        sample = fed_dataset.train.x[0, 0]
        self.params = jax.device_put(bundle.init(init_rng, sample),
                                     self.repl_sharding)
        self.server_state = jax.device_put(self.opt.server_init(self.params),
                                           self.repl_sharding)
        cstate0 = self.opt.client_state_init(self.params)
        stacked_states = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a[None], (self.total_clients,) + a.shape),
            cstate0)
        self.client_states = jax.tree_util.tree_map(shard_clients, stacked_states)

        self._round_fn = self._build_round_fn()
        self._evaluate = jax.jit(lambda p, x, y, m: evaluate(spec, p, x, y, m))
        self.history: List[Dict[str, Any]] = []

    # ------------------------------------------------------------------
    def _build_round_fn(self):
        opt = self.opt
        cpd = self.cpd

        def round_body(params, server_state, local_data, local_states,
                       sched_idx, sched_active, round_key, hyper):
            """Runs per shard. shard_map hands blocks with a leading axis of
            size 1 for P(client)-sharded inputs — squeeze it, and restore it
            on the sharded output."""
            dev = jax.lax.axis_index(AXIS_CLIENT)
            local_data = jax.tree_util.tree_map(lambda a: a[0], local_data)
            local_states = jax.tree_util.tree_map(lambda a: a[0], local_states)
            sched_idx = sched_idx[0]
            sched_active = sched_active[0]

            zero_update = tree_zeros_like(params)
            zero_extras = opt.server_extras_zero(params)
            zero_metrics = {"loss_sum": jnp.float32(0), "correct": jnp.float32(0),
                            "count": jnp.float32(0)}

            def slot(carry, s):
                states, acc_u, acc_ex, acc_w, acc_m = carry
                li = sched_idx[s]
                active = sched_active[s]
                cdata = jax.tree_util.tree_map(lambda a: a[li], local_data)
                cstate = jax.tree_util.tree_map(lambda a: a[li], states)
                gcid = dev * cpd + li
                key = jax.random.fold_in(round_key, gcid)
                out = opt.local_train(params, server_state, cstate, cdata,
                                      key, hyper)
                w = out.weight * active
                acc_u = jax.tree_util.tree_map(
                    lambda acc, u: acc + u * w.astype(u.dtype), acc_u, out.update)
                acc_ex = jax.tree_util.tree_map(
                    lambda acc, e: acc + e * w.astype(e.dtype), acc_ex, out.extras)
                acc_w = acc_w + w
                acc_m = jax.tree_util.tree_map(
                    lambda acc, m: acc + m * active, acc_m, out.metrics)
                states = jax.tree_util.tree_map(
                    lambda a, n: a.at[li].set(
                        jnp.where(active > 0, n, a[li])), states, out.client_state)
                return (states, acc_u, acc_ex, acc_w, acc_m), None

            init = (local_states, zero_update, zero_extras,
                    jnp.float32(0), zero_metrics)
            (states, acc_u, acc_ex, acc_w, acc_m), _ = jax.lax.scan(
                slot, init, jnp.arange(sched_idx.shape[0]))

            # ---- the FedAvg collective: pre-scaled SUM-reduce over clients.
            total_w = jax.lax.psum(acc_w, AXIS_CLIENT)
            denom = jnp.maximum(total_w, 1e-12)
            agg_update = jax.tree_util.tree_map(
                lambda x: x / denom.astype(x.dtype), psum_tree(acc_u))
            agg_extras = jax.tree_util.tree_map(
                lambda x: x / denom.astype(x.dtype), psum_tree(acc_ex))
            metrics = psum_tree(acc_m)

            new_params, new_server_state = opt.server_update(
                params, server_state, agg_update, agg_extras, hyper.round_idx)
            states = jax.tree_util.tree_map(lambda a: a[None], states)
            return new_params, new_server_state, states, metrics

        shard_fn = jax.shard_map(
            round_body,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS_CLIENT), P(AXIS_CLIENT),
                      P(AXIS_CLIENT), P(AXIS_CLIENT), P(), P()),
            out_specs=(P(), P(), P(AXIS_CLIENT), P()),
            check_vma=False,
        )
        return jax.jit(shard_fn)

    # ------------------------------------------------------------------
    def run_round(self, round_idx: int, hyper: TrainHyper) -> Dict[str, float]:
        sampled = client_sampling(round_idx, self.fed.num_clients,
                                  int(self.args.client_num_per_round))
        max_slots = min(self.cpd, int(self.args.client_num_per_round))
        idx, active = build_schedule(sampled, self.n_devices, self.cpd,
                                     max_slots=max_slots)
        idx = jax.device_put(jnp.asarray(idx), self.client_sharding)
        active = jax.device_put(jnp.asarray(active), self.client_sharding)
        round_key = jax.random.fold_in(self.rng, round_idx)
        (self.params, self.server_state, self.client_states,
         metrics) = self._round_fn(
            self.params, self.server_state, self.train_data,
            self.client_states, idx, active, round_key,
            hyper.replace(round_idx=jnp.int32(round_idx)))
        return metrics

    def run(self, comm_round: Optional[int] = None) -> Dict[str, Any]:
        args = self.args
        rounds = comm_round if comm_round is not None else int(args.comm_round)
        hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                           epochs=int(args.epochs))
        t0 = time.time()
        for round_idx in range(rounds):
            metrics = self.run_round(round_idx, hyper)
            rec: Dict[str, Any] = {"round": round_idx}
            cnt = max(float(metrics["count"]), 1.0)
            rec["train_loss"] = float(metrics["loss_sum"]) / cnt
            rec["train_acc"] = float(metrics["correct"]) / cnt
            freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
            if round_idx % freq == 0 or round_idx == rounds - 1:
                stats = self._evaluate(self.params, self.fed.test["x"],
                                       self.fed.test["y"], self.fed.test["mask"])
                n = max(float(stats["count"]), 1.0)
                rec["test_acc"] = float(stats["correct"]) / n
                rec["test_loss"] = float(stats["loss_sum"]) / n
                logger.info("round %d: test_acc=%.4f", round_idx, rec["test_acc"])
            self.history.append(rec)
        wall = time.time() - t0
        last_eval = next(r for r in reversed(self.history) if "test_acc" in r)
        return {"params": self.params, "history": self.history,
                "wall_time_s": wall, "final_test_acc": last_eval["test_acc"],
                "rounds": rounds}
