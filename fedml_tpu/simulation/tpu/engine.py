"""The TPU mesh simulator — an FL round as ONE jitted SPMD program.

This is the TPU-native endpoint of the reference's SP → MPI → NCCL
evolution (``simulation/nccl/base_framework/``): where the NCCL simulator
broadcasts the state-dict, trains scheduled clients per GPU, pre-scales by
the average weight and ``dist.reduce(SUM)``s to the server
(``Server.py:155-198``, ``LocalAggregator.py:69-96``, ``common.py:180-228``),
here the *entire round* — per-chip sequential client training (``lax.scan``
over schedule slots), weighted ``psum`` aggregation over the ``client`` mesh
axis, and the server transform — is a single ``jax.jit(shard_map(...))``
call. No host round-trips, no pickled state-dicts, collectives ride ICI.
"""

from __future__ import annotations

import logging
import os
import time
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...constants import AXIS_CLIENT
from ...core.jax_compat import shard_map
from ...core.algframe.types import ClientData, TrainHyper
from ...core.algframe.local_training import evaluate
from ...core.collectives import (
    psum_tree, tree_scale, tree_zeros_like, vector_to_tree_like)
from ...core.dp import FedMLDifferentialPrivacy
from ...core import mlops
from ...core.obs import profiler as obs_profiler
from ...core.obs import roofline as obs_roofline
from ...core.obs import trace as obs_trace
from ...core.chaos import ChaosCrash, FaultLedger, FaultPlan
from ...core.checkpoint import RoundCheckpointer
from ...core.contribution import ContributionAssessorManager
from ...core.mesh import build_mesh
from ...core.security import FedMLAttacker, FedMLDefender
from ...core.security.defense import sharded as sharded_defense
from ...core.selection import SelectionManager, slot_placement
from ..sampling import build_schedule

# PRNG fold tags reserved for the DP noise streams (shared with the SP
# golden loop so LDP/CDP runs stay backend-parity-testable)
DP_LDP_FOLD = 999983
DP_CDP_FOLD = 999979
ATTACK_FOLD = 1000003
DEFENSE_FOLD = 1000033

logger = logging.getLogger(__name__)
PyTree = Any


def _pad_clients(fed_train: ClientData, num_clients: int, n_devices: int):
    """Pad the stacked client axis to a multiple of n_devices with zero-weight
    dummy clients (they can be scheduled but contribute weight 0)."""
    cpd = -(-num_clients // n_devices)
    total = cpd * n_devices
    pad = total - num_clients
    if pad:
        def padleaf(a):
            pads = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return jnp.pad(a, pads)
        fed_train = jax.tree_util.tree_map(padleaf, fed_train)
    return fed_train, cpd, total


def _maybe_enable_compile_cache(args) -> None:
    """Opt-in persistent XLA compilation cache (``compile_cache_dir``):
    repeat runs reuse the compiled fused round programs instead of paying
    the multi-second compile that dominates short-run wall time (the
    ``fedavg_digits_time_to_90pct_s`` bench is mostly compile). The knob is
    process-global (``jax.config``), so the first engine wins; failures are
    never fatal — a run without the cache is just slower."""
    path = getattr(args, "compile_cache_dir", None)
    if not path:
        return
    path = os.path.abspath(os.path.expanduser(str(path)))
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception as e:
        logger.warning("compile_cache_dir %s ignored (%s: %s)", path,
                       type(e).__name__, e)
        return
    # also cache fast-compiling programs (jax's defaults skip sub-second
    # compiles, which would exclude every small-model test program)
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except Exception:  # older jax: knob absent — dir alone still works
            pass
    try:
        # jax decides cache-used ONCE per task; any compile before this
        # point (data loading jits small programs) froze the verdict with
        # no dir configured — reset so it re-evaluates with ours
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:
        pass
    logger.info("persistent XLA compilation cache at %s", path)


# moved to core/security/defense (the cross-silo async server consumes it
# too); the old private name stays importable for existing callers/tests
from ...core.security.defense import verdict_from_info as _verdict_from_info


def _check_extras_compat(opt, params, dp, robust_mode: bool) -> None:
    """Optimizers whose extras ride the aggregation (SCAFFOLD delta_c, Mime
    full-batch grads, FedNova a_i) leak through side channels that LDP noise
    and robust defenses do not cover — combining them would silently void
    the privacy/robustness guarantee, so refuse loudly."""
    has_extras = bool(jax.tree_util.tree_leaves(opt.server_extras_zero(params)))
    if not has_extras:
        return
    if dp.is_dp_enabled():
        raise ValueError(
            f"{opt.name}: DP cannot cover this optimizer's extras (they "
            "would be aggregated un-noised and leak client data); use a "
            "stateless-extras optimizer (FedAvg/FedProx/FedOpt/FedDyn) "
            "with DP.")
    if robust_mode:
        raise ValueError(
            f"{opt.name}: robust aggregation defends only model updates; "
            "this optimizer's extras would bypass the defense. Use a "
            "stateless-extras optimizer (FedAvg/FedProx/FedOpt/FedDyn) "
            "with attacks/defenses.")


class TPUSimulator:
    """Parrot on a TPU mesh: clients sharded over the ``client`` axis,
    multiple clients per chip via the schedule tensor."""

    def __init__(self, args, fed_dataset, bundle, optimizer, spec,
                 mesh: Optional[Mesh] = None, server_aggregator=None):
        self.args = args
        # `round_mode: async_buffered` lives in the AsyncBufferedSimulator
        # subclass (simulation/tpu/async_engine.py); constructing the base
        # engine with it would silently run the sync barrier — refuse.
        from ...core.async_rounds import round_mode_from_args
        if (round_mode_from_args(args) == "async_buffered"
                and type(self) is TPUSimulator):
            raise ValueError(
                "round_mode: async_buffered needs the "
                "AsyncBufferedSimulator — build via FedMLRunner / "
                "run_simulation (they dispatch on round_mode), or import "
                "fedml_tpu.simulation.tpu.async_engine directly")
        self.server_aggregator = server_aggregator
        self.fed = fed_dataset
        self.bundle = bundle
        self.opt = optimizer
        self.spec = spec
        _maybe_enable_compile_cache(args)
        self.mesh = mesh if mesh is not None else build_mesh(
            getattr(args, "mesh_shape", None))
        self.n_devices = self.mesh.shape[AXIS_CLIENT]
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        init_rng, self.rng = jax.random.split(self.rng)

        # ---- place data: [num_clients, ...] -> [D, cpd, ...] sharded on D.
        train, self.cpd, self.total_clients = _pad_clients(
            fed_dataset.train, fed_dataset.num_clients, self.n_devices)
        self.client_sharding = NamedSharding(self.mesh, P(AXIS_CLIENT))
        self.repl_sharding = NamedSharding(self.mesh, P())

        # donate round inputs (params/server_state/client_states) back to
        # XLA: the round program's outputs replace them 1:1, so donation
        # lets the compiler alias in/out buffers and halves the model-state
        # HBM peak. Off-switch kept for debugging aliasing suspicions.
        self._donate = bool(getattr(args, "donate_buffers", True))
        mlops.install_compile_counter()
        self.dispatch_stats: Dict[str, Any] = {"dispatches": 0,
                                               "compiles": 0}
        # profiling plane (core/obs/profiler): OPT-IN host/device wall
        # split + per-round MFU at the dispatch seam. Off by default
        # because it blocks on dispatch results, defeating the async
        # dispatch overlap (and its FLOPs-model lowering would perturb
        # the compile-once counters tests pin).
        self._obs_profile = bool(getattr(args, "obs_profile_device",
                                         False))
        self._flops_per_round: Optional[float] = None
        # compute plane (core/obs/roofline): per-dispatch abstract-shape
        # signatures feed always-on recompile forensics; `obs_roofline`
        # additionally AOT-captures each program's per-op roofline +
        # collective-traffic record (one extra backend compile per
        # program — opt-in, like obs_profile_device, so the compile-once
        # invariants hold at default knobs)
        self._roofline = obs_roofline.DispatchTracker(
            enabled=bool(getattr(args, "obs_roofline",
                                 obs_roofline.default_enabled())),
            n_devices=self.n_devices,
            device=self.mesh.devices.flat[0])

        # chaos: seeded fault injection (off by default). Availability
        # faults ride the round programs as DATA (per-slot work fractions
        # next to the active mask) so injecting them never recompiles and
        # the schedule width stays canonical; `chaos_tolerance` picks the
        # aggregation semantics (renormalize over survivors vs dilute).
        self.chaos = FaultPlan.from_args(args)
        self.chaos_ledger = FaultLedger()
        self.chaos_tolerance = bool(getattr(args, "chaos_tolerance", True))
        # participant selection (core/selection): host-side policy whose
        # cohorts ride the jitted programs purely as schedule DATA.
        # Passive no-op at the default knobs (uniform strategy on the
        # legacy sampling stream = bit-identical schedules, nothing
        # observed, nothing checkpointed).
        self.selection = SelectionManager(args, fed_dataset.num_clients)
        if (self.selection.strategy_name == "reputation"
                and not self.chaos_tolerance):
            # benched clients ride the work-0 dropout channel, which only
            # RENORMALIZES under tolerance; with tolerance off their full
            # weight would stay in the denominator and every bench would
            # dilute the aggregate with zeros — strictly worse than not
            # benching, so refuse instead of silently degrading
            raise ValueError(
                "client_selection: reputation requires chaos_tolerance "
                "(benched clients are renormalized out of the weighted "
                "average); with chaos_tolerance: false they would dilute "
                "every round's aggregate instead")
        over = float(getattr(args, "chaos_over_sample", 0.0) or 0.0)
        base_n = int(args.client_num_per_round)
        self._base_n = base_n
        # static over-sampling: draw extra clients so the post-dropout
        # cohort still hits the configured size in expectation
        self._static_n = min(int(fed_dataset.num_clients),
                             int(np.ceil(base_n * (1.0 + max(over, 0.0)))))
        # _sample_n is the COHORT CAP — the canonical-width anchor. With
        # adaptive over-sampling the dropout posterior sizes each round's
        # draw between base_n and this cap; the CAP (not the draw) fixes
        # the compiled schedule width, so adaptivity never recompiles.
        if self.selection.adaptive:
            cap = float(getattr(args, "selection_max_over_sample", 1.0)
                        or 0.0)
            self._sample_n = min(
                int(fed_dataset.num_clients),
                int(np.ceil(base_n * (1.0 + max(cap, over, 0.0)))))
        else:
            self._sample_n = self._static_n

        self.attacker = FedMLAttacker(args)
        self.defender = FedMLDefender(args)
        self.dp = FedMLDifferentialPrivacy(args)
        if self.attacker.is_data_attack():
            from ..poisoning import poison_dataset
            poisoned = poison_dataset(self.fed, self.attacker)
            train = _pad_clients(poisoned.train, fed_dataset.num_clients,
                                 self.n_devices)[0]

        def shard_clients(a):
            a = a.reshape((self.n_devices, self.cpd) + a.shape[1:])
            return jax.device_put(a, self.client_sharding)
        self.train_data = jax.tree_util.tree_map(shard_clients, train)

        sample = fed_dataset.train.x[0, 0]
        self.params = jax.device_put(bundle.init(init_rng, sample),
                                     self.repl_sharding)
        self.server_state = jax.device_put(self.opt.server_init(self.params),
                                           self.repl_sharding)
        cstate0 = self.opt.client_state_init(self.params)
        stacked_states = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a[None], (self.total_clients,) + a.shape),
            cstate0)
        self.client_states = jax.tree_util.tree_map(shard_clients, stacked_states)

        self.contribution = ContributionAssessorManager(args)
        defended_mode = (self.attacker.is_model_attack()
                         or self.defender.is_defense_enabled())
        self.robust_mode = (defended_mode or self.contribution.enabled
                            or self.server_aggregator is not None)
        if (self.server_aggregator is not None
                and self.defender.is_defense_enabled()):
            logger.warning(
                "both a defense (%s) and a user ServerAggregator are "
                "configured: the defense takes precedence and the user "
                "aggregator is SKIPPED", self.defender.defense_type)
        _check_extras_compat(self.opt, self.params, self.dp, defended_mode)
        # ONE dispatch per defended round: every built-in defense now has a
        # sharded kernel, so the whole robust pipeline (train -> attack ->
        # defense -> CDP -> server transform) fuses into a single jitted
        # program — contribution assessment rides the same program (the
        # post-attack sharded matrix is an extra output; subset values are
        # evaluated on device, only [K] scores come host-side)
        self._true_d = int(sum(int(np.prod(l.shape)) for l in
                               jax.tree_util.tree_leaves(self.params)))
        self._d_pad = self._true_d + ((-self._true_d) % self.n_devices)
        self.robust_fused = self._resolve_robust_fused()
        if self.robust_fused and self.selection.adaptive:
            # the fused robust program's defense kernel works on a [K]
            # cohort whose SHAPE is baked into the compiled program
            # (rows/byz/ids stack per round inside the fused block): a
            # posterior-driven cohort-size flip would crash the stack
            # mid-block and recompile across blocks, breaking the
            # compile-once invariant — pin the cohort instead
            self.selection.pin_adaptive(
                "the fused robust program needs a constant [K] cohort "
                "shape (compile-once); use robust_fused: host for a "
                "per-round adaptive cohort under defenses")
            self._sample_n = self._static_n
        # defenses with cross-round state (foolsgold history, cclip
        # momentum, slsgd prev-global, cross_round prev updates) keep it as
        # a DEVICE-RESIDENT feature-sharded pytree: threaded through the
        # fused multi-round scan like client_states, donated, and saved in
        # checkpoints so crash-resume replays identical defense verdicts
        self._defense_state = None
        self._defense_state_specs: Dict[str, Any] = {}
        if (self.defender.is_defense_enabled() and self._use_sharded_defense()
                and sharded_defense.is_stateful(self.defender.defense_type)):
            self._defense_state_specs = sharded_defense.defense_state_spec(
                self.defender.defense_type, AXIS_CLIENT)
            self._defense_state = jax.tree_util.tree_map(
                lambda z, s: jax.device_put(z, NamedSharding(self.mesh, s)),
                sharded_defense.defense_state_init(
                    self.defender.defense_type, int(fed_dataset.num_clients),
                    self._d_pad),
                self._defense_state_specs)
        # perf knobs (ISSUE 16): both default-off, off = bit-identical
        # programs. Resolve BEFORE the round fns are built — the cores
        # close over the resolved values.
        self._relayout_quant = self._resolve_relayout_quant()
        self._slot_fold = self._resolve_slot_fold()
        self._round_fn = (self._build_robust_fn() if self.robust_fused
                          else self._build_collect_fn() if self.robust_mode
                          else self._build_round_fn())
        self._server_update = jax.jit(
            self.opt.server_update,
            donate_argnums=(0, 1) if self._donate else ())
        self._evaluate = jax.jit(lambda p, x, y, m: evaluate(spec, p, x, y, m))
        self.ckpt = RoundCheckpointer(
            getattr(args, "checkpoint_dir", None),
            int(getattr(args, "checkpoint_every_rounds", 0) or 0))
        if (self.ckpt.enabled and self.defender.is_defense_enabled()
                and sharded_defense.is_stateful(self.defender.defense_type)
                and self._defense_state is None):
            # host-kernel path (sharded_defense: false): the defender's
            # numpy state lives outside the checkpoint — a resumed run
            # restarts it cold and can diverge from the uninterrupted one
            logger.warning(
                "%s keeps cross-round state, but the host-kernel path "
                "does not checkpoint it — crash-resume restarts the "
                "defense state cold; use the default sharded path for "
                "checkpointed defense state", self.defender.defense_type)
        self.history: List[Dict[str, Any]] = []

    def _ckpt_state(self):
        st = {"params": self.params, "server_state": self.server_state,
              "client_states": self.client_states, "rng": self.rng,
              "dp": self.dp.state_dict()}
        if self._defense_state is not None:
            # cross-round defense state (e.g. the foolsgold similarity
            # history) must survive a crash, or a resumed run would score
            # clients against an amnesiac history and diverge from the
            # uninterrupted trajectory
            st["defense_state"] = self._defense_state
        if self.selection.stateful:
            # selection history (losses, dropout posterior, reputation):
            # strategies are pure functions of (seed, round, history), so
            # checkpointing the history is what makes crash-resume replay
            # IDENTICAL selections instead of re-selecting amnesiacally
            st["selection"] = self.selection.state_dict()
        return st

    # checkpoint leaves whose presence can legitimately flip between save
    # and resume (knob changes, version skew); dropped one at a time on
    # restore failure rather than making a valid checkpoint unloadable
    _OPTIONAL_CKPT_KEYS = ("selection", "defense_state")

    def _ckpt_latest(self):
        """Restore the newest checkpoint, tolerating optional leaves
        (``defense_state``, ``selection``) whose presence flips between
        save and resume: a checkpoint written before the feature was
        configured lacks the key, and orbax refuses a template with extra
        structure — retry without the leaf rather than failing (the
        subsystem then resumes from its cold-start state, loudly)."""
        template = self._ckpt_state()
        opts = [k for k in self._OPTIONAL_CKPT_KEYS if k in template]
        # least state lost first: full template, each optional leaf
        # dropped alone, then all of them
        candidates = [()] + [(k,) for k in opts]
        if len(opts) > 1:
            candidates.append(tuple(opts))
        last_err = None
        for drop in candidates:
            try:
                restored = self.ckpt.latest(
                    {k: v for k, v in template.items() if k not in drop})
            except Exception as e:
                last_err = e
                continue
            if drop and restored is not None:
                logger.warning(
                    "checkpoint restore succeeded only without the %s "
                    "leaf(s) (last error: %s: %s) — the corresponding "
                    "state resumes cold", "/".join(drop),
                    type(last_err).__name__, last_err)
            return restored
        raise last_err

    def _load_ckpt_state(self, st):
        self.params = jax.device_put(st["params"], self.repl_sharding)
        self.server_state = jax.device_put(st["server_state"],
                                           self.repl_sharding)
        self.client_states = jax.device_put(st["client_states"],
                                            self.client_sharding)
        self.rng = jnp.asarray(st["rng"])
        self.dp.load_state_dict(st["dp"])
        if "defense_state" in st:
            self._defense_state = jax.tree_util.tree_map(
                lambda a, s: jax.device_put(jnp.asarray(a),
                                            NamedSharding(self.mesh, s)),
                dict(st["defense_state"]), self._defense_state_specs)
        if "selection" in st and self.selection.stateful:
            self.selection.load_state_dict(st["selection"])

    # ------------------------------------------------------------------
    def _make_round_core(self):
        """The per-shard FL-round program, on SQUEEZED local blocks (no
        shard_map leading axis): shared by the single-round fn and the
        fused multi-round fn (which scans it — any drift would silently
        break their parity).

        Schedule slots run SEQUENTIALLY per chip (lax.scan) with full
        per-op batches. A client-lockstep vmap mode was built and measured
        in rounds 3-4 (scripts/vmap_vs_scan.py): XLA lowers
        per-client-weight batched convs to per-group execution with a
        fixed ~10-25 us/group overhead, and the mode LOST to scan on every
        shipped model — 16..64-channel ResNet-56 (r3) AND MXU-wide
        ResNet-18 (r4: 0.70x at chunk 8, 0.68x at chunk 4) — so it was
        deleted rather than kept as a footgun.

        ``client_slot_fold`` (ISSUE 16) is the mode that CAN win where
        vmap could not: optimizers that evaluate the SHARED global params
        (FedSGD) share one weight tensor across clients, so folding the
        [S] slot axis into the conv batch axis yields ordinary big-batch
        convs — no per-client-weight grouped-conv lowering — and S-times
        the per-op arithmetic intensity. See
        :meth:`_make_folded_round_core`."""
        if self._slot_fold:
            return self._make_folded_round_core()
        opt = self.opt
        cpd = self.cpd
        dp = self.dp
        tolerance = self.chaos_tolerance

        def core(params, server_state, local_data, local_states,
                 sched_idx, sched_active, sched_work, round_key, hyper):
            dev = jax.lax.axis_index(AXIS_CLIENT)
            zero_update = tree_zeros_like(params)
            zero_extras = opt.server_extras_zero(params)
            zero_metrics = {"loss_sum": jnp.float32(0), "correct": jnp.float32(0),
                            "count": jnp.float32(0)}

            def run_slot(states, li, active, ws):
                """Train one schedule slot. CDP soundness note: the
                per-client sensitivity bound (clip) must hold before
                aggregation even though noise is added centrally.

                Chaos semantics: ``ws`` (per-slot work fraction, data not
                shape) truncates the client's dynamic local-step count; a
                dropped client (ws == 0) runs zero steps and reports
                nothing — ``report`` masks its update, metrics and state
                write. At the default ws == 1.0 every product below
                multiplies by exactly 1.0, so the round is bit-identical
                to the chaos-free program."""
                cdata = jax.tree_util.tree_map(lambda a: a[li], local_data)
                cstate = jax.tree_util.tree_map(lambda a: a[li], states)
                gcid = dev * cpd + li
                key = jax.random.fold_in(round_key, gcid)
                out = opt.local_train(params, server_state, cstate, cdata,
                                      key, hyper.replace(work_scale=ws))
                upd = out.update
                if dp.is_local_dp_enabled():
                    upd = dp.add_local_noise(
                        upd, jax.random.fold_in(key, DP_LDP_FOLD))
                elif dp.is_global_dp_enabled():
                    upd = dp.clip_update(upd)
                report = active * (ws > 0).astype(active.dtype)
                w = out.weight * report
                # tolerance ON: dropped clients leave the denominator too
                # (renormalize over survivors). OFF: their scheduled
                # weight still counts, diluting the aggregate with zeros
                # — the failure mode the bench demonstrates.
                w_den = w if tolerance else out.weight * active
                return (upd, out.extras, w, w_den, report, out.metrics,
                        out.client_state)

            def finish(states, acc_u, acc_ex, acc_w, acc_m):
                """The FedAvg collective (pre-scaled SUM-reduce over
                clients) + central DP + server transform."""
                total_w = jax.lax.psum(acc_w, AXIS_CLIENT)
                denom = jnp.maximum(total_w, 1e-12)
                agg_update = jax.tree_util.tree_map(
                    lambda x: x / denom.astype(x.dtype), psum_tree(acc_u))
                agg_extras = jax.tree_util.tree_map(
                    lambda x: x / denom.astype(x.dtype), psum_tree(acc_ex))
                metrics = psum_tree(acc_m)
                if dp.is_global_dp_enabled():
                    agg_update = dp.add_global_noise(
                        agg_update, jax.random.fold_in(round_key,
                                                       DP_CDP_FOLD))
                new_params, new_server_state = opt.server_update(
                    params, server_state, agg_update, agg_extras,
                    hyper.round_idx)
                return new_params, new_server_state, states, metrics

            init = (local_states, zero_update, zero_extras,
                    jnp.float32(0), zero_metrics)

            def slot(carry, s):
                states, acc_u, acc_ex, acc_w, acc_m = carry
                li = sched_idx[s]
                active = sched_active[s]
                (upd, extras, w, w_den, report, mets,
                 new_cstate) = run_slot(states, li, active, sched_work[s])
                acc_u = jax.tree_util.tree_map(
                    lambda acc, u: acc + u * w.astype(u.dtype), acc_u, upd)
                acc_ex = jax.tree_util.tree_map(
                    lambda acc, e: acc + e * w.astype(e.dtype), acc_ex,
                    extras)
                acc_w = acc_w + w_den
                acc_m = jax.tree_util.tree_map(
                    lambda acc, m: acc + m * report, acc_m, mets)
                states = jax.tree_util.tree_map(
                    lambda a, n: a.at[li].set(
                        jnp.where(report > 0, n, a[li])), states,
                    new_cstate)
                # per-slot metrics ride out as scan ys: the selection
                # subsystem's per-CLIENT loss signal (the psum'd acc_m
                # sums them away). Masked like acc_m; devices keep their
                # own [S] slices, so the output stays client-sharded.
                slot_m = jax.tree_util.tree_map(lambda m: m * report, mets)
                return (states, acc_u, acc_ex, acc_w, acc_m), slot_m

            (states, acc_u, acc_ex, acc_w, acc_m), slot_mets = jax.lax.scan(
                slot, init, jnp.arange(sched_idx.shape[0]))
            return finish(states, acc_u, acc_ex, acc_w, acc_m) + (slot_mets,)

        return core

    def _make_folded_round_core(self):
        """Client-slot batch folding (ISSUE 16 tentpole part 2): the [S]
        schedule-slot axis joins the batch axis, so every conv in the
        round sees an S-times-larger batch — one pass replaces the slot
        scan. Exactness: FedSGD's aggregate is the sample-additive
        ``-Σ_i g_i`` over all reporting clients' samples, which a folded
        big-batch backward reproduces up to float summation order (the
        parity test pins rtol 1e-5). Slot masking (chaos drops, inactive
        padding slots) becomes sample masking: a non-reporting slot's
        sample masks are zeroed before the fold, so its gradients AND its
        metrics vanish from the sums just as the scan's ``report`` gate
        made them vanish per-slot.

        Same core signature/outputs as :meth:`_make_round_core`, so the
        single-round and fused multi-round builders consume it unchanged.
        Per-slot metrics cannot exist in a folded pass — ``slot_mets``
        is zeros, and :meth:`_resolve_slot_fold` refuses configs whose
        selection strategy consumes them."""
        opt = self.opt
        tolerance = self.chaos_tolerance

        def core(params, server_state, local_data, local_states,
                 sched_idx, sched_active, sched_work, round_key, hyper):
            # hyper.epochs/work_scale are unused: FedSGD-style folds are
            # epoch-free full-batch passes (the unfolded path ignores
            # them identically), and a chaos straggler's ws>0 still
            # reports its full gradient — only ws==0 drops it
            n_slots = sched_idx.shape[0]
            cdata = jax.tree_util.tree_map(lambda a: a[sched_idx],
                                           local_data)  # [S, nb, bs, ...]
            report = sched_active * (sched_work > 0).astype(
                sched_active.dtype)                                  # [S]

            def fold(a):  # [S, nb, bs, ...] -> [nb, S*bs, ...]
                a = jnp.moveaxis(a, 0, 1)
                return a.reshape((a.shape[0], n_slots * a.shape[2])
                                 + a.shape[3:])

            mask = cdata.mask * report.reshape(
                (n_slots,) + (1,) * (cdata.mask.ndim - 1)).astype(
                cdata.mask.dtype)
            w_slot = cdata.num_samples.astype(jnp.float32) * report
            folded = ClientData(x=fold(cdata.x), y=fold(cdata.y),
                                mask=fold(mask),
                                num_samples=jnp.sum(w_slot))
            acc_u, acc_m = opt.local_train_folded(params, folded, round_key)
            acc_w = jnp.sum(w_slot) if tolerance else jnp.sum(
                cdata.num_samples.astype(jnp.float32) * sched_active)
            total_w = jax.lax.psum(acc_w, AXIS_CLIENT)
            denom = jnp.maximum(total_w, 1e-12)
            agg_update = jax.tree_util.tree_map(
                lambda x: x / denom.astype(x.dtype), psum_tree(acc_u))
            zero_extras = opt.server_extras_zero(params)
            agg_extras = jax.tree_util.tree_map(
                lambda x: x / denom.astype(x.dtype), psum_tree(zero_extras))
            metrics = psum_tree(jax.tree_util.tree_map(
                lambda m: m.astype(jnp.float32), acc_m))
            new_params, new_server_state = opt.server_update(
                params, server_state, agg_update, agg_extras,
                hyper.round_idx)
            slot_mets = {k: jnp.zeros((n_slots,), jnp.float32)
                         for k in ("loss_sum", "correct", "count")}
            return (new_params, new_server_state, local_states, metrics,
                    slot_mets)

        return core

    def _resolve_slot_fold(self) -> bool:
        """``client_slot_fold`` knob: folding is only exact when every
        scheduled client evaluates the SHARED params and nothing
        downstream needs per-client updates — refuse loudly otherwise
        (a silent fallback would misreport the measured mode)."""
        pref = getattr(self.args, "client_slot_fold", False)
        if not pref or str(pref).lower() in ("false", "0", "no", "none",
                                             "off"):
            return False
        reasons = []
        if not getattr(self.opt, "folds_client_slots", False):
            reasons.append(
                f"optimizer {type(self.opt).__name__} runs per-client "
                "local trajectories (only optimizers declaring "
                "folds_client_slots=True, e.g. FedSGD, evaluate shared "
                "params on a sample-additive objective)")
        if self.robust_mode:
            reasons.append("robust mode needs the per-client update stack")
        if self.dp.is_local_dp_enabled() or self.dp.is_global_dp_enabled():
            reasons.append("DP clips/noises per-client updates")
        if self.selection.track:
            reasons.append("the selection strategy consumes per-slot "
                           "metrics, which a folded pass cannot produce")
        if reasons:
            raise ValueError(
                "client_slot_fold: this config cannot fold client slots "
                "into the batch axis: " + "; ".join(reasons))
        return True

    def _resolve_relayout_quant(self) -> Optional[str]:
        """``robust_relayout_quant`` knob -> None | 'int8' | 'bf16'. Only
        the fused robust path's ``all_to_all`` re-layout is quantized;
        on the host-dispatch path the knob warns and stays off (its
        re-layout rides jit out_shardings, not an explicit collective)."""
        pref = getattr(self.args, "robust_relayout_quant", None)
        if pref is None or str(pref).lower() in ("none", "off", "false",
                                                 "0", ""):
            return None
        mode = str(pref).lower()
        if mode == "bfloat16":
            mode = "bf16"
        if mode not in ("int8", "bf16"):
            raise ValueError(
                f"unknown robust_relayout_quant {pref!r} "
                "(none|int8|bf16)")
        if self.robust_mode and not self.robust_fused:
            logger.warning(
                "robust_relayout_quant: %s requested but the robust path "
                "is host-dispatch (robust_fused off) — the dense f32 "
                "re-layout is kept; use robust_fused: auto/fused for the "
                "quantized all_to_all", mode)
            return None
        return mode

    def _donate_args(self, *argnums: int):
        """donate_argnums for the round programs: params / server_state /
        client_states are replaced 1:1 by outputs of the same shape and
        sharding, so XLA can alias them in-place (client DATA is never
        donated — it is reused every round)."""
        return argnums if self._donate else ()

    # dispatches that execute no client training: profiled for wall/wait
    # but never converted to MFU (the FLOPs model is per training round)
    _NON_TRAINING_DISPATCHES = frozenset({"server_update"})

    def _ensure_flops_model(self, hyper) -> None:
        """Profiling plane: lower the FLOPs model once per run (it is the
        SAME ``round_cost_flops`` the bench reads, so MFU numbers stay
        comparable across BENCH rounds). Only under ``obs_profile_device``
        — the lowering compiles a throwaway program, which would otherwise
        trip the compile-once regression counters."""
        if self._obs_profile and self._flops_per_round is None:
            self._flops_per_round = self.round_cost_flops(hyper)

    def _traced(self, name: str, n_rounds: int, fn, *args):
        """Per-dispatch observability at the mlops seam: a ``dispatch``
        span + wall time of the dispatch call (host-side cost; device
        work is async) plus the process-wide XLA-compile delta it
        triggered — the recompile counter that makes shape instability
        loud instead of silent.

        With ``obs_profile_device`` the dispatch additionally blocks on
        its outputs to split wall time into host (enqueue) vs device-wait
        (compute tail), wraps the call in a ``jax.profiler`` annotation,
        and converts the FLOPs model into the per-round MFU gauge."""
        # compute plane: signature BEFORE the dispatch (donated buffers
        # die with it), capture BEFORE the counter snapshot (the opt-in
        # AOT compile must not be charged to the dispatch record)
        sig = obs_roofline.dispatch_signature(args)
        self._roofline.maybe_capture(name, fn, args, sig=sig)
        c0 = mlops.compile_count()
        with obs_trace.span("dispatch",
                            attrs={"name": name,
                                   "rounds": int(n_rounds)}) as sp:
            t0 = time.perf_counter()
            if self._obs_profile:
                with obs_profiler.trace_annotation(name):
                    out = fn(*args)
            else:
                out = fn(*args)
            wall = time.perf_counter() - t0
            wait = None
            if self._obs_profile:
                t1 = time.perf_counter()
                jax.block_until_ready(out)
                wait = time.perf_counter() - t1
                sp.set_attr("device_wait_s", round(wait, 6))
        compiles = mlops.compile_count() - c0
        self._roofline.observe(name, sig, compiles)
        if self._obs_profile:
            # the FLOPs model describes a TRAINING round: dispatches that
            # carry no training (the host-robust path's server_update is
            # a millisecond aggregation) must not be credited a round's
            # FLOPs — the resulting >1.0 MFU would overwrite the real
            # per-round gauge every round
            fpr = (self._flops_per_round
                   if name not in self._NON_TRAINING_DISPATCHES else None)
            obs_profiler.record_dispatch_profile(
                name, n_rounds, wall, wait, fpr,
                self.n_devices, compiles=compiles)
            obs_profiler.sample_hbm_peak_gb()
        self.dispatch_stats["dispatches"] += 1
        self.dispatch_stats["compiles"] += compiles
        mlops.log_dispatch(name, wall, rounds=n_rounds, compiles=compiles)
        return out

    def _build_round_fn(self):
        core = self._make_round_core()

        def round_body(params, server_state, local_data, local_states,
                       sched_idx, sched_active, sched_work, round_key,
                       hyper):
            """Runs per shard. shard_map hands blocks with a leading axis of
            size 1 for P(client)-sharded inputs — squeeze it, and restore it
            on the sharded output."""
            sq = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
            new_params, new_sstate, states, metrics, slot_mets = core(
                params, server_state, sq(local_data), sq(local_states),
                sched_idx[0], sched_active[0], sched_work[0], round_key,
                hyper)
            states = jax.tree_util.tree_map(lambda a: a[None], states)
            slot_mets = jax.tree_util.tree_map(lambda a: a[None], slot_mets)
            return new_params, new_sstate, states, metrics, slot_mets

        shard_fn = shard_map(
            round_body,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS_CLIENT), P(AXIS_CLIENT),
                      P(AXIS_CLIENT), P(AXIS_CLIENT), P(AXIS_CLIENT),
                      P(), P()),
            out_specs=(P(), P(), P(AXIS_CLIENT), P(), P(AXIS_CLIENT)),
            check_vma=False,
        )
        return jax.jit(shard_fn, donate_argnums=self._donate_args(0, 1, 3))

    def _build_fused_fn(self):
        """R rounds in ONE dispatch: an outer lax.scan over per-round
        schedules/keys inside the same shard_map — eliminates the
        per-round host dispatch (~120 ms through the tunneled chip, 4.4%
        of the flagship round; see BASELINE.md §3b) and every host
        round-trip between rounds. Non-robust mode only: the robust path
        hands the raw update matrix to the host defense pipeline each
        round by design."""
        core = self._make_round_core()

        def rounds_body(params, server_state, local_data, local_states,
                        sched_idxs, sched_actives, sched_works, round_keys,
                        round_idxs, hyper):
            sq = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
            local_data = sq(local_data)
            local_states = sq(local_states)
            sched_idxs = sched_idxs[:, 0]      # [R, 1, S] block -> [R, S]
            sched_actives = sched_actives[:, 0]
            sched_works = sched_works[:, 0]

            def one_round(carry, xs):
                params, server_state, states = carry
                idx_r, act_r, work_r, key_r, ridx_r = xs
                hyper_r = hyper.replace(round_idx=ridx_r)
                new_p, new_s, states, metrics, slot_m = core(
                    params, server_state, local_data, states,
                    idx_r, act_r, work_r, key_r, hyper_r)
                return (new_p, new_s, states), (metrics, slot_m)

            (params, server_state, states), (metrics, slot_mets) = \
                jax.lax.scan(
                    one_round, (params, server_state, local_states),
                    (sched_idxs, sched_actives, sched_works, round_keys,
                     round_idxs))
            states = jax.tree_util.tree_map(lambda a: a[None], states)
            slot_mets = jax.tree_util.tree_map(lambda a: a[:, None],
                                               slot_mets)  # [R, 1, S]
            return params, server_state, states, metrics, slot_mets

        shard_fn = shard_map(
            rounds_body,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS_CLIENT), P(AXIS_CLIENT),
                      P(None, AXIS_CLIENT), P(None, AXIS_CLIENT),
                      P(None, AXIS_CLIENT), P(), P(), P()),
            out_specs=(P(), P(), P(AXIS_CLIENT), P(),
                       P(None, AXIS_CLIENT)),
            check_vma=False,
        )
        return jax.jit(shard_fn, donate_argnums=self._donate_args(0, 1, 3))

    # ------------------------------------------------------------------
    def _make_collect_core(self, emit_extras_stack: bool = False):
        """Per-shard slot scan on SQUEEZED local blocks that keeps every
        scheduled client's raw update as a [S, ...] stack (plus the psum-
        ready extras/weight/metrics accumulators). Shared by the host-
        dispatch collect program, the fused robust program, and the async
        pour program — one training implementation, or their parity would
        silently drift.

        ``emit_extras_stack`` additionally returns the PER-SLOT extras
        stack (async buffering needs each client's own extras — SCAFFOLD
        delta_c — not the weighted sum; the flag is off for every sync
        path, so their scan ys are byte-identical to before)."""
        opt = self.opt
        cpd = self.cpd
        dp = self.dp
        tolerance = self.chaos_tolerance

        def core(params, server_state, local_data, local_states,
                 sched_idx, sched_active, sched_work, round_key, hyper):
            dev = jax.lax.axis_index(AXIS_CLIENT)
            zero_extras = opt.server_extras_zero(params)
            zero_metrics = {"loss_sum": jnp.float32(0), "correct": jnp.float32(0),
                            "count": jnp.float32(0)}

            def slot(carry, s):
                states, acc_ex, acc_w, acc_m = carry
                li = sched_idx[s]
                active = sched_active[s]
                ws = sched_work[s]
                cdata = jax.tree_util.tree_map(lambda a: a[li], local_data)
                cstate = jax.tree_util.tree_map(lambda a: a[li], states)
                gcid = dev * cpd + li
                key = jax.random.fold_in(round_key, gcid)
                out = opt.local_train(params, server_state, cstate, cdata,
                                      key, hyper.replace(work_scale=ws))
                upd = out.update
                if dp.is_local_dp_enabled():
                    upd = dp.add_local_noise(
                        upd, jax.random.fold_in(key, DP_LDP_FOLD))
                elif dp.is_global_dp_enabled():
                    # CDP soundness: the per-client sensitivity bound must
                    # hold before aggregation even though noise is central
                    upd = dp.clip_update(upd)
                # chaos: a dropped slot (ws == 0) contributes a zero-weight
                # row — the defense/aggregation downstream sees w == 0.
                # Default ws == 1.0 multiplies by exactly 1.0: bit-identical.
                report = active * (ws > 0).astype(active.dtype)
                w = out.weight * report
                w_den = w if tolerance else out.weight * active
                acc_ex = jax.tree_util.tree_map(
                    lambda acc, e: acc + e * w.astype(e.dtype), acc_ex, out.extras)
                acc_w = acc_w + w_den
                acc_m = jax.tree_util.tree_map(
                    lambda acc, m: acc + m * report, acc_m, out.metrics)
                states = jax.tree_util.tree_map(
                    lambda a, n: a.at[li].set(
                        jnp.where(report > 0, n, a[li])), states, out.client_state)
                # per-slot metrics for the selection subsystem (see
                # _make_round_core) — masked like acc_m, device-local
                slot_m = jax.tree_util.tree_map(
                    lambda m: m * report, out.metrics)
                ys = (upd, w, slot_m)
                if emit_extras_stack:
                    ys = ys + (out.extras,)
                return (states, acc_ex, acc_w, acc_m), ys

            init = (local_states, zero_extras, jnp.float32(0), zero_metrics)
            (states, acc_ex, acc_w, acc_m), ys = jax.lax.scan(
                slot, init, jnp.arange(sched_idx.shape[0]))
            upd_stack, w_stack, slot_mets = ys[:3]
            out = (upd_stack, w_stack, states, acc_ex, acc_w, acc_m,
                   slot_mets)
            return out + (ys[3],) if emit_extras_stack else out

        return core

    def _build_collect_fn(self):
        """Robust-mode round, host-dispatch flavor: instead of the psum
        fast path, emit every scheduled client's raw update (sharded
        [D, S, ...]) so the host can run the attack->defense pipeline on
        the full update matrix — the mesh equivalent of the reference
        ServerAggregator receiving the individual client models
        (``fedml_aggregator.py:58-78``). User ServerAggregators and
        ``sharded_defense: false`` configs take this path; every built-in
        defense (and contribution assessment) takes
        :meth:`_build_robust_fn` unless ``robust_fused`` says host."""
        core = self._make_collect_core()

        def round_body(params, server_state, local_data, local_states,
                       sched_idx, sched_active, sched_work, round_key,
                       hyper):
            sq = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
            (upd_stack, w_stack, states, acc_ex, acc_w, acc_m,
             slot_mets) = core(
                params, server_state, sq(local_data), sq(local_states),
                sched_idx[0], sched_active[0], sched_work[0], round_key,
                hyper)
            total_w = jax.lax.psum(acc_w, AXIS_CLIENT)
            denom = jnp.maximum(total_w, 1e-12)
            agg_extras = jax.tree_util.tree_map(
                lambda x: x / denom.astype(x.dtype), psum_tree(acc_ex))
            metrics = psum_tree(acc_m)
            states = jax.tree_util.tree_map(lambda a: a[None], states)
            upd_stack = jax.tree_util.tree_map(lambda a: a[None], upd_stack)
            slot_mets = jax.tree_util.tree_map(lambda a: a[None], slot_mets)
            return (upd_stack, w_stack[None], agg_extras, states, metrics,
                    slot_mets)

        shard_fn = shard_map(
            round_body,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS_CLIENT), P(AXIS_CLIENT),
                      P(AXIS_CLIENT), P(AXIS_CLIENT), P(AXIS_CLIENT),
                      P(), P()),
            out_specs=(P(AXIS_CLIENT), P(AXIS_CLIENT), P(), P(AXIS_CLIENT),
                       P(), P(AXIS_CLIENT)),
            check_vma=False,
        )
        # params/server_state are NOT donated here: the host still needs
        # them after this dispatch (defense ordering + _server_update)
        return jax.jit(shard_fn, donate_argnums=self._donate_args(3))

    # ------------------------------------------------------------------
    def _make_robust_core(self, emit_matrix: bool = False):
        """The per-shard FUSED robust round: slot-scan training, on-device
        model-attack injection, the feature-sharded defense (with its
        cross-round state threaded in and out), central-DP noise, and the
        server transform — the whole defended round with no host
        round-trip. The [D, S, ...] update stack never leaves device: an
        ``all_to_all`` turns rows-with-all-features into all-rows-with-
        a-feature-shard, landing bit-for-bit the same [K, D/n] layout (and
        attack/defense PRNG streams) as the host-dispatch sharded path in
        :meth:`_robust_aggregate`, so the two are parity-testable.

        ``emit_matrix`` additionally returns the POST-ATTACK sharded matrix
        and the [K] weights (what the defense saw) — the contribution
        assessor's input; off, XLA never materializes the extra output."""
        collect = self._make_collect_core()
        opt = self.opt
        dp = self.dp
        n_dev = self.n_devices
        defense_type = (self.defender.defense_type
                        if self.defender.is_defense_enabled() else "mean")
        hp = sharded_defense.DefenseHP.from_defender(self.defender)
        attack_type = (self.attacker.attack_type
                       if self.attacker.is_model_attack() else None)
        attack_scale = float(getattr(self.attacker, "attack_scale", 1.0))
        relayout_quant = self._relayout_quant

        def relayout(local_mat):
            """[S, D] rows -> [S*n, D/n] feature-sharded grid. The dense
            f32 ``all_to_all`` carries (g-1)/g of the matrix over the
            wire every round — the byte stream that dominates the
            weak-scaling leg. ``robust_relayout_quant`` shrinks it by
            riding PR 1's int8-wire idiom (utils/compression.py): int8
            rows with per-row f32 scales (4x fewer re-layout bytes; the
            [S] scale vector is a rounding error next to [S, D]) or a
            plain bf16 cast (2x). Rounding is DETERMINISTIC (not QSGD's
            stochastic round): every device dequantizes identical rows,
            so the defense verdict stays replicated. None = the original
            dense all_to_all, byte- and bit-identical."""
            if relayout_quant == "bf16":
                grid = jax.lax.all_to_all(
                    local_mat.astype(jnp.bfloat16), AXIS_CLIENT,
                    split_axis=1, concat_axis=0, tiled=True)
                return grid.astype(jnp.float32)
            if relayout_quant == "int8":
                amax = jnp.max(jnp.abs(local_mat), axis=1, keepdims=True)
                scale = jnp.where(amax > 0, amax, 1.0) / 127.0   # [S, 1]
                q = jnp.round(local_mat / scale).astype(jnp.int8)
                qgrid = jax.lax.all_to_all(q, AXIS_CLIENT, split_axis=1,
                                           concat_axis=0, tiled=True)
                # tiled all_gather rows land source-device-major, exactly
                # like the tiled all_to_all's concat axis — scales align
                scales = jax.lax.all_gather(scale[:, 0], AXIS_CLIENT,
                                            tiled=True)
                return qgrid.astype(jnp.float32) * scales[:, None]
            return jax.lax.all_to_all(local_mat, AXIS_CLIENT, split_axis=1,
                                      concat_axis=0, tiled=True)

        def core(params, server_state, local_data, local_states,
                 sched_idx, sched_active, sched_work, rows, byz_mask, ids,
                 dstate, round_key, hyper):
            (upd_stack, w_stack, states, acc_ex, acc_w, acc_m,
             slot_mets) = collect(
                params, server_state, local_data, local_states,
                sched_idx, sched_active, sched_work, round_key, hyper)
            # [S, ...] stack -> [S, D] f32 local matrix: same leaf order
            # and dtype cast as stack_to_matrix on the host path
            leaves = jax.tree_util.tree_leaves(upd_stack)
            n_slots = leaves[0].shape[0]
            local_mat = jnp.concatenate(
                [jnp.reshape(l, (n_slots, -1)).astype(jnp.float32)
                 for l in leaves], axis=1)
            true_d = local_mat.shape[1]
            pad = (-true_d) % n_dev
            if pad:  # even feature shards, as on the host path
                local_mat = jnp.pad(local_mat, ((0, 0), (0, pad)))
            grid = relayout(local_mat)
            mat_s = grid[rows]          # [K, D/n] in sampled-client order
            w = jax.lax.all_gather(w_stack, AXIS_CLIENT, tiled=True)[rows]
            if attack_type is not None:
                mat_s = sharded_defense._apply_attack_shard(
                    attack_type, mat_s, byz_mask,
                    jax.random.fold_in(round_key, ATTACK_FOLD),
                    attack_scale, AXIS_CLIENT)
            # verdict: the defense's [K] per-client effective inclusion —
            # replicated and tiny, emitted so reputation updates cost
            # zero extra dispatches
            vec_s, new_dstate, verdict = \
                sharded_defense.defend_shard_stateful(
                    mat_s, w, AXIS_CLIENT, defense_type, hp, state=dstate,
                    ids=ids,
                    key=jax.random.fold_in(round_key, DEFENSE_FOLD),
                    true_d=true_d)
            vec = jax.lax.all_gather(vec_s, AXIS_CLIENT, tiled=True)[:true_d]
            agg_update = vector_to_tree_like(vec, params)
            if dp.is_global_dp_enabled():
                agg_update = dp.add_global_noise(
                    agg_update, jax.random.fold_in(round_key, DP_CDP_FOLD))
            total_w = jax.lax.psum(acc_w, AXIS_CLIENT)
            denom = jnp.maximum(total_w, 1e-12)
            agg_extras = jax.tree_util.tree_map(
                lambda x: x / denom.astype(x.dtype), psum_tree(acc_ex))
            metrics = psum_tree(acc_m)
            new_params, new_sstate = opt.server_update(
                params, server_state, agg_update, agg_extras,
                hyper.round_idx)
            out = (new_params, new_sstate, states, new_dstate, metrics,
                   slot_mets, verdict)
            return out + (mat_s, w) if emit_matrix else out

        return core

    def _build_robust_fn(self):
        """ONE dispatch per defended round (vs three-plus-host-work on the
        host-dispatch path). With contribution assessment enabled the same
        program also emits the post-attack sharded update matrix."""
        emit = self.contribution.enabled
        core = self._make_robust_core(emit_matrix=emit)
        state_specs = self._defense_state_specs

        def round_body(params, server_state, local_data, local_states,
                       sched_idx, sched_active, sched_work, rows, byz_mask,
                       ids, dstate, round_key, hyper):
            sq = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
            out = core(
                params, server_state, sq(local_data), sq(local_states),
                sched_idx[0], sched_active[0], sched_work[0], rows,
                byz_mask, ids, dstate, round_key, hyper)
            (new_params, new_sstate, states, new_dstate, metrics,
             slot_mets, verdict) = out[:7]
            states = jax.tree_util.tree_map(lambda a: a[None], states)
            slot_mets = jax.tree_util.tree_map(lambda a: a[None], slot_mets)
            res = (new_params, new_sstate, states, new_dstate, metrics,
                   slot_mets, verdict)
            return res + out[7:] if emit else res

        out_specs = (P(), P(), P(AXIS_CLIENT), state_specs, P(),
                     P(AXIS_CLIENT), P())
        if emit:
            out_specs = out_specs + (P(None, AXIS_CLIENT), P())
        shard_fn = shard_map(
            round_body,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS_CLIENT), P(AXIS_CLIENT),
                      P(AXIS_CLIENT), P(AXIS_CLIENT), P(AXIS_CLIENT),
                      P(), P(), P(), state_specs, P(), P()),
            out_specs=out_specs,
            check_vma=False,
        )
        # contribution assessment evaluates coalitions around the ROUND-
        # START params after the dispatch returns, so params must not be
        # donated then (the assessor would read a deleted buffer)
        donate = (1, 3, 10) if emit else (0, 1, 3, 10)
        return jax.jit(shard_fn, donate_argnums=self._donate_args(*donate))

    def _build_robust_fused_fn(self):
        """R defended rounds in ONE dispatch: the robust core under an
        outer ``lax.scan``, mirroring :meth:`_build_fused_fn` — defended
        runs amortize the same ~120 ms dispatch constant (BASELINE.md §3b)
        the undefended fused path already eliminates. Cross-round defense
        state rides the scan CARRY (foolsgold's round-R history feeds round
        R+1 inside the same dispatch), sampled ids ride the xs."""
        core = self._make_robust_core()
        state_specs = self._defense_state_specs

        def rounds_body(params, server_state, local_data, local_states,
                        sched_idxs, sched_actives, sched_works, rows_r,
                        byz_r, ids_r, dstate, round_keys, round_idxs,
                        hyper):
            sq = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
            local_data = sq(local_data)
            local_states = sq(local_states)
            sched_idxs = sched_idxs[:, 0]      # [R, 1, S] block -> [R, S]
            sched_actives = sched_actives[:, 0]
            sched_works = sched_works[:, 0]

            def one_round(carry, xs):
                params, server_state, states, dstate = carry
                idx_r, act_r, work_r, rows_i, byz_i, ids_i, key_r, ridx_r \
                    = xs
                hyper_r = hyper.replace(round_idx=ridx_r)
                new_p, new_s, states, dstate, metrics, slot_m, verdict = \
                    core(params, server_state, local_data, states,
                         idx_r, act_r, work_r, rows_i, byz_i, ids_i,
                         dstate, key_r, hyper_r)
                return ((new_p, new_s, states, dstate),
                        (metrics, slot_m, verdict))

            ((params, server_state, states, dstate),
             (metrics, slot_mets, verdicts)) = jax.lax.scan(
                one_round, (params, server_state, local_states, dstate),
                (sched_idxs, sched_actives, sched_works, rows_r, byz_r,
                 ids_r, round_keys, round_idxs))
            states = jax.tree_util.tree_map(lambda a: a[None], states)
            slot_mets = jax.tree_util.tree_map(lambda a: a[:, None],
                                               slot_mets)  # [R, 1, S]
            return (params, server_state, states, dstate, metrics,
                    slot_mets, verdicts)  # metrics/verdicts: [R]

        shard_fn = shard_map(
            rounds_body,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS_CLIENT), P(AXIS_CLIENT),
                      P(None, AXIS_CLIENT), P(None, AXIS_CLIENT),
                      P(None, AXIS_CLIENT), P(), P(), P(), state_specs,
                      P(), P(), P()),
            out_specs=(P(), P(), P(AXIS_CLIENT), state_specs, P(),
                       P(None, AXIS_CLIENT), P()),
            check_vma=False,
        )
        return jax.jit(shard_fn,
                       donate_argnums=self._donate_args(0, 1, 3, 10))

    def _resolve_robust_fused(self) -> bool:
        """``robust_fused`` knob: auto (default) fuses whenever the
        sharded defense path applies (every built-in defense) OR the run
        is contribution-only (no defense — the fused program aggregates
        with the ``mean`` kernel and emits the sharded matrix for the
        on-device assessor); ``host`` keeps the 3-dispatch
        host-orchestrated pipeline; ``fused`` demands fusion and refuses
        configs that cannot fuse (user ServerAggregators,
        ``sharded_defense: false``)."""
        pref = str(getattr(self.args, "robust_fused", "auto")
                   or "auto").lower()
        if pref in ("false", "0", "no", "host"):
            if self.robust_mode:
                self._log_host_path("robust_fused: %r" % pref)
            return False
        ok = self.robust_mode and (self._use_sharded_defense()
                                   or self._fusable_without_defense())
        if pref in ("true", "1", "yes", "fused") and self.robust_mode \
                and not ok:
            raise ValueError(
                "robust_fused: this config cannot fuse the robust round "
                "(it needs the sharded defense path — no user "
                "ServerAggregator, sharded_defense not forced off); use "
                "robust_fused: auto or host")
        return ok

    def _fusable_without_defense(self) -> bool:
        """Contribution-only robust runs (no defense, no model attack, no
        user aggregator) fuse via the ``mean`` kernel: the round is the
        plain weighted average, plus the sharded matrix output the
        assessor consumes."""
        return (self.contribution.enabled
                and not self.defender.is_defense_enabled()
                and not self.attacker.is_model_attack()
                and self.server_aggregator is None)

    def _log_host_path(self, reason: str) -> None:
        """Say ONCE which config knob forced the host robust path — a
        silently-slow defended run is a support ticket, a logged one is a
        config fix."""
        if not getattr(self, "_host_path_logged", False):
            self._host_path_logged = True
            logger.info("robust rounds take the HOST-dispatch path: %s",
                        reason)

    def _use_sharded_defense(self) -> bool:
        """Sharded (feature-parallel, no host materialization) defense is
        the DEFAULT whenever a defense is configured — every built-in
        defense now has a sharded kernel; set ``sharded_defense: false``
        to force the host kernels. User ServerAggregators need the
        host-ordered full matrix, so they keep the host path. Contribution
        assessment no longer disqualifies the sharded path: it runs on the
        sharded matrix the round program already emits."""
        from ...core.security.defense import sharded
        if not self.defender.is_defense_enabled():
            return False
        pref = str(getattr(self.args, "sharded_defense", "auto")
                   or "auto").lower()
        if pref in ("false", "0", "no", "host"):
            self._log_host_path("sharded_defense: %r forces the host "
                                "kernels" % pref)
            return False
        if not sharded.supports_sharded(self.defender.defense_type):
            # unreachable for today's DEFENSE_TYPES (all sharded) — kept
            # for defenses added without a sharded kernel
            self._log_host_path(
                "defense_type %r has no sharded kernel (sharded: %s)"
                % (self.defender.defense_type,
                   sharded.sharded_defense_names()))
            return False
        if self.server_aggregator is not None:
            self._log_host_path("a user ServerAggregator consumes the "
                                "host-ordered update matrix")
            return False
        return True

    def _robust_rows(self, sampled, n_slots: int):
        """Map sampled client ids onto the device-major [D*S] update grid:
        ``rows[k]`` is client k's row, ``byz[k]`` its byzantine-mask entry
        (zeros when no model attack is configured). Shared by the host-
        dispatch and fused robust paths — identical ordering is what makes
        their defense verdicts comparable client-for-client. Derived from
        the ONE slot-placement loop (``slot_placement``) so update rows,
        schedules, and the selection subsystem's per-slot bookkeeping can
        never drift apart."""
        rows = [d * n_slots + s for _, d, s in
                slot_placement(sampled, self.n_devices, self.cpd)]
        ids = np.asarray(sampled)
        if self.attacker.is_model_attack():
            byz = np.asarray(self.attacker.byzantine_mask(ids), np.float32)
        else:
            byz = np.zeros(len(sampled), np.float32)
        return np.asarray(rows, np.int32), byz

    def _robust_aggregate(self, upd_stack, w_stack, sampled, n_slots,
                          round_key, round_idx):
        """Order the [D, S] update grid into sampled-client order, run
        attacker/defender, return the aggregate update pytree (matches the
        SP golden path client-for-client)."""
        from ...core.security.defense import stack_to_matrix
        from ...core.security.defense.robust_agg import weighted_mean
        from ...core.security.defense import sharded
        rows_np, _ = self._robust_rows(sampled, n_slots)
        rows = jnp.asarray(rows_np)
        ids = np.asarray(sampled)

        if self._use_sharded_defense():
            # LLM-scale path: flatten + row-order INTO a feature-sharded
            # layout (out_shardings makes XLA emit the all-to-all; the
            # replicated [K, D] matrix never exists), inject the model
            # attack on-device on the shards, defend, all without a host
            # round-trip. The jitted builders are cached on the instance —
            # fresh closures per round would recompile every round.
            if not hasattr(self, "_to_matrix_fn"):
                mat_sharding = NamedSharding(self.mesh,
                                             P(None, AXIS_CLIENT))
                n_dev = self.n_devices

                def to_matrix(upd_stack, rows):
                    flat = jax.tree_util.tree_map(
                        lambda a: a.reshape((-1,) + a.shape[2:]), upd_stack)
                    m = stack_to_matrix(flat)[rows]
                    pad = (-m.shape[1]) % n_dev  # even feature shards
                    return jnp.pad(m, ((0, 0), (0, pad))) if pad else m

                self._to_matrix_fn = jax.jit(to_matrix,
                                             out_shardings=mat_sharding)
                self._row_select_fn = jax.jit(
                    lambda ws, r: ws.reshape(-1)[r])

            true_d = int(np.sum([np.prod(l.shape[2:]) for l in
                                 jax.tree_util.tree_leaves(upd_stack)]))
            mat = self._to_matrix_fn(upd_stack, rows)
            w = self._row_select_fn(w_stack, rows)
            attack_type = (self.attacker.attack_type
                           if self.attacker.is_model_attack() else None)
            byz_mask = (jnp.asarray(self.attacker.byzantine_mask(ids),
                                    jnp.float32)
                        if attack_type else None)
            stateful = self._defense_state is not None
            out = sharded.defend_matrix_sharded(
                self.mesh, AXIS_CLIENT, mat, w,
                self.defender.defense_type,
                hp=sharded.DefenseHP.from_defender(self.defender),
                attack_type=attack_type,
                attack_scale=getattr(self.attacker, "attack_scale", 1.0),
                byz_mask=byz_mask,
                attack_key=jax.random.fold_in(round_key, ATTACK_FOLD),
                defense_key=jax.random.fold_in(round_key, DEFENSE_FOLD),
                state=self._defense_state,
                ids=jnp.asarray(ids, jnp.int32),
                return_matrix=self.contribution.enabled,
                return_verdict=self.selection.track)
            if not isinstance(out, tuple):
                out = (out,)
            vec = out[0]
            pos = 1
            if stateful:
                self._defense_state = out[pos]
                pos += 1
            if self.contribution.enabled:
                # the assessor must see the POST-ATTACK matrix the defense
                # saw, still feature-sharded — scores come from the same
                # on-device kernel as the fused path (self.params is still
                # the round-start model here: _server_update runs later)
                self._assess_contribution_fused(out[pos], w, sampled,
                                                round_idx, self.params)
                pos += 1
            if self.selection.track:
                self.selection.note_results(
                    round_idx, sampled,
                    slot_placement(sampled, self.n_devices, self.cpd),
                    verdict=out[pos])
            agg = vector_to_tree_like(vec[:true_d], self.params)
            if self.dp.is_global_dp_enabled():
                agg = self.dp.add_global_noise(
                    agg, jax.random.fold_in(round_key, DP_CDP_FOLD))
            return agg

        flat = jax.tree_util.tree_map(
            lambda a: a.reshape((-1,) + a.shape[2:]), upd_stack)
        mat = stack_to_matrix(flat)[rows]
        w = w_stack.reshape(-1)[rows]
        if self.attacker.is_model_attack():
            mat = self.attacker.poison_updates(
                mat, ids, jax.random.fold_in(round_key, ATTACK_FOLD))
        if self.defender.is_defense_enabled():
            vec, info = self.defender.defend_matrix(
                mat, w, jax.random.fold_in(round_key, DEFENSE_FOLD), ids)
            if self.selection.track:
                verdict = _verdict_from_info(info, len(sampled))
                if verdict is not None:
                    self.selection.note_results(
                        round_idx, sampled,
                        slot_placement(sampled, self.n_devices, self.cpd),
                        verdict=verdict)
        elif self.server_aggregator is not None:
            # user-pluggable hook chain (reference server_aggregator.py
            # :44/:75/:90) on the stacked matrix
            mat2, w2 = self.server_aggregator.on_before_aggregation(
                mat, jnp.asarray(w, jnp.float32))
            vec = self.server_aggregator.on_after_aggregation(
                self.server_aggregator.aggregate(mat2, w2))
        else:
            vec = weighted_mean(mat, jnp.asarray(w, jnp.float32))
        if self.contribution.enabled:
            self._assess_contribution(mat, w, sampled, round_idx)
        agg = vector_to_tree_like(vec, self.params)
        if self.dp.is_global_dp_enabled():
            agg = self.dp.add_global_noise(
                agg, jax.random.fold_in(round_key, DP_CDP_FOLD))
        return agg

    def _assess_contribution_fused(self, mat, w, sampled, round_idx,
                                   params):
        """LOO / GTG-Shapley on the FEATURE-SHARDED update matrix: the
        subset-value kernel does the masked weighted average on the shards,
        gathers only the [D] candidate vector (model-sized, same as the
        params the eval needs anyway), and evaluates on a held-out eval set
        SHARDED over the device axis — one jitted program per coalition
        query, only the final [K] scores cross to the host. This is what
        lets ``contribution.enabled`` ride the fused robust round instead
        of forcing the 3-dispatch host path. ``params`` must be the
        ROUND-START model (host-path semantics: coalition values measure
        what subsets of this round's updates would have produced), which
        is why the contribution-enabled robust program does not donate its
        params input."""
        if not hasattr(self, "_contrib_value_fn"):
            spec = self.spec
            true_d = self._true_d
            test = self.fed.test
            nb = int(test["x"].shape[0])
            pad = (-nb) % self.n_devices

            def shard_batches(a):
                a = jnp.asarray(a)
                if pad:  # padded batches carry mask 0: they count nothing
                    a = jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
                return jax.device_put(
                    a, NamedSharding(self.mesh, P(AXIS_CLIENT)))

            self._contrib_test = tuple(
                shard_batches(test[k]) for k in ("x", "y", "mask"))

            def value_body(params, mat_s, weights, mask, x_s, y_s, m_s):
                wm = weights * mask
                denom = jnp.maximum(jnp.sum(wm), 1e-12)
                vec_s = jnp.einsum("k,kd->d", wm / denom, mat_s)
                vec = jax.lax.all_gather(vec_s, AXIS_CLIENT,
                                         tiled=True)[:true_d]
                cand = jax.tree_util.tree_map(
                    jnp.add, params, vector_to_tree_like(vec, params))
                stats = evaluate(spec, cand, x_s, y_s, m_s)
                stats = {k: jax.lax.psum(v, AXIS_CLIENT)
                         for k, v in stats.items()}
                return stats["correct"] / jnp.maximum(stats["count"], 1.0)

            self._contrib_value_fn = jax.jit(shard_map(
                value_body, mesh=self.mesh,
                in_specs=(P(), P(None, AXIS_CLIENT), P(), P(),
                          P(AXIS_CLIENT), P(AXIS_CLIENT), P(AXIS_CLIENT)),
                out_specs=P(),
                check_vma=False,
            ))
        tx, ty, tm = self._contrib_test
        w32 = jnp.asarray(w, jnp.float32)
        vfn = lambda mask: float(self._contrib_value_fn(
            params, mat, w32, jnp.asarray(mask, jnp.float32), tx, ty, tm))
        self.contribution.assess_values(vfn, len(sampled),
                                        client_ids=list(sampled),
                                        round_idx=round_idx)

    def _assess_contribution(self, mat, w, sampled, round_idx):
        """Shapley/LOO over the flattened update matrix — the subset-value
        function works in vector space and unflattens per evaluation.

        Size guard: Shapley evaluates O(2^K or MC-samples) candidate
        models, each a host-materialized [D] vector; on an LLM-sized
        update matrix that OOMs the host. Refuse loudly above 2 GiB
        rather than dying mid-round."""
        nbytes = int(mat.size) * mat.dtype.itemsize
        if nbytes > (2 << 30):
            logger.error(
                "contribution assessment skipped: update matrix is %.1f "
                "GiB (> 2 GiB host guard) — Shapley/LOO on a model this "
                "size would OOM the host; use a smaller model or disable "
                "contribution assessment", nbytes / 2**30)
            return
        from ...core.collectives import tree_flatten_to_vector
        spec, fed, params = self.spec, self.fed, self.params
        pvec = tree_flatten_to_vector(params)

        def eval_fn(p):
            cand = vector_to_tree_like(p["v"], params)
            stats = evaluate(spec, cand, fed.test["x"], fed.test["y"],
                             fed.test["mask"])
            return stats["correct"] / jnp.maximum(stats["count"], 1.0)

        self.contribution.assess({"v": pvec}, {"v": mat}, w, eval_fn,
                                 client_ids=sampled, round_idx=round_idx)

    def round_cost_flops(self, hyper: TrainHyper) -> float:
        """FLOPs one round of this workload executes (all devices), for the
        bench's MFU metric. XLA's cost analysis counts a loop body ONCE
        regardless of trip count, so instead of lowering the whole round
        program we cost a single loop-free fwd+bwd batch step and multiply
        by the number of REAL local steps a round runs. On hetero partitions
        clients are padded to the largest client's batch count, and the
        dynamic local loop (``run_local_sgd``) skips padded batches — so the
        step count here is the mask-derived mean real batches per client,
        not the padded shape, or MFU would count padding as useful work."""
        try:
            batch = {
                "x": jnp.zeros_like(self.fed.train.x[0, 0]),
                "y": jnp.zeros_like(self.fed.train.y[0, 0]),
                "mask": jnp.zeros_like(self.fed.train.mask[0, 0]),
            }
            rng = jax.random.PRNGKey(0)

            def one_step(params, batch, rng):
                (_, aux), grads = jax.value_and_grad(
                    self.spec.loss, has_aux=True)(params, batch, rng)
                return grads

            compiled = jax.jit(one_step).lower(
                self.params, batch, rng).compile()
            cost = compiled.cost_analysis()
            if isinstance(cost, list):
                cost = cost[0] if cost else {}
            per_batch = float(cost.get("flops", 0.0) or 0.0)
            n_sampled = int(self.args.client_num_per_round)
            mask = np.asarray(self.fed.train.mask)  # [clients, batches, bs]
            real_batches = mask.reshape(mask.shape[0], mask.shape[1], -1)
            mean_real = float(np.mean(np.sum(
                np.any(real_batches > 0, axis=-1), axis=-1)))
            steps = n_sampled * int(hyper.epochs) * mean_real
            # chaos: dropped clients run zero steps, stragglers a fraction
            # — scale by the plan's mean work fraction or MFU under
            # injection would count never-executed steps as useful work
            if self.chaos.injects_availability:
                steps *= self.chaos.expected_work_fraction
            return per_batch * steps
        except Exception as e:
            # never crash a bench over cost analysis — but a silent 0.0
            # zeroes the MFU column with no trace, so say why ONCE
            if not getattr(self, "_flops_cost_warned", False):
                self._flops_cost_warned = True
                logger.warning(
                    "round_cost_flops failed (MFU will report 0): %s: %s",
                    type(e).__name__, e, exc_info=True)
            return 0.0

    def run_round(self, round_idx: int, hyper: TrainHyper) -> Dict[str, float]:
        self._ensure_flops_model(hyper)
        with obs_trace.span("round", root=True,
                            attrs={"role": "engine",
                                   "round_idx": int(round_idx)}):
            return self._run_round_traced(round_idx, hyper)

    def _run_round_traced(self, round_idx: int,
                          hyper: TrainHyper) -> Dict[str, float]:
        pad_to = self._canonical_width() if self.robust_fused else None
        with obs_trace.span("host.input",
                            attrs={"round_idx": int(round_idx)}):
            sampled, (idx, active, work), faults = self._schedule_for(
                round_idx, pad_to=pad_to)
            self._ledger_round(round_idx, sampled, active, work, faults)
            idx = jax.device_put(jnp.asarray(idx), self.client_sharding)
            active = jax.device_put(jnp.asarray(active),
                                    self.client_sharding)
            work = jax.device_put(jnp.asarray(work), self.client_sharding)
        round_key = jax.random.fold_in(self.rng, round_idx)
        hyper_r = hyper.replace(round_idx=jnp.int32(round_idx))
        placement = slot_placement(sampled, self.n_devices, self.cpd)
        if self.robust_fused:
            rows, byz = self._robust_rows(sampled, int(idx.shape[1]))
            dstate = (self._defense_state if self._defense_state is not None
                      else {})
            prev_params = self.params  # round-START params: the assessor's
            # reference point (not donated when contribution is enabled)
            out = self._traced(
                "robust_round_fused", 1, self._round_fn,
                self.params, self.server_state, self.train_data,
                self.client_states, idx, active, work, jnp.asarray(rows),
                jnp.asarray(byz), jnp.asarray(sampled, jnp.int32), dstate,
                round_key, hyper_r)
            (self.params, self.server_state, self.client_states,
             new_dstate, metrics, slot_mets, verdict) = out[:7]
            if self._defense_state is not None:
                self._defense_state = new_dstate
            if self.contribution.enabled:
                # the same dispatch emitted the post-attack sharded matrix;
                # coalition values apply subsets of THIS round's updates to
                # the round-start params (host-path semantics); only the
                # [K] scores come host-side
                self._assess_contribution_fused(out[7], out[8], sampled,
                                                round_idx, prev_params)
            # device arrays only — materialized lazily at the next
            # selection query, never a transfer inside run_round
            self.selection.note_results(round_idx, sampled, placement,
                                        slot_metrics=slot_mets,
                                        verdict=verdict)
            self.dp.record_round(len(sampled) / max(self.fed.num_clients, 1))
            return metrics
        if self.robust_mode:
            (upd_stack, w_stack, agg_extras, self.client_states,
             metrics, slot_mets) = self._traced(
                "robust_collect", 1, self._round_fn,
                self.params, self.server_state, self.train_data,
                self.client_states, idx, active, work, round_key, hyper_r)
            self.selection.note_results(round_idx, sampled, placement,
                                        slot_metrics=slot_mets)
            agg_update = self._robust_aggregate(
                upd_stack, w_stack, sampled, int(idx.shape[1]),
                round_key, round_idx)
            self.params, self.server_state = self._traced(
                "server_update", 1, self._server_update,
                self.params, self.server_state, agg_update, agg_extras,
                jnp.int32(round_idx))
            self.dp.record_round(len(sampled) / max(self.fed.num_clients, 1))
            return metrics
        (self.params, self.server_state, self.client_states,
         metrics, slot_mets) = self._traced(
            "round", 1, self._round_fn,
            self.params, self.server_state, self.train_data,
            self.client_states, idx, active, work, round_key, hyper_r)
        self.selection.note_results(round_idx, sampled, placement,
                                    slot_metrics=slot_mets)
        self.dp.record_round(len(sampled) / max(self.fed.num_clients, 1))
        return metrics

    def _canonical_width(self) -> int:
        """The simulator-canonical schedule width: the cap build_schedule
        buckets against. Padding every round to THIS width (instead of a
        per-block max) keeps the fused programs at exactly one compile per
        run — padded slots carry active=0 and are masked in the round
        body, so results are unchanged. ``_sample_n`` already folds the
        chaos over-sampling factor in, so an over-sampled run is as
        compile-stable as a plain one."""
        return min(self.cpd, self._sample_n)

    def _schedule_for(self, round_idx: int, pad_to: Optional[int] = None):
        # adaptive sizing REPLACES the static chaos_over_sample factor
        # (documented semantics): its base is the raw per-round target,
        # not the statically inflated one — otherwise the two compound
        # and the cohort never shrinks below the static inflation even
        # at an observed dropout of ~0
        base = (self._base_n if self.selection.adaptive
                else self._static_n)
        target_n = self.selection.round_target(round_idx, base,
                                               self._sample_n)
        sampled, excluded = self.selection.select(round_idx, target_n)
        max_slots = min(self.cpd, self._sample_n)
        idx, active = build_schedule(sampled, self.n_devices, self.cpd,
                                     max_slots=max_slots)
        # chaos availability as DATA: per-slot work fractions next to the
        # active mask (0 = dropped, (0,1) = straggler, 1 = healthy).
        # Reputation-benched clients ride the SAME channel — work 0 is
        # renormalized in-program dropout under chaos_tolerance, which is
        # exactly how the byzantine-aware-dropout leftover closes: the
        # benched client neither trains nor dilutes the denominator.
        # slot_placement mirrors build_schedule's loop, so work[d, s]
        # lands on exactly the client idx[d, s] trains.
        work = np.ones_like(active)
        faults = None
        excl = set(excluded)
        work_by_client = {int(c): 1.0 for c in sampled}
        if self.chaos.injects_availability or excl:
            if self.chaos.injects_availability:
                faults = self.chaos.round_faults(round_idx, sampled)
            for cid, d, s in slot_placement(sampled, self.n_devices,
                                            self.cpd):
                w = faults.scale_for(cid) if faults is not None else 1.0
                if cid in excl:
                    w = 0.0
                work[d, s] = w
                work_by_client[cid] = w
        self.selection.note_schedule(round_idx, sampled, excluded,
                                     work_by_client, target_n)
        if pad_to is not None and idx.shape[1] < pad_to:
            extra = pad_to - idx.shape[1]
            idx = np.pad(idx, ((0, 0), (0, extra)))
            active = np.pad(active, ((0, 0), (0, extra)))
            work = np.pad(work, ((0, 0), (0, extra)))
        return sampled, (idx, active, work), faults

    def _ledger_round(self, round_idx: int, sampled, active, work,
                      faults) -> None:
        """Injected-vs-observed fault accounting at the aggregation seam:
        ``observed`` is what the round program was actually fed (the
        participating slot count after masking)."""
        if faults is None:
            return
        participating = int(np.sum((np.asarray(active) > 0)
                                   & (np.asarray(work) > 0)))
        self.chaos_ledger.record_round(
            round_idx,
            injected={"dropped": list(faults.dropped),
                      "stragglers": dict(faults.work_scale)},
            observed={"sampled": len(sampled),
                      "participating": participating,
                      "tolerance": self.chaos_tolerance})

    def run_rounds_fused(self, start_round: int, n_rounds: int,
                         hyper: TrainHyper) -> List[Dict[str, float]]:
        """Run ``n_rounds`` rounds as ONE device dispatch (schedules and
        round keys precomputed host-side, stacked, scanned on-device).
        Returns the per-round metrics list. Robust mode fuses too when the
        sharded defense path applies (``robust_fused``); host-bound robust
        configs (user ServerAggregators, ``sharded_defense: false``) fall
        back to the per-round path. Contribution-enabled runs stay
        per-round as well — each round is still ONE fused dispatch, but the
        assessor needs that round's update matrix (and issues its own
        coalition-eval dispatches) before the next round runs."""
        if n_rounds == 1 or (self.robust_mode and not self.robust_fused) \
                or (self.robust_fused and self.contribution.enabled):
            return [self.run_round(start_round + i, hyper)
                    for i in range(n_rounds)]
        self._ensure_flops_model(hyper)
        with obs_trace.span("block", root=True,
                            attrs={"role": "engine",
                                   "start_round": int(start_round),
                                   "rounds": int(n_rounds)}):
            return self._run_rounds_fused_traced(start_round, n_rounds,
                                                 hyper)

    def _run_rounds_fused_traced(self, start_round: int, n_rounds: int,
                                 hyper: TrainHyper) -> List[Dict[str, float]]:
        host_span = obs_trace.tracer.start_span(
            "host.input", attrs={"start_round": int(start_round),
                                 "rounds": int(n_rounds)})
        try:
            return self._run_rounds_fused_body(
                start_round, n_rounds, hyper, host_span)
        finally:
            # schedule building can raise (device_put OOM, shape errors);
            # the span must still flush so a failed run's log shows where
            # the host time went. end() is idempotent — the success path
            # already ended it right before dispatch.
            host_span.end()

    def _run_rounds_fused_body(self, start_round: int, n_rounds: int,
                               hyper: TrainHyper,
                               host_span) -> List[Dict[str, float]]:
        idxs, acts, works, keys, ridxs, rows_r, byz_r, ids_r = (
            [], [], [], [], [], [], [], [])
        sampled_r = []
        # every round pads to the simulator-canonical width (padded slots
        # carry active=0 and are masked in the round body): build_schedule
        # buckets slot counts per round (powers of two), and a per-block
        # max would recompile the fused program whenever blocks disagree
        # on width — canonical padding compiles it exactly once per run
        width = self._canonical_width()
        part = 0.0
        for r in range(start_round, start_round + n_rounds):
            sampled, (idx, active, work), faults = self._schedule_for(
                r, pad_to=width)
            self._ledger_round(r, sampled, active, work, faults)
            sampled_r.append(sampled)
            idxs.append(idx)
            acts.append(active)
            works.append(work)
            keys.append(jax.random.fold_in(self.rng, r))
            ridxs.append(r)
            if self.robust_fused:
                rows, byz = self._robust_rows(sampled, width)
                rows_r.append(rows)
                byz_r.append(byz)
                ids_r.append(np.asarray(sampled, np.int32))
            part += len(sampled) / max(self.fed.num_clients, 1)
        sched_sharding = NamedSharding(self.mesh, P(None, AXIS_CLIENT))
        idxs = jax.device_put(jnp.stack([jnp.asarray(i) for i in idxs],
                                        axis=0), sched_sharding)
        acts = jax.device_put(jnp.stack([jnp.asarray(a) for a in acts],
                                        axis=0), sched_sharding)
        works = jax.device_put(jnp.stack([jnp.asarray(w) for w in works],
                                         axis=0), sched_sharding)
        keys = jnp.stack(keys)
        ridxs = jnp.asarray(ridxs, jnp.int32)
        hyper_0 = hyper.replace(round_idx=jnp.int32(start_round))
        host_span.end()  # host-side schedule building done; dispatch next
        if self.robust_fused:
            if not hasattr(self, "_robust_fused_fn"):
                self._robust_fused_fn = self._build_robust_fused_fn()
            dstate = (self._defense_state if self._defense_state is not None
                      else {})
            (self.params, self.server_state, self.client_states,
             new_dstate, metrics, slot_mets, verdicts) = self._traced(
                "robust_rounds_fused", n_rounds, self._robust_fused_fn,
                self.params, self.server_state, self.train_data,
                self.client_states, idxs, acts, works,
                jnp.stack([jnp.asarray(r) for r in rows_r]),
                jnp.stack([jnp.asarray(b) for b in byz_r]),
                jnp.stack([jnp.asarray(i) for i in ids_r]),
                dstate, keys, ridxs, hyper_0)
            if self._defense_state is not None:
                self._defense_state = new_dstate
        else:
            if not hasattr(self, "_fused_fn"):
                self._fused_fn = self._build_fused_fn()
            (self.params, self.server_state, self.client_states,
             metrics, slot_mets) = self._traced(
                "rounds_fused", n_rounds, self._fused_fn,
                self.params, self.server_state, self.train_data,
                self.client_states, idxs, acts, works, keys, ridxs,
                hyper_0)
            verdicts = None
        if self.selection.track:
            # queue each round's slice of the block outputs (lazy device
            # slices; materialized at the next selection query)
            for i, sampled in enumerate(sampled_r):
                sm_i = jax.tree_util.tree_map(lambda a: a[i], slot_mets)
                self.selection.note_results(
                    start_round + i, sampled,
                    slot_placement(sampled, self.n_devices, self.cpd),
                    slot_metrics=sm_i,
                    verdict=None if verdicts is None else verdicts[i])
        for _ in range(n_rounds):  # DP accounting stays per-round
            self.dp.record_round(part / n_rounds)
        host = jax.device_get(metrics)
        return [{k: host[k][i] for k in host} for i in range(n_rounds)]

    def run(self, comm_round: Optional[int] = None) -> Dict[str, Any]:
        args = self.args
        rounds = comm_round if comm_round is not None else int(args.comm_round)
        hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                           epochs=int(args.epochs))
        self._ensure_flops_model(hyper)
        t0 = time.time()
        start_round = 0
        restored = self._ckpt_latest()
        if restored is not None:
            step, st = restored
            self._load_ckpt_state(st)
            start_round = step + 1
            logger.info("resumed from checkpoint at round %d", step)
        freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
        # Rounds between eval/checkpoint boundaries run as ONE device
        # dispatch (run_rounds_fused): the per-round dispatch constant is
        # ~120 ms through the tunneled chip — 4.4% of a flagship round
        # (BASELINE.md §3b). rounds_per_dispatch caps the fused block
        # (compile time grows with the scan length; 8 amortizes dispatch
        # to <1% while keeping compiles quick).
        rpd = max(int(getattr(args, "rounds_per_dispatch", 8) or 1), 1)
        round_idx = start_round
        while round_idx < rounds:
            # run up to (and including) the next eval/checkpoint boundary.
            # freq <= 0 = never evaluate in-loop (bench timing mode; note
            # x % -1 == 0 for every x, so -1 must not reach the modulo —
            # it would force n_block=1 AND eval every round, the exact
            # inverse of the intent)
            if freq <= 0:
                next_eval = rounds - 1
            else:
                next_eval = (round_idx if round_idx % freq == 0
                             else (round_idx // freq + 1) * freq)
            stop = min(next_eval, rounds - 1, round_idx + rpd - 1)
            if self.ckpt.enabled:
                # maybe_save fires when (r + 1) % every == 0 — the block
                # must END on such a round or the checkpoint would be
                # written from end-of-block params under an earlier label
                # (wrong state on resume)
                every = self.ckpt.every
                nxt = ((round_idx + every) // every) * every - 1
                stop = min(stop, nxt)
            n_block = stop - round_idx + 1
            block = self.run_rounds_fused(round_idx, n_block, hyper)
            for i, metrics in enumerate(block):
                r = round_idx + i
                rec: Dict[str, Any] = {"round": r}
                cnt = max(float(metrics["count"]), 1.0)
                rec["train_loss"] = float(metrics["loss_sum"]) / cnt
                rec["train_acc"] = float(metrics["correct"]) / cnt
                if freq > 0 and (r % freq == 0 or r == rounds - 1):
                    with obs_trace.span("eval", root=True,
                                        attrs={"role": "engine",
                                               "round_idx": r}):
                        stats = self._evaluate(self.params,
                                               self.fed.test["x"],
                                               self.fed.test["y"],
                                               self.fed.test["mask"])
                        n = max(float(stats["count"]), 1.0)
                        rec["test_acc"] = float(stats["correct"]) / n
                        rec["test_loss"] = float(stats["loss_sum"]) / n
                    logger.info("round %d: test_acc=%.4f", r,
                                rec["test_acc"])
                self.history.append(rec)
                if self.ckpt.enabled:
                    # building the state dict is no longer free (a
                    # stateful selection store flushes its device-array
                    # observation queue) — skip it when checkpointing is
                    # off rather than paying a readback per round
                    with obs_trace.span("checkpoint", root=True,
                                        attrs={"role": "engine",
                                               "round_idx": r}):
                        self.ckpt.maybe_save(r, self._ckpt_state())
                mlops.log_round_info(rounds, r)
                mlops.log({k: v for k, v in rec.items() if k != "round"},
                          step=r)
                if self.chaos.crash_due(r):
                    # injected crash-at-round event: surface AFTER the
                    # round's record + checkpoint so a resume restores a
                    # consistent trajectory. Flush the async checkpoint
                    # writer first — a torn save would turn a
                    # deterministic e2e into a flaky one.
                    self.ckpt.flush()
                    raise ChaosCrash(r)
            round_idx = stop + 1
        # async checkpoint saves must be durable before the run returns —
        # the next run's RoundCheckpointer is a different manager and
        # cannot wait on this one's pending writes
        self.ckpt.flush()
        # final metrics snapshot: the cadence flush misses everything
        # after its last boundary — the run log must be self-contained
        from ...core.obs import metrics as _obs_metrics
        _obs_metrics.flush_final(step=rounds - 1)
        wall = time.time() - t0
        last_eval = next((r for r in reversed(self.history) if "test_acc" in r),
                         None)
        if last_eval is None:
            if freq <= 0:  # timing mode: no eval, in-loop or here
                last_eval = {"test_acc": None}
            else:
                stats = self._evaluate(self.params, self.fed.test["x"],
                                       self.fed.test["y"],
                                       self.fed.test["mask"])
                n = max(float(stats["count"]), 1.0)
                last_eval = {"test_acc": float(stats["correct"]) / n,
                             "test_loss": float(stats["loss_sum"]) / n}
        result = {"params": self.params, "history": self.history,
                  "wall_time_s": wall, "final_test_acc": last_eval["test_acc"],
                  "final_test_loss": last_eval.get("test_loss"),
                  "rounds": rounds}
        if self.dp.is_dp_enabled():
            result["dp_epsilon_spent"] = self.dp.get_epsilon_spent()
        return result
