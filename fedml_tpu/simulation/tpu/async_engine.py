"""Buffered-async federated rounds on the TPU mesh simulator.

``round_mode: async_buffered`` removes the round barrier: the server pours
a staleness-weighted buffer of K client updates whenever the K-th arrives
(FedBuff, Nguyen et al. AISTATS 2022; decay families from FedAsync, Xie et
al. 2019), so one slow or dead client caps nothing — it is down-weighted
when it finally lands and redeemed back into the rotation, never waited on.

How the async world maps onto a synchronous mesh:

* **Arrival time is simulated.** Clients get seeded heterogeneous base
  durations (``core/async_rounds/arrivals.py``); the chaos plan is the
  adversary — a straggler does full work slowly (duration / work fraction)
  and a dropped client never delivers, rejoining the idle pool after its
  duration (the redemption event). A virtual clock + event heap orders
  arrivals; everything is a pure function of the seeds, so runs (and
  crash-resumes) replay identical pours.

* **Device work stays one-dispatch-per-pour.** Each pour is ONE jitted
  ``shard_map`` program that simultaneously (a) aggregates the poured
  buffer — a ``[K, D]`` matrix of staleness-tagged update vectors, weights
  and staleness decay riding as DATA — through the staleness-corrected
  server transform (``FedOptimizer.server_update_async``), and (b) trains
  the re-dispatched cohort on the PRE-POUR params. The two subgraphs share
  only that stale input, so XLA overlaps training of cohort N+1 with
  aggregation of cohort N — the double-buffered dispatch: two model slots
  (the donated pre-pour params in, the post-pour params out), and the
  program compiles exactly once (schedules pad to one canonical width, all
  staleness math is data). The pour programs ride the inherited
  ``_traced`` compute-plane seam (``core/obs/roofline``): recompile
  forensics on every dispatch, and under ``obs_roofline: true`` a per-op
  roofline + collective-traffic record per pour program
  (``async_pour`` / ``async_pour_defended``).

* **A client trains on the model it was handed.** Its update is computed
  at dispatch (mathematically identical to computing it at arrival, since
  the base is fixed then) but enters the buffer only when the virtual
  clock says it arrived — staleness is the honest per-update count of
  pours that happened in between.

Buffered rows are replicated ``[K, D]`` f32 vectors (update ‖ extras), so
SCAFFOLD's control variates ride the buffer next to the model delta; for
LLM-scale models a feature-sharded buffer is the known follow-up.

**Defended pours** (ISSUE 7): attacks/defenses compose with the buffer.
A robust defense compares update vectors, but buffered updates were
trained from DIFFERENT model versions — their deltas are not comparable
until every row is re-based onto the current version. The engine keeps a
fixed-size per-version base-delta ring on device (slot ``v mod R`` holds
the server movement ``params_{v+1} − params_v``; the async cross-silo
server's base ring is the host-side template): at pour time each row is
corrected by the accumulated movement it missed (``Δ − (params_v −
params_{v−s})``, a DATA-driven masked sum over the ring — never a
recompile), the chaos model-attack injects on the re-based shards as the
in-program adversary, and the row flows through the same feature-sharded
defense kernels as the sync fused path with the staleness decay folded
into the defense's row weights and a ``[K]`` validity mask covering
partial pours. At staleness 0 the correction is exactly zero, so a
defended pour is bit-identical to the sync defended round — the parity
anchor the tests pin. Stateful defenses keep their device-resident state
pytree, which joins the async checkpoint so crash-resume replays
identical verdicts; verdicts feed the PR 5 reputation store, and the
arrival rotation stops re-dispatching benched byzantine clients.
"""

from __future__ import annotations

import heapq
import logging
import time
from collections import deque
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...constants import AXIS_CLIENT
from ...core import mlops
from ...core.obs import metrics as obs_metrics
from ...core.obs import trace as obs_trace
from ...core.async_rounds import (adaptive_staleness_cap, buffer_k_from_args,
                                  durations_from_args, faulted_duration,
                                  make_staleness_fn, merge_alpha_from_args,
                                  pour_weights, staleness_cap_from_args,
                                  UpdateBuffer, weighting_knobs_from_args)
from ...core.algframe.types import TrainHyper
from ...core.chaos import ChaosCrash
from ...core.collectives import psum_tree, vector_to_tree_like
from ...core.jax_compat import shard_map
from ...core.security.defense import sharded as sharded_defense
from ...core.selection import slot_placement
from ..sampling import build_schedule
from .engine import ATTACK_FOLD, DEFENSE_FOLD, TPUSimulator

logger = logging.getLogger(__name__)

_ARRIVE = 0
_REDEEM = 1

# domain-separation tag for the idle-pool rotation order (distinct from
# the chaos and duration tags)
_ROTATION_TAG = 1013


class AsyncBufferedSimulator(TPUSimulator):
    """TPU engine in ``round_mode: async_buffered``. ``comm_round`` counts
    POURS (global model versions), the async analog of rounds."""

    def __init__(self, args, fed_dataset, bundle, optimizer, spec,
                 mesh=None, server_aggregator=None):
        super().__init__(args, fed_dataset, bundle, optimizer, spec,
                         mesh=mesh, server_aggregator=server_aggregator)
        # --- config guards: fail loudly, never silently degrade ----------
        if self.contribution.enabled or self.server_aggregator is not None:
            raise ValueError(
                "round_mode: async_buffered composes with attacks/defenses "
                "(defended pours re-base the buffer onto the current "
                "version), but not yet with contribution assessment or "
                "user ServerAggregators — both consume a same-version "
                "host-ordered update matrix; use round_mode: sync")
        if self.dp.is_dp_enabled():
            raise ValueError(
                "round_mode: async_buffered does not yet compose with DP "
                "(per-pour accounting under stale mixed cohorts is an open "
                "design); use round_mode: sync with DP")
        # defended pours: attack/defense ride the compile-once pour
        # program (re-base -> in-program attack -> sharded defense)
        self._defended = (self.defender.is_defense_enabled()
                          or self.attacker.is_model_attack())
        if self.defender.is_defense_enabled():
            if self.defender.defense_type in ("weak_dp", "crfl"):
                raise ValueError(
                    "round_mode: async_buffered refuses defense_type "
                    f"{self.defender.defense_type!r}: noise-adding "
                    "defenses are DP by another name, and per-pour noise "
                    "accounting over a mixed-staleness buffer is the same "
                    "open design that keeps async+DP refused; use "
                    "round_mode: sync")
            if not self._use_sharded_defense():
                raise ValueError(
                    "round_mode: async_buffered runs the defense INSIDE "
                    "the compile-once pour program and needs the sharded "
                    "defense path; sharded_defense: false configs must "
                    "use round_mode: sync")
            pref = str(getattr(args, "robust_fused", "auto")
                       or "auto").lower()
            if pref in ("false", "0", "no", "host"):
                raise ValueError(
                    "robust_fused: host has no meaning under round_mode: "
                    "async_buffered — the defended pour is one fused "
                    "program by construction; use robust_fused: auto")
        if self.selection.adaptive:
            # no per-round cohort to over-sample: the in-flight
            # concurrency is fixed and dropped arrivals are redeemed by
            # the rotation — pin rather than refuse, loudly
            self.selection.pin_adaptive(
                "async_buffered has no per-round cohort to over-sample "
                "(fixed in-flight concurrency; drops redeem via the "
                "rotation)")
        self.concurrency = min(int(args.client_num_per_round),
                               int(fed_dataset.num_clients))
        self.k = buffer_k_from_args(args, self.concurrency)
        self.merge_alpha = merge_alpha_from_args(args)
        (self._weighting_kind, self._poly_a,
         self._hinge_b) = weighting_knobs_from_args(args)
        self._cap_adaptive = int(getattr(args, "async_staleness_cap", 16)
                                 or 0) == 0
        self.staleness_cap = staleness_cap_from_args(args)
        # validate the weighting knobs NOW, not at the first pour
        make_staleness_fn(self._weighting_kind, self._poly_a, self._hinge_b,
                          self.staleness_cap)
        self.buffer = UpdateBuffer(self.k)
        self.durations = durations_from_args(fed_dataset.num_clients, args)
        self._n_k = np.asarray(fed_dataset.train.num_samples, np.float64)

        # flattened-row geometry: update vector ‖ extras vector
        extras_zero = self.opt.server_extras_zero(self.params)
        self._extras_d = int(sum(int(np.prod(l.shape)) for l in
                                 jax.tree_util.tree_leaves(extras_zero)))
        self._row_d = self._true_d + self._extras_d

        if self._defended:
            # _check_extras_compat (base __init__) already refuses
            # extras-carrying optimizers in robust mode, so a defended
            # buffer row is exactly the [true_d] model delta
            # per-version base-delta ring: slot (v mod R) holds the
            # server movement params_{v+1} - params_v as a replicated
            # device row; R covers the staleness cap (the adaptive cap
            # can grow to its 64 ceiling, so adaptive runs size for it).
            # Staleness beyond the ring re-bases over the retained
            # movement only — the weight is saturated anyway (logged
            # once, mirroring the cross-silo base ring's fallback).
            self._ring_r = int(np.clip(
                64 if self._cap_adaptive else self.staleness_cap, 1, 64))
            self._ring = jax.device_put(
                jnp.zeros((self._ring_r, self._true_d), jnp.float32),
                self.repl_sharding)
            self._ring_fallback_logged = False

        # virtual clock + event heap: (t, seq, kind, cid, version, weight,
        # duration, vec) — vec is the client's device-resident [row_d]
        # update row for arrivals, None for redemption events; seq is
        # unique, so tuple ordering never compares the trailing array
        self.version = 0
        self.virtual_t = 0.0
        self.updates_aggregated = 0
        self._dispatch_seq = 0
        self._evseq = 0
        self._events: List[Any] = []
        self._pour_interval_ema: Optional[float] = None
        self._last_pour_t = 0.0
        # per-client observed arrival latency EMA (simulated seconds) —
        # the arrival-rate signal behind the adaptive staleness cap
        self._lat_ema = np.zeros(fed_dataset.num_clients, np.float64)
        self._lat_seen = np.zeros(fed_dataset.num_clients, np.float64)
        # running aggregates over the seen-clients' EMAs, maintained
        # incrementally so the per-arrival rate gauge costs O(1), not a
        # full-population mean in the event-heap hot loop
        self._lat_ema_sum = 0.0
        self._lat_seen_n = 0
        self._last_arrival_t = np.full(fed_dataset.num_clients, -1.0,
                                       np.float64)
        # idle rotation: seeded permutation so dispatch order respects
        # random_seed via the same (seed, tag) stream discipline
        order = np.random.default_rng(
            (int(getattr(args, "random_seed", 0) or 0),
             _ROTATION_TAG)).permutation(fed_dataset.num_clients)
        self._idle = deque(int(c) for c in order)
        self._bootstrapped = False

        self._async_width = min(self.cpd, self.concurrency)
        self._pour_fn = self._build_async_pour_fn()
        self._row_fn = jax.jit(lambda m, i: m[i])
        self._stack_fn = jax.jit(lambda vs: jnp.stack(vs))
        self._zero_row = jnp.zeros((self._row_d,), jnp.float32)

    # ------------------------------------------------------------------
    def _build_async_pour_fn(self):
        """The ONE async program: pour the buffer through the staleness-
        corrected server transform while training the freshly-dispatched
        cohort on the pre-pour params (independent subgraphs — XLA
        overlaps them; two donated model slots). In defended mode the
        pour half additionally re-bases every buffered row onto the
        current version (base-delta ring, DATA masks), injects the
        on-device model attack, and runs the feature-sharded defense —
        still one program, still compiled exactly once."""
        emit_extras = self._extras_d > 0
        collect = self._make_collect_core(emit_extras_stack=emit_extras)
        opt = self.opt
        true_d = self._true_d
        extras_zero = opt.server_extras_zero(self.params)
        n_total = float(max(self.fed.num_clients, 1))

        def train_rows(params, server_state, local_data, local_states,
                       sched_idx, sched_active, sched_work, round_key,
                       hyper):
            """The training half shared by both pour flavors: slot-scan
            the dispatched cohort, then gather the [S, ...] local stacks
            into the replicated [n_dev*S, row_d] dispatch matrix (row =
            d*S+s, the _robust_rows convention)."""
            sq = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
            res = collect(params, server_state, sq(local_data),
                          sq(local_states), sched_idx[0], sched_active[0],
                          sched_work[0], round_key, hyper)
            (upd_stack, w_stack, states, acc_ex, acc_w, acc_m,
             slot_mets) = res[:7]
            leaves = jax.tree_util.tree_leaves(upd_stack)
            n_slots = leaves[0].shape[0]
            parts = [jnp.reshape(l, (n_slots, -1)).astype(jnp.float32)
                     for l in leaves]
            if emit_extras:
                parts += [jnp.reshape(l, (n_slots, -1)).astype(jnp.float32)
                          for l in jax.tree_util.tree_leaves(res[7])]
            rows_mat = jax.lax.all_gather(
                jnp.concatenate(parts, axis=1), AXIS_CLIENT, axis=0,
                tiled=True)
            metrics = psum_tree(acc_m)
            states = jax.tree_util.tree_map(lambda a: a[None], states)
            slot_mets = jax.tree_util.tree_map(lambda a: a[None], slot_mets)
            return rows_mat, states, metrics, slot_mets

        if self._defended:
            return self._build_defended_pour_fn(train_rows, opt, true_d,
                                                n_total)

        def pour_body(params, server_state, local_data, local_states,
                      sched_idx, sched_active, sched_work,
                      buf_mat, buf_nw, merge_scale, pour_n,
                      round_key, hyper):
            rows_mat, states, metrics, slot_mets = train_rows(
                params, server_state, local_data, local_states,
                sched_idx, sched_active, sched_work, round_key, hyper)
            # the pour: buf_nw is the padded [K] relative mix and
            # merge_scale the absolute damping, BOTH computed host-side by
            # core/async_rounds.pour_weights (the one staleness
            # implementation) and riding as DATA; pour_n (the actual
            # poured count — partial pours under heavy dropout pour fewer
            # than K) sizes the population fraction SCAFFOLD's control
            # variate advances by
            agg_vec = jnp.einsum("k,kd->d", buf_nw, buf_mat)
            agg_update = vector_to_tree_like(agg_vec[:true_d], params)
            agg_extras = (vector_to_tree_like(agg_vec[true_d:], extras_zero)
                          if emit_extras else {})
            upd_params, upd_sstate = opt.server_update_async(
                params, server_state, agg_update, agg_extras,
                hyper.round_idx, merge_scale, pour_n / n_total)
            # a no-op pour (bootstrap, drained-heap retry) must leave the
            # SERVER STATE untouched too: merge_scale=0 already pins the
            # params, but FedOpt's adam/yogi would still advance its step
            # count and decay its moments on a zero pseudo-gradient
            poured = pour_n > 0
            new_params = jax.tree_util.tree_map(
                lambda n, o: jnp.where(poured, n, o), upd_params, params)
            new_sstate = jax.tree_util.tree_map(
                lambda n, o: jnp.where(poured, n, o), upd_sstate,
                server_state)
            return (new_params, new_sstate, states, rows_mat, metrics,
                    slot_mets)

        shard_fn = shard_map(
            pour_body,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS_CLIENT), P(AXIS_CLIENT),
                      P(AXIS_CLIENT), P(AXIS_CLIENT), P(AXIS_CLIENT),
                      P(), P(), P(), P(), P(), P()),
            out_specs=(P(), P(), P(AXIS_CLIENT), P(), P(), P(AXIS_CLIENT)),
            check_vma=False,
        )
        return jax.jit(shard_fn, donate_argnums=self._donate_args(0, 1, 3))

    def _build_defended_pour_fn(self, train_rows, opt, true_d,
                                n_total: float):
        """The defended pour flavor: re-base the buffer onto the current
        version via the base-delta ring (DATA masks — staleness never
        recompiles), inject the on-device model attack on the re-based
        feature shards, run the sharded defense with the staleness decay
        already folded into ``buf_nw`` and a [K] validity mask for
        partial pours, then apply the defended aggregate through the
        staleness-corrected server transform. Also maintains the ring
        (this pour's server movement lands in slot ``version mod R``) and
        emits the defense's [K] verdict for the reputation store."""
        defense_type = (self.defender.defense_type
                        if self.defender.is_defense_enabled() else "mean")
        hp = sharded_defense.DefenseHP.from_defender(self.defender)
        attack_type = (self.attacker.attack_type
                       if self.attacker.is_model_attack() else None)
        attack_scale = float(getattr(self.attacker, "attack_scale", 1.0))
        n_dev = self.n_devices
        d_pad = self._d_pad
        k_buf = self.k
        state_specs = self._defense_state_specs

        def flat32(tree):
            return jnp.concatenate(
                [jnp.reshape(l, (-1,)).astype(jnp.float32)
                 for l in jax.tree_util.tree_leaves(tree)])

        def pour_body(params, server_state, local_data, local_states,
                      sched_idx, sched_active, sched_work,
                      buf_mat, buf_nw, merge_scale, pour_n,
                      drift_mask, row_mask, pour_ids, byz_mask, ring,
                      dstate, ring_slot, round_key, hyper):
            rows_mat, states, metrics, slot_mets = train_rows(
                params, server_state, local_data, local_states,
                sched_idx, sched_active, sched_work, round_key, hyper)
            # RE-BASE: a row trained from version v-s proposed the target
            # model params_{v-s} + delta; comparable at version v it is
            # delta - (params_v - params_{v-s}) — the accumulated server
            # movement the client missed, summed from the ring by the
            # per-row DATA mask. At staleness 0 the mask is all-zero and
            # the row passes through untouched (the sync-parity anchor).
            drift = jnp.einsum("kr,rd->kd", drift_mask, ring)
            rebased = buf_mat - drift
            pad = d_pad - true_d
            mat_full = (jnp.pad(rebased, ((0, 0), (0, pad))) if pad
                        else rebased)
            shard_w = d_pad // n_dev
            dev = jax.lax.axis_index(AXIS_CLIENT)
            # replicated [K, D] -> this device's [K, D/n] feature shard:
            # same column blocks as the P(None, axis) layout the sync
            # sharded path lands via its all_to_all
            mat_s = jax.lax.dynamic_slice(
                mat_full, (jnp.int32(0), dev * shard_w), (k_buf, shard_w))
            if attack_type is not None:
                mat_s = sharded_defense._apply_attack_shard(
                    attack_type, mat_s, byz_mask,
                    jax.random.fold_in(round_key, ATTACK_FOLD),
                    attack_scale, AXIS_CLIENT)
            vec_s, new_dstate, verdict = \
                sharded_defense.defend_shard_stateful(
                    mat_s, buf_nw, AXIS_CLIENT, defense_type, hp,
                    state=dstate, ids=pour_ids,
                    key=jax.random.fold_in(round_key, DEFENSE_FOLD),
                    true_d=true_d, row_mask=row_mask)
            vec = jax.lax.all_gather(vec_s, AXIS_CLIENT,
                                     tiled=True)[:true_d]
            agg_update = vector_to_tree_like(vec, params)
            upd_params, upd_sstate = opt.server_update_async(
                params, server_state, agg_update, {}, hyper.round_idx,
                merge_scale, pour_n / n_total)
            # no-op pour (bootstrap, drained-heap retry): pin params,
            # server state AND defense state — the kernels just ran on
            # all-padding and must not advance cross-round history
            poured = pour_n > 0
            new_params = jax.tree_util.tree_map(
                lambda n, o: jnp.where(poured, n, o), upd_params, params)
            new_sstate = jax.tree_util.tree_map(
                lambda n, o: jnp.where(poured, n, o), upd_sstate,
                server_state)
            new_dstate = jax.tree_util.tree_map(
                lambda n, o: jnp.where(poured, n, o), new_dstate, dstate)
            # ring maintenance: this pour's server movement becomes the
            # base delta of the version it just created; a no-op pour
            # leaves the slot holding whatever version it still caches
            delta = flat32(new_params) - flat32(params)
            new_ring = ring.at[ring_slot].set(
                jnp.where(poured, delta, ring[ring_slot]))
            return (new_params, new_sstate, states, rows_mat, metrics,
                    slot_mets, new_dstate, verdict, new_ring)

        shard_fn = shard_map(
            pour_body,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS_CLIENT), P(AXIS_CLIENT),
                      P(AXIS_CLIENT), P(AXIS_CLIENT), P(AXIS_CLIENT),
                      P(), P(), P(), P(),
                      P(), P(), P(), P(), P(),
                      state_specs, P(), P(), P()),
            out_specs=(P(), P(), P(AXIS_CLIENT), P(), P(), P(AXIS_CLIENT),
                       state_specs, P(), P()),
            check_vma=False,
        )
        # donate params / server_state / client_states / ring / defense
        # state: each is replaced 1:1 by an output of identical shape+spec
        return jax.jit(shard_fn,
                       donate_argnums=self._donate_args(0, 1, 3, 15, 16))

    # ------------------------------------------------------------------
    def _staleness_fn(self):
        if self._cap_adaptive:
            seen = self._lat_seen > 0
            self.staleness_cap = adaptive_staleness_cap(
                self._lat_ema[seen], self._pour_interval_ema or 0.0)
        return make_staleness_fn(self._weighting_kind, self._poly_a,
                                 self._hinge_b, self.staleness_cap)

    def _inflight(self) -> int:
        return len(self._events)

    def _rank_idle(self) -> None:
        """Async-aware dispatch (non-uniform ``client_selection``): there
        is no per-round cohort to strategize over, so the strategy instead
        decides WHO the freed capacity goes to next by reordering the idle
        rotation before the draw.

        * ``oort`` / ``power_of_choice``: rank by statistical utility ×
          arrival-rate posterior — a high-loss client that also delivers
          updates quickly buys the most model movement per unit of
          simulated time. Clients with no observed arrivals score the
          observed-mean rate (neutral), so exploration still happens.
        ``reputation`` benches by EXCLUSION instead (see
        :meth:`_benched_now`): with the buffer in steady state every
        freed client is re-dispatched immediately, so reordering alone
        could never keep a byzantine client out of the rotation.

        ``uniform`` (the default) never calls this — the rotation is
        bit-identical to the pre-defense engine."""
        idle = list(self._idle)
        if len(idle) <= 1:
            return
        self.selection.flush()
        st = self.selection.store
        name = self.selection.strategy_name
        if name == "power_of_choice":
            util = st.last_loss()  # +inf for unobserved: explore first
        else:  # oort
            util = self.selection.strategy._utility(self.version)
        rate = st.arrival_rate()
        # rate == 0 IFF never observed (both store backends); the sparse
        # store's arr_obs is row-space, so never read it as [n] here
        seen = rate > 0
        fill = (float(np.mean(rate[seen])) if bool(np.any(seen)) else 1.0)
        rate = np.where(seen, rate, max(fill, 1e-9))
        score = np.asarray([float(util[c]) * float(rate[c])
                            if np.isfinite(util[c]) else np.inf
                            for c in idle])
        order = np.argsort(-score, kind="stable")
        self._idle = deque(idle[i] for i in order)

    def _benched_now(self) -> set:
        """The ``reputation`` strategy's benched set: clients whose
        defense-verdict reputation fell below the threshold are excluded
        from dispatch entirely — they sit idle (burning no compute,
        poisoning no pour) until the relative posterior heals. The shared
        ``cap_bench`` floor guarantees at least ``max(K, min_keep_frac ×
        population)`` clients stay dispatchable, so a poisoned score
        stream can neither empty the rotation nor starve the pour
        trigger below its K."""
        if self.selection.strategy_name != "reputation":
            return set()
        from ...core.selection.strategies import cap_bench, rep_bench_knobs
        self.selection.flush()
        rep = self.selection.store.reputation
        thresh, keep_frac = rep_bench_knobs(self.args)
        flagged = [c for c in range(self.fed.num_clients)
                   if rep[c] < thresh]
        return set(cap_bench(
            self.fed.num_clients, flagged, badness=lambda c: -rep[c],
            keep_frac=keep_frac, quorum=self.k))

    def _draw_cohort(self, target: int) -> List[int]:
        """Pop up to ``target`` idle clients, deferring any whose device
        already filled its canonical slot width this dispatch (the [D, S]
        schedule shape must never grow, or the program recompiles).
        Reputation-benched clients are skipped (they stay idle);
        non-uniform strategies rank the pool first."""
        benched = self._benched_now()
        if self.selection.strategy_name not in ("uniform", "reputation"):
            self._rank_idle()
        counts = [0] * self.n_devices
        cohort: List[int] = []
        deferred: List[int] = []
        while self._idle and len(cohort) < target:
            cid = self._idle.popleft()
            d = cid // self.cpd
            if cid in benched or counts[d] >= self._async_width:
                deferred.append(cid)
                continue
            counts[d] += 1
            cohort.append(cid)
        self._idle.extendleft(reversed(deferred))
        return cohort

    def _defended_pour_data(self, entries):
        """Host-side DATA for one defended pour: per-update drift masks
        over the base-delta ring, the [K] partial-pour validity mask,
        pour client ids (padded with ids DISJOINT from the poured clients
        so the stateful defenses' masked scatters are exact no-ops), and
        the byzantine mask driving the in-program model attack."""
        k, r, v = self.k, self._ring_r, self.version
        dmask = np.zeros((k, r), np.float32)
        row_mask = np.zeros((k,), np.float32)
        for i, e in enumerate(entries):
            row_mask[i] = 1.0
            u = int(e.version)
            if u < v - r and not self._ring_fallback_logged:
                self._ring_fallback_logged = True
                logger.warning(
                    "defended pour: staleness %d exceeds the base-delta "
                    "ring (%d slots) — re-basing over the retained server "
                    "movement only; the update's staleness weight is "
                    "saturated anyway", v - u, r)
            for j in range(max(u, v - r), v):
                dmask[i, j % r] = 1.0
        poured = {int(e.client_id) for e in entries}
        ids = [int(e.client_id) for e in entries]
        ids += [c for c in range(self.fed.num_clients)
                if c not in poured][:k - len(ids)]
        ids = np.asarray(ids, np.int32)
        if self.attacker.is_model_attack():
            byz = np.asarray(self.attacker.byzantine_mask(ids),
                             np.float32) * row_mask
        else:
            byz = np.zeros(k, np.float32)
        return dmask, row_mask, ids, byz

    def _dispatch_plan(self, cohort: List[int]):
        """Chaos verdicts + schedule arrays for one dispatch. Returns
        (idx, active, work, per-client plan rows) — work is 0 only for
        dropped clients (stragglers do FULL work slowly in async; the
        fault is their arrival time)."""
        self._dispatch_seq += 1
        width = self._async_width
        idx, active = build_schedule(cohort, self.n_devices, self.cpd,
                                     max_slots=width)
        if idx.shape[1] < width:
            extra = width - idx.shape[1]
            idx = np.pad(idx, ((0, 0), (0, extra)))
            active = np.pad(active, ((0, 0), (0, extra)))
        work = np.ones_like(active)
        plan = []  # (cid, row, work_scale, duration)
        inj = self.chaos.injects_availability
        for cid, d, s in slot_placement(cohort, self.n_devices, self.cpd):
            ws = self.chaos.work_scale(self._dispatch_seq, cid) if inj \
                else 1.0
            if ws <= 0.0:
                work[d, s] = 0.0  # dropped: no compute, no arrival
            plan.append((cid, d * width + s, ws,
                         faulted_duration(self.durations[cid], ws)))
        return idx, active, work, plan

    def _push_events(self, plan, rows_mat, ctx=None) -> None:
        """Turn a dispatch plan into future events: arrivals carry the
        client's update row (extracted as a device slice — computed at
        dispatch, delivered at arrival); drops become redemption events.
        ``ctx`` is the dispatching pour span's trace context: it rides
        the event to the buffer entry, so the pour that eventually
        consumes the update can LINK back to the dispatch that produced
        it (staleness per link). Never compared by the heap — ``seq`` is
        unique before it."""
        t0 = self.virtual_t
        dropped = []
        for cid, row, ws, dur in plan:
            if ws <= 0.0:
                kind, vec = _REDEEM, None
                dropped.append(cid)
            else:
                kind, vec = _ARRIVE, self._row_fn(rows_mat,
                                                  jnp.int32(row))
            heapq.heappush(self._events,
                           (t0 + dur, self._evseq, kind, cid, self.version,
                            float(self._n_k[cid]), dur, vec, ctx))
            self._evseq += 1
        if dropped:
            mlops.log_chaos(round_idx=self._dispatch_seq,
                            injected={"dropped": dropped})

    def _absorb_until(self, n: int) -> bool:
        """Advance the virtual clock until ``n`` updates are buffered.
        False when the event heap drains first (everything idle)."""
        while len(self.buffer) < n:
            if not self._events:
                return False
            (t, _, kind, cid, ver, w, dur, vec,
             ctx) = heapq.heappop(self._events)
            self.virtual_t = max(self.virtual_t, t)
            if kind == _ARRIVE:
                self.buffer.add(cid, vec, weight=w, version=ver,
                                arrival_t=t, trace=ctx)
                # observed arrival latency = the FAULTED duration (a
                # straggler's slowness is the signal, not its base speed)
                self._note_arrival(cid, dur)
                if self._last_arrival_t[cid] >= 0:
                    self.selection.note_arrival(
                        cid, t - self._last_arrival_t[cid])
                self._last_arrival_t[cid] = t
            self._idle.append(cid)
        return True

    def _note_arrival(self, cid: int, latency_s: float) -> None:
        a = 0.2
        old = float(self._lat_ema[cid])
        if self._lat_seen[cid] > 0:
            self._lat_ema[cid] = (1 - a) * old + a * float(latency_s)
            self._lat_ema_sum += float(self._lat_ema[cid]) - old
        else:
            self._lat_ema[cid] = float(latency_s)
            self._lat_seen[cid] = 1.0
            self._lat_ema_sum += float(latency_s)
            self._lat_seen_n += 1
        self.selection.note_latency(int(cid), float(latency_s))
        # arrival-rate plane: latency histogram + the population-mean
        # rate gauge the adaptive staleness cap effectively tracks
        # (running sum/count — O(1) per arrival)
        mean_lat = (self._lat_ema_sum / self._lat_seen_n
                    if self._lat_seen_n else 0.0)
        obs_metrics.record_arrival(
            float(latency_s),
            rate_mean=(1.0 / mean_lat) if mean_lat > 0 else None)

    # ------------------------------------------------------------------
    def _pour_step(self, hyper: TrainHyper) -> Dict[str, Any]:
        """One pour: absorb arrivals to K, aggregate them, re-dispatch the
        freed clients — all device work in ONE program call. The pour is
        its own trace, LINKING each consumed update back to the pour span
        of the dispatch that produced it, staleness per link — the async
        fan-in a parent/child tree cannot express."""
        with obs_trace.tracer.span(
                "pour", root=True,
                attrs={"role": "engine", "version": self.version}) as psp:
            with obs_trace.span("wait.arrivals",
                                attrs={"version": self.version}):
                # the absorb loop advances the virtual clock to the K-th
                # arrival; wall-wise it is the host draining the event
                # heap (device row slices included) — the async analog
                # of the sync server's wait.uploads
                self._absorb_until(self.k)
                entries = self.buffer.pour(self.version)
            psp.set_attr("poured", len(entries))
            for e in entries:
                if e.trace is not None:
                    psp.add_link(e.trace, client=int(e.client_id),
                                 staleness=int(e.staleness(self.version)),
                                 dispatch_version=int(e.version))
            return self._pour_step_traced(hyper, entries, psp)

    def _pour_step_traced(self, hyper: TrainHyper, entries,
                          psp) -> Dict[str, Any]:
        # host-side pour prep (staleness weights, buffer stack, cohort
        # draw, schedule device_put) — its own span so trace_report can
        # attribute the pour's host half, not just the dispatch
        # the with-form ends the span even when prep raises (device_put
        # OOM, shape errors) — a failed pour still flushes its host half
        with obs_trace.span("host.input", attrs={"version": self.version}):
            fn = self._staleness_fn()
            stal = np.asarray([e.staleness(self.version) for e in entries],
                              np.float64)
            pad = self.k - len(entries)
            if entries:
                # the ONE staleness implementation: relative mix + absolute
                # merge scale from core/async_rounds.pour_weights, fed to
                # the program as data (padded rows carry weight 0)
                norm_w, merge_scale = pour_weights(
                    [e.weight for e in entries], stal, fn, self.merge_alpha)
                buf_nw = np.concatenate([norm_w, np.zeros(pad, np.float32)])
            else:  # bootstrap / drained heap: a no-op pour
                buf_nw = np.zeros(self.k, np.float32)
                merge_scale = 0.0
            vecs = [e.update for e in entries] + [self._zero_row] * pad
            # pin the stacked buffer to the replicated sharding: the
            # bootstrap rows (fresh zeros, single-device sharding) and
            # steady-state rows (slices of the shard_map output, named
            # sharding) must present the SAME input sharding or pjit
            # recompiles the pour program on the bootstrap->steady-state
            # transition
            buf_mat = jax.device_put(self._stack_fn(vecs),
                                     self.repl_sharding)

            target = max(0, self.concurrency - self._inflight()
                         - len(self.buffer))
            cohort = self._draw_cohort(target)
            idx, active, work, plan = self._dispatch_plan(cohort)
            idx = jax.device_put(jnp.asarray(idx), self.client_sharding)
            active = jax.device_put(jnp.asarray(active),
                                    self.client_sharding)
            work = jax.device_put(jnp.asarray(work), self.client_sharding)
            round_key = jax.random.fold_in(self.rng, self._dispatch_seq)
            hyper_r = hyper.replace(round_idx=jnp.int32(self.version))
        if self._defended:
            dmask, row_mask, pour_ids, byz = self._defended_pour_data(
                entries)
            dstate = (self._defense_state
                      if self._defense_state is not None else {})
            (self.params, self.server_state, self.client_states, rows_mat,
             metrics, slot_mets, new_dstate, verdict,
             self._ring) = self._traced(
                "async_pour_defended", 1, self._pour_fn,
                self.params, self.server_state, self.train_data,
                self.client_states, idx, active, work, buf_mat,
                jnp.asarray(buf_nw), jnp.float32(merge_scale),
                jnp.float32(len(entries)), jnp.asarray(dmask),
                jnp.asarray(row_mask), jnp.asarray(pour_ids),
                jnp.asarray(byz), self._ring, dstate,
                jnp.int32(self.version % self._ring_r), round_key, hyper_r)
            if self._defense_state is not None:
                self._defense_state = new_dstate
            if self.selection.track and entries:
                # the defense's verdict is about the POURED clients (not
                # the freshly-dispatched cohort): reputation evidence, so
                # the arrival rotation stops re-dispatching benched
                # byzantine clients
                self.selection.note_results(
                    self.version, [e.client_id for e in entries], [],
                    verdict=verdict[:len(entries)])
        else:
            (self.params, self.server_state, self.client_states, rows_mat,
             metrics, slot_mets) = self._traced(
                "async_pour", 1, self._pour_fn,
                self.params, self.server_state, self.train_data,
                self.client_states, idx, active, work, buf_mat,
                jnp.asarray(buf_nw), jnp.float32(merge_scale),
                jnp.float32(len(entries)), round_key, hyper_r)
        with obs_trace.span("host.close", attrs={"version": self.version}):
            self._push_events(plan, rows_mat, ctx=psp.context)
            if self.selection.track:
                self.selection.note_results(
                    self.version, cohort,
                    slot_placement(cohort, self.n_devices, self.cpd),
                    slot_metrics=slot_mets)

            poured = len(entries)
            self.updates_aggregated += poured
            if poured:
                # pour-interval EMA: the clock the adaptive staleness cap
                # converts arrival latencies into version lag with
                dt = self.virtual_t - self._last_pour_t
                self._last_pour_t = self.virtual_t
                self._pour_interval_ema = (dt
                                           if self._pour_interval_ema is None
                                           else 0.8 * self._pour_interval_ema
                                           + 0.2 * dt)
                self.chaos_ledger.record_pour(
                    self.version,
                    arrivals=[{"client": e.client_id,
                               "staleness": e.staleness(self.version),
                               "arrival_t": e.arrival_t,
                               "dispatch_version": e.version}
                              for e in entries],
                    observed={"poured": poured,
                              "buffered": len(self.buffer),
                              "staleness_cap": self.staleness_cap,
                              "virtual_t": self.virtual_t})
                self.version += 1
        return {"metrics": metrics, "poured": poured,
                "staleness_mean": float(np.mean(stal)) if poured else 0.0,
                "staleness_max": int(np.max(stal)) if poured else 0}

    def _bootstrap(self, hyper: TrainHyper) -> None:
        """Dispatch the initial in-flight cohort (empty buffer — the
        program's zero-masked pour is a no-op on the model)."""
        if self._bootstrapped:
            return
        self._bootstrapped = True
        self._pour_step(hyper)  # buffer empty: trains, pours nothing

    # ------------------------------------------------------------------
    # sync-engine entry points that make no sense without a barrier
    def run_round(self, round_idx, hyper):  # pragma: no cover - guard
        raise NotImplementedError(
            "async_buffered has no per-round barrier; use run()")

    def run_rounds_fused(self, start_round, n_rounds, hyper):
        raise NotImplementedError(
            "async_buffered has no per-round barrier; use run()")

    def run(self, comm_round: Optional[int] = None) -> Dict[str, Any]:
        args = self.args
        pours = comm_round if comm_round is not None \
            else int(args.comm_round)
        hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                           epochs=int(args.epochs))
        t0 = time.time()
        restored = self._ckpt_latest()
        if restored is not None:
            step, st = restored
            self._load_ckpt_state(st)
            logger.info("resumed async state from checkpoint at pour %d "
                        "(version %d)", step, self.version)
        freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
        self._ensure_flops_model(hyper)
        self._bootstrap(hyper)
        stalls = 0
        while self.version < pours:
            rec_in = self._pour_step(hyper)
            if rec_in["poured"] == 0:
                # nothing buffered AND nothing in flight produced an
                # arrival — one redispatch retry, then refuse to spin
                stalls += 1
                if stalls > 2:
                    raise RuntimeError(
                        "async pour stalled: no updates in flight "
                        f"(concurrency={self.concurrency}, k={self.k})")
                continue
            stalls = 0
            v = self.version - 1  # the pour that just completed
            metrics = jax.device_get(rec_in["metrics"])
            rec: Dict[str, Any] = {"round": v,
                                   "virtual_t": self.virtual_t,
                                   "poured": rec_in["poured"],
                                   "staleness_mean": rec_in["staleness_mean"],
                                   "staleness_max": rec_in["staleness_max"]}
            cnt = max(float(metrics["count"]), 1.0)
            rec["train_loss"] = float(metrics["loss_sum"]) / cnt
            rec["train_acc"] = float(metrics["correct"]) / cnt
            if freq > 0 and (v % freq == 0 or v == pours - 1):
                stats = self._evaluate(self.params, self.fed.test["x"],
                                       self.fed.test["y"],
                                       self.fed.test["mask"])
                n = max(float(stats["count"]), 1.0)
                rec["test_acc"] = float(stats["correct"]) / n
                rec["test_loss"] = float(stats["loss_sum"]) / n
                logger.info("pour %d (staleness mean %.2f): test_acc=%.4f",
                            v, rec["staleness_mean"], rec["test_acc"])
            self.history.append(rec)
            if self.ckpt.enabled:
                self.ckpt.maybe_save(v, self._ckpt_state())
            mlops.log_round_info(pours, v)
            mlops.log({k: val for k, val in rec.items() if k != "round"},
                      step=v)
            if self.chaos.crash_due(v):
                self.ckpt.flush()
                raise ChaosCrash(v)
        self.ckpt.flush()
        # final metrics snapshot (see the sync engine's run())
        obs_metrics.flush_final(step=self.version - 1)
        wall = time.time() - t0
        last_eval = next((r for r in reversed(self.history)
                          if "test_acc" in r), None)
        if last_eval is None:
            if freq <= 0:
                last_eval = {"test_acc": None}
            else:
                stats = self._evaluate(self.params, self.fed.test["x"],
                                       self.fed.test["y"],
                                       self.fed.test["mask"])
                n = max(float(stats["count"]), 1.0)
                last_eval = {"test_acc": float(stats["correct"]) / n,
                             "test_loss": float(stats["loss_sum"]) / n}
        return {"params": self.params, "history": self.history,
                "wall_time_s": wall,
                "final_test_acc": last_eval["test_acc"],
                "final_test_loss": last_eval.get("test_loss"),
                "rounds": self.version,
                "virtual_time_s": self.virtual_t,
                "updates_aggregated": self.updates_aggregated}

    # ------------------------------------------------------------------
    # checkpointing: the async control state rides RoundCheckpointer next
    # to params/server_state/client_states — fixed shapes (buffer padded
    # to its hard bound, events to the concurrency) so the orbax template
    # never depends on how full the buffer was at the save
    _OPTIONAL_CKPT_KEYS = TPUSimulator._OPTIONAL_CKPT_KEYS + (
        "async_rounds",)

    def _ckpt_state(self):
        st = super()._ckpt_state()
        st["async_rounds"] = self._async_state_dict()
        return st

    def _load_ckpt_state(self, st):
        super()._load_ckpt_state(st)
        if "async_rounds" in st:
            self._async_load_state(st["async_rounds"])
        else:
            logger.warning(
                "checkpoint has no async_rounds leaf — async control "
                "state (buffer, in-flight cohort, virtual clock) resumes "
                "cold from the restored model")

    def _async_state_dict(self) -> Dict[str, np.ndarray]:
        n = self.fed.num_clients
        ev = sorted(self._events, key=lambda e: e[:2])
        e_rows = self.concurrency
        if len(ev) > e_rows:  # cannot happen by construction; be loud
            raise RuntimeError(f"{len(ev)} in-flight events > concurrency")
        ev_meta = np.zeros((e_rows, 7), np.float64)  # t,seq,kind,cid,ver,w,dur
        ev_vecs = np.zeros((e_rows, self._row_d), np.float32)
        ev_mask = np.zeros((e_rows,), np.float32)
        # the trailing trace context (observability only) is NOT
        # persisted: a resumed run replays identical pours, just without
        # links to spans from before the crash
        for i, (t, seq, kind, cid, ver, w, dur, vec, _ctx) in enumerate(ev):
            ev_meta[i] = (t, seq, kind, cid, ver, w, dur)
            if vec is not None:
                ev_vecs[i] = np.asarray(vec, np.float32)
            ev_mask[i] = 1.0
        idle = np.full((n,), -1, np.int64)
        for i, cid in enumerate(self._idle):
            idle[i] = cid
        out = {
            "scalars": np.asarray(
                [self.version, self.virtual_t, self._dispatch_seq,
                 self._evseq,
                 -1.0 if self._pour_interval_ema is None
                 else self._pour_interval_ema,
                 self._last_pour_t, self.updates_aggregated,
                 1.0 if self._bootstrapped else 0.0,
                 self.staleness_cap], np.float64),
            "buffer": self.buffer.state_dict(
                encode=lambda v: np.asarray(v, np.float32),
                pad_rows=2 * self.k, vec_dim=self._row_d),
            "ev_meta": ev_meta, "ev_vecs": ev_vecs, "ev_mask": ev_mask,
            "idle": idle,
            "lat_ema": self._lat_ema.copy(),
            "lat_seen": self._lat_seen.copy(),
            "last_arrival_t": self._last_arrival_t.copy(),
        }
        if self._defended:
            # the base-delta ring must survive a crash, or a resumed run
            # would re-base the restored buffer's stale rows against a
            # zeroed movement history and diverge from the uninterrupted
            # pour trajectory (fixed [R, D] shape — template-stable)
            out["ring"] = np.asarray(jax.device_get(self._ring), np.float32)
        return out

    def _async_load_state(self, st: Dict[str, np.ndarray]) -> None:
        sc = np.asarray(st["scalars"], np.float64)
        (self.version, self.virtual_t, self._dispatch_seq, self._evseq,
         pie, self._last_pour_t, self.updates_aggregated) = (
            int(sc[0]), float(sc[1]), int(sc[2]), int(sc[3]), float(sc[4]),
            float(sc[5]), int(sc[6]))
        self._bootstrapped = sc[7] > 0.0
        self.staleness_cap = int(sc[8])
        self._pour_interval_ema = None if pie < 0 else pie
        self.buffer.load_state_dict(dict(st["buffer"]),
                                    decode=lambda a: jnp.asarray(a))
        self._events = []
        mask = np.asarray(st["ev_mask"], np.float32)
        meta = np.asarray(st["ev_meta"], np.float64)
        vecs = np.asarray(st["ev_vecs"], np.float32)
        for i in range(mask.shape[0]):
            if mask[i] <= 0.0:
                continue
            t, seq, kind, cid, ver, w, dur = meta[i]
            vec = jnp.asarray(vecs[i]) if int(kind) == _ARRIVE else None
            heapq.heappush(self._events, (float(t), int(seq), int(kind),
                                          int(cid), int(ver), float(w),
                                          float(dur), vec, None))
        self._idle = deque(int(c) for c in np.asarray(st["idle"], np.int64)
                           if c >= 0)
        self._lat_ema = np.asarray(st["lat_ema"], np.float64).copy()
        self._lat_seen = np.asarray(st["lat_seen"], np.float64).copy()
        # rebuild the O(1) running aggregates from the restored arrays
        seen = self._lat_seen > 0
        self._lat_ema_sum = float(np.sum(self._lat_ema[seen]))
        self._lat_seen_n = int(np.sum(seen))
        self._last_arrival_t = np.asarray(st["last_arrival_t"],
                                          np.float64).copy()
        if self._defended and "ring" in st:
            self._ring = jax.device_put(
                jnp.asarray(np.asarray(st["ring"], np.float32)),
                self.repl_sharding)
