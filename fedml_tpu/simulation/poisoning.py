"""Data-poisoning application shared by simulation engines — the engine-side
counterpart of the reference's ``ClientTrainer.update_dataset`` poisoning
hook (``core/alg_frame/client_trainer.py:38``)."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ..core.security import FedMLAttacker


def poison_dataset(fed, attacker: FedMLAttacker):
    """Apply label-flipping to the byzantine clients' training shards."""
    mask = attacker.byzantine_mask(np.arange(fed.num_clients))  # [K]
    y = np.asarray(fed.train.y)
    flipped = attacker.poison_labels(y, fed.num_classes)
    sel = mask.reshape((-1,) + (1,) * (y.ndim - 1)) > 0
    new_y = np.where(sel, flipped, y)
    new_train = fed.train.replace(y=jnp.asarray(new_y))
    return dataclasses.replace(fed, train=new_train)
