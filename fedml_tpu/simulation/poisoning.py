"""Data-poisoning application shared by simulation engines — the engine-side
counterpart of the reference's ``ClientTrainer.update_dataset`` poisoning
hook (``core/alg_frame/client_trainer.py:38``)."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ..core.security import FedMLAttacker


def poison_dataset(fed, attacker: FedMLAttacker):
    """Apply the configured data attack to the byzantine clients' shards:
    label flipping, or backdoor trigger stamping (all samples / edge-case
    variant that stamps only the globally rarest class — reference
    edge-case backdoor of ``core/security/attack/``)."""
    from ..core.security.attack import backdoor_stamp

    mask = attacker.byzantine_mask(np.arange(fed.num_clients))  # [K]
    y = np.asarray(fed.train.y)
    sel = mask.reshape((-1,) + (1,) * (y.ndim - 1)) > 0
    t = attacker.attack_type
    if t in ("backdoor", "edge_case_backdoor"):
        x = np.asarray(fed.train.x)
        target = int(getattr(attacker.args, "backdoor_target_label", 0) or 0)
        # x is [K, nb, bs, ...feature dims]; image iff features are H,W,C
        stamped = backdoor_stamp(x, image=(x.ndim == y.ndim + 3))
        if t == "edge_case_backdoor":
            # padding rows carry label 0 — count only real samples
            real = np.asarray(fed.train.mask).reshape(-1) > 0
            counts = np.bincount(y.reshape(-1)[real],
                                 minlength=fed.num_classes)
            rare = int(np.argmin(np.where(counts > 0, counts, counts.max())))
            edge = (y == rare)
            sel = sel & edge
        new_x = np.where(
            np.broadcast_to(sel.reshape(sel.shape + (1,) * (x.ndim - y.ndim)),
                            x.shape), stamped, x)
        new_y = np.where(sel, target, y)
        new_train = fed.train.replace(x=jnp.asarray(new_x),
                                      y=jnp.asarray(new_y))
        return dataclasses.replace(fed, train=new_train)
    flipped = attacker.poison_labels(y, fed.num_classes)
    new_y = np.where(sel, flipped, y)
    new_train = fed.train.replace(y=jnp.asarray(new_y))
    return dataclasses.replace(fed, train=new_train)
