"""FedMLRunner façade — picks the scenario runner.

Parity target: ``python/fedml/runner.py:19,34-53,181`` of the reference.
"""

from __future__ import annotations

from typing import Any, Optional

from .constants import (
    FEDML_SIMULATION_TYPE_SP,
    FEDML_SIMULATION_TYPE_TPU,
    FEDML_TRAINING_PLATFORM_CROSS_DEVICE,
    FEDML_TRAINING_PLATFORM_CROSS_SILO,
    FEDML_TRAINING_PLATFORM_CROSS_CLOUD,
    FEDML_TRAINING_PLATFORM_SIMULATION,
)


class FedMLRunner:
    """Dispatch to the right scenario runner based on
    ``args.training_type`` × ``args.backend`` (reference ``runner.py:34-53``)."""

    def __init__(self, args, device=None, dataset=None, model=None,
                 client_trainer=None, server_aggregator=None):
        self.args = args
        self.dataset = dataset
        self.model = model
        self.client_trainer = client_trainer
        self.server_aggregator = server_aggregator
        self.runner = self._build(args)

    def _build(self, args):
        ttype = getattr(args, "training_type", FEDML_TRAINING_PLATFORM_SIMULATION)
        if ttype == FEDML_TRAINING_PLATFORM_SIMULATION:
            return self._build_simulator(args)
        if ttype in (FEDML_TRAINING_PLATFORM_CROSS_SILO,
                     FEDML_TRAINING_PLATFORM_CROSS_CLOUD):
            from .cross_silo.runner import build_cross_silo_runner
            return build_cross_silo_runner(
                args, self.dataset, self.model,
                self.client_trainer, self.server_aggregator)
        if ttype == FEDML_TRAINING_PLATFORM_CROSS_DEVICE:
            from .cross_device.runner import build_cross_device_runner
            return build_cross_device_runner(args, self.dataset, self.model)
        raise ValueError(f"unknown training_type {ttype!r}")

    def _build_simulator(self, args):
        from .core.algframe.client_trainer import (ClassificationTrainer,
                                                   SequenceTrainer)
        from .optimizers.registry import create_optimizer
        fed, bundle = self.dataset, self.model
        if self.client_trainer is not None:
            spec = self.client_trainer
        elif fed.train.y.ndim >= 4:  # [clients, nb, bs, L] — per-token task
            spec = SequenceTrainer(bundle.apply)
        else:
            spec = ClassificationTrainer(bundle.apply)
        opt = create_optimizer(args, spec)
        backend = getattr(args, "backend", FEDML_SIMULATION_TYPE_TPU)
        if backend == FEDML_SIMULATION_TYPE_SP:
            from .simulation.sp.simulator import SPSimulator
            return SPSimulator(args, fed, bundle, opt, spec)
        from .simulation.tpu.engine import TPUSimulator
        return TPUSimulator(args, fed, bundle, opt, spec)

    def run(self, comm_round: Optional[int] = None) -> Any:
        return self.runner.run(comm_round)
