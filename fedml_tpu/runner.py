"""FedMLRunner façade — picks the scenario runner.

Parity target: ``python/fedml/runner.py:19,34-53,181`` of the reference.
"""

from __future__ import annotations

from typing import Any, Optional

from .constants import (
    FEDML_SIMULATION_TYPE_SP,
    FEDML_SIMULATION_TYPE_TPU,
    FEDML_TRAINING_PLATFORM_CROSS_DEVICE,
    FEDML_TRAINING_PLATFORM_CROSS_SILO,
    FEDML_TRAINING_PLATFORM_CROSS_CLOUD,
    FEDML_TRAINING_PLATFORM_SERVING,
    FEDML_TRAINING_PLATFORM_SIMULATION,
)


def _with_fedavg(args, create_optimizer, spec):
    """Protocol simulators drive plain FedAvg client steps internally."""
    import copy
    inner_args = copy.copy(args)
    inner_args.federated_optimizer = "FedAvg"
    return create_optimizer(inner_args, spec)


class FedMLRunner:
    """Dispatch to the right scenario runner based on
    ``args.training_type`` × ``args.backend`` (reference ``runner.py:34-53``)."""

    def __init__(self, args, device=None, dataset=None, model=None,
                 client_trainer=None, server_aggregator=None):
        self.args = args
        self.dataset = dataset
        self.model = model
        self.client_trainer = client_trainer
        self.server_aggregator = server_aggregator
        self.runner = self._build(args)

    def _build(self, args):
        ttype = getattr(args, "training_type", FEDML_TRAINING_PLATFORM_SIMULATION)
        if ttype == FEDML_TRAINING_PLATFORM_SIMULATION:
            return self._build_simulator(args)
        if ttype in (FEDML_TRAINING_PLATFORM_CROSS_SILO,
                     FEDML_TRAINING_PLATFORM_CROSS_CLOUD):
            from .cross_silo.runner import build_cross_silo_runner
            return build_cross_silo_runner(
                args, self.dataset, self.model,
                self.client_trainer, self.server_aggregator)
        if ttype == FEDML_TRAINING_PLATFORM_CROSS_DEVICE:
            from .cross_device.runner import build_cross_device_runner
            return build_cross_device_runner(args, self.dataset, self.model)
        if ttype == FEDML_TRAINING_PLATFORM_SERVING:
            from .serving.federated import FederatedServingRunner
            return FederatedServingRunner(
                args, self.dataset, self.model,
                self.client_trainer, self.server_aggregator)
        raise ValueError(f"unknown training_type {ttype!r}")

    # federated_optimizer values that dispatch to dedicated protocol
    # simulators below — none of them runs the TPU engine, so none can
    # honor `round_mode: async_buffered`; refuse the combination loudly
    # instead of silently running the protocol's own (synchronous) loop
    _PROTOCOL_FOS = frozenset((
        "centralized", "fedgkt", "fednas", "fedseg", "fedgan",
        "hierarchicalfl", "async_fedavg", "asyncfedavg",
        "decentralized_fl", "split_nn", "classical_vertical",
        "vertical_fl", "vfl", "turbo_aggregate", "turboaggregate"))

    def _build_simulator(self, args):
        from .core.algframe.client_trainer import make_trainer_spec
        from .optimizers.registry import create_optimizer
        fed, bundle = self.dataset, self.model
        fo = str(getattr(args, "federated_optimizer", "FedAvg")).lower()
        from .core.async_rounds import round_mode_from_args
        async_mode = round_mode_from_args(args) == "async_buffered"
        if async_mode and fo in self._PROTOCOL_FOS:
            raise ValueError(
                f"round_mode: async_buffered is a TPU-engine mode; "
                f"federated_optimizer {fo!r} runs its own protocol "
                "simulator and would silently ignore it (the SP async "
                "equivalent is federated_optimizer: Async_FedAvg)")
        if fo == "centralized":
            from .centralized import CentralizedTrainer
            return CentralizedTrainer(args, fed, bundle)
        # protocols with their own model/loss stacks dispatch before the
        # TrainerSpec is built (segmentation/GAN/NAS/GKT tasks have no
        # classification spec)
        if fo == "fedgkt":
            from .simulation.sp.fedgkt import FedGKTSimulator
            return FedGKTSimulator(args, fed)
        if fo == "fednas":
            from .simulation.sp.fednas import FedNASSimulator
            return FedNASSimulator(args, fed)
        if fo == "fedseg" or fed.task == "segmentation":
            from .simulation.sp.fedseg import FedSegSimulator
            return FedSegSimulator(args, fed)
        if fo == "fedgan" or isinstance(bundle, tuple):
            from .simulation.sp.fedgan import FedGANSimulator
            return FedGANSimulator(args, fed, bundle)
        spec = (self.client_trainer if self.client_trainer is not None
                else make_trainer_spec(fed, bundle))
        # protocol-level optimizers get dedicated simulators (reference
        # simulator.py:27-216 dispatches these to their own API stacks)
        if fo == "hierarchicalfl":
            from .simulation.sp.hierarchical import HierarchicalSimulator
            inner = _with_fedavg(args, create_optimizer, spec)
            return HierarchicalSimulator(args, fed, bundle, inner, spec)
        if fo in ("async_fedavg", "asyncfedavg"):
            from .simulation.sp.async_fedavg import AsyncFedAvgSimulator
            inner = _with_fedavg(args, create_optimizer, spec)
            return AsyncFedAvgSimulator(args, fed, bundle, inner, spec)
        if fo == "decentralized_fl":
            from .simulation.sp.decentralized import DecentralizedSimulator
            inner = _with_fedavg(args, create_optimizer, spec)
            return DecentralizedSimulator(args, fed, bundle, inner, spec)
        if fo == "split_nn":
            from .simulation.sp.split_nn import SplitNNSimulator
            return SplitNNSimulator(args, fed, bundle)
        if fo in ("classical_vertical", "vertical_fl", "vfl"):
            from .simulation.sp.vertical_fl import VerticalFLSimulator
            return VerticalFLSimulator(args, fed, bundle)
        if fo in ("turbo_aggregate", "turboaggregate"):
            from .simulation.sp.turbo_aggregate import TurboAggregateSimulator
            inner = _with_fedavg(args, create_optimizer, spec)
            return TurboAggregateSimulator(args, fed, bundle, inner, spec)
        opt = create_optimizer(args, spec)
        backend = getattr(args, "backend", FEDML_SIMULATION_TYPE_TPU)
        if backend == FEDML_SIMULATION_TYPE_SP:
            if async_mode:
                raise ValueError(
                    "round_mode: async_buffered is a TPU-engine mode; the "
                    "SP equivalent is federated_optimizer: Async_FedAvg")
            from .simulation.sp.simulator import SPSimulator
            return SPSimulator(args, fed, bundle, opt, spec)
        if async_mode:
            from .simulation.tpu.async_engine import AsyncBufferedSimulator
            return AsyncBufferedSimulator(
                args, fed, bundle, opt, spec,
                server_aggregator=self.server_aggregator)
        from .simulation.tpu.engine import TPUSimulator
        return TPUSimulator(args, fed, bundle, opt, spec,
                            server_aggregator=self.server_aggregator)

    def run(self, comm_round: Optional[int] = None) -> Any:
        return self.runner.run(comm_round)
