"""Federated serving runner — ``training_type: fedml_serving``.

Parity target: reference ``serving/client/*`` + ``serving/server/*`` and
the ``runner.py:137`` dispatch: a federated session whose END STATE is a
live model endpoint — silos fine-tune collaboratively, the server
aggregates and then serves the resulting global model.

Composition over new machinery: the training phase IS the cross-silo
runtime; this runner chains it with :class:`FedMLInferenceRunner` so the
aggregated params go live the moment the session finishes.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

logger = logging.getLogger(__name__)


class FederatedServingRunner:
    """role=server: run the FL session, then serve the aggregate over HTTP
    (blocking unless ``serving_block: false``); role=client: plain silo."""

    def __init__(self, args, dataset, model, client_trainer=None,
                 server_aggregator=None):
        from ..cross_silo.horizontal.runner import CrossSiloRunner
        self.args = args
        self.fed = dataset
        self.bundle = model
        self.role = str(getattr(args, "role", "client")).lower()
        self.inner = CrossSiloRunner(args, dataset, model, client_trainer,
                                     server_aggregator)
        self.inference_runner = None

    def run(self, comm_round: Optional[int] = None) -> Any:
        result = self.inner.run(comm_round)
        if self.role != "server" or not isinstance(result, dict):
            return result
        from . import CheckpointPredictor, FedMLInferenceRunner
        predictor = CheckpointPredictor(self.bundle, result["params"])
        port = int(getattr(self.args, "serving_port", 0) or 0)
        self.inference_runner = FedMLInferenceRunner(predictor, port=port)
        block = bool(getattr(self.args, "serving_block", False))
        if block:
            logger.info("federated serving: endpoint on :%d",
                        self.inference_runner.port)
            self.inference_runner.run()
        else:
            self.inference_runner.start()
            logger.info("federated serving: endpoint live on :%d",
                        self.inference_runner.port)
        result["serving_port"] = self.inference_runner.port
        return result
