"""Subprocess replica entrypoint: ``python -m fedml_tpu.serving.replica_main
<spec.json>`` builds a :class:`CheckpointPredictor` from a model artifact
and serves it over HTTP until killed.

This is the process-isolation analogue of the reference's container
deployment (``model_scheduler/device_model_deployment.py:61-333``: one
docker container per replica): a replica crash — up to ``kill -9`` — takes
down this process only, never the gateway or its siblings; the replica
controller's health check replaces the corpse. No container runtime exists
in this environment, so the isolation boundary is the OS process.

Spec schema (JSON):
  ``args``        flat config dict (model/dataset fields the bundle needs)
  ``params_path`` msgpack model artifact (``serving.save_model``)
  ``output_dim``  classifier width
  ``port_file``   where to write the bound port (the parent polls it)
  ``platform``    jax platform for the replica (default "cpu" — serving
                  replicas must not fight the trainer for the chip)
"""

from __future__ import annotations

import json
import os
import sys


def main() -> None:
    spec_path = sys.argv[1]
    with open(spec_path) as f:
        spec = json.load(f)
    # the SPEC decides the platform — an inherited JAX_PLATFORMS=tpu from
    # the trainer process must not make every replica fight it for the
    # chip (the whole point of platform='cpu' isolation)
    os.environ["JAX_PLATFORMS"] = spec.get("platform", "cpu")
    import jax
    jax.config.update("jax_platforms",
                      os.environ["JAX_PLATFORMS"].split(",")[0])
    # re-key the platform-scoped compile cache: the package import (and
    # its cache setup) happened under the PARENT's JAX_PLATFORMS — host
    # executables must not land in the tunnel-compiled cache dir
    from fedml_tpu import _enable_compile_cache
    _enable_compile_cache()

    from types import SimpleNamespace
    from . import CheckpointPredictor, FedMLInferenceRunner

    args = SimpleNamespace(**spec["args"])
    # a replica is a first-class observability citizen: its own JSONL
    # sink (run_<id>.jsonl, distinguished by pid-suffixed run_id so
    # sibling replicas never interleave one file), the obs knobs from
    # the spec's flat config, and — in batch mode — the engine's flight
    # recorder dumping on SIGTERM (the platform's shutdown signal)
    from fedml_tpu.core import mlops
    args.run_id = f"{getattr(args, 'run_id', '0')}_replica{os.getpid()}"
    mlops.init(args)
    # serving chaos in a SUBPROCESS replica is allowed to be lethal:
    # crash-at-request-N exits this process for real (the gateway's
    # health-aware failover + the set's health check are what recover)
    from fedml_tpu.core.chaos import ServingChaosInjector
    chaos = ServingChaosInjector.from_args(args, hard_crash=True)
    if spec.get("kind") == "causal_lm":
        # LLM template replica: chat route mounted, artifact + bundle
        # rebuilt from the spec's flat config
        from .llm_template import CausalLMPredictor, ChatCompletionRunner
        predictor = CausalLMPredictor.from_artifact(
            args, spec["params_path"])
        runner = ChatCompletionRunner(predictor, chaos=chaos)
        if predictor.engine is not None:
            from fedml_tpu.core.obs import flight as obs_flight
            obs_flight.install_signal_dump(
                predictor.engine.flight, predictor.engine._flight_path)
    else:
        predictor = CheckpointPredictor.from_files(
            args, spec["params_path"], int(spec["output_dim"]))
        runner = FedMLInferenceRunner(predictor, chaos=chaos)
    port = runner.start()
    # graceful SIGTERM drain (the drain-before-kill scale-down path):
    # stop accepting, let the engine finish/flush, then exit 0 — so a
    # scale-down victim's in-flight work resolves instead of dying
    # mid-stream. SIGKILL remains the crash path chaos exercises.
    import signal
    import threading
    stop_evt = threading.Event()

    def _graceful(_sig, _frm):
        stop_evt.set()

    signal.signal(signal.SIGTERM, _graceful)
    port_file = spec.get("port_file")
    if port_file:
        tmp = f"{port_file}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            f.write(str(port))
        os.replace(tmp, port_file)
    # serve until terminated; the server thread keeps running while the
    # main thread waits on the shutdown signal
    while not stop_evt.wait(0.5):
        if not runner._thread.is_alive():
            return
    close = getattr(predictor, "close", None)
    if callable(close):
        try:
            close()   # engine stop: drains the loop + flushes metrics
        except Exception:
            pass
    runner.stop()


if __name__ == "__main__":
    main()
