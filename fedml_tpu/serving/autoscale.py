"""Serving autoscaler: policies + replica set + scale-out gateway.

Parity target: reference ``model_scheduler/autoscaler/autoscaler.py``
(policy classes :20,70,135,186 — EWM of QPS, concurrency, traffic
lookback — consulted by the deploy agents to resize endpoint replicas)
and the inference gateway (``device_model_inference.py``). Local-first
redesign: replicas are in-process :class:`FedMLInferenceRunner` instances
(the docker-container analogue without a container runtime); the
:class:`Gateway` fronts them with round-robin dispatch and records the
QPS/latency series the policies consume; :class:`Autoscaler` applies a
policy on a cadence and grows/shrinks the replica set.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import threading
import time
import urllib.request
from collections import deque
from typing import Deque, List, Optional

logger = logging.getLogger(__name__)


# ------------------------------------------------------------- policies ----

@dataclasses.dataclass
class EWMPolicy:
    """Exponentially-weighted moving average of per-replica QPS (reference
    ``EWMPolicy`` :70): scale so that EWM(qps)/replica stays under
    ``target_qps_per_replica``."""
    target_qps_per_replica: float = 10.0
    alpha: float = 0.5
    _ewm: Optional[float] = None

    def desired_replicas(self, qps: float, latency_s: float,
                         current: int) -> int:
        self._ewm = (qps if self._ewm is None
                     else self.alpha * qps + (1 - self.alpha) * self._ewm)
        return max(1, math.ceil(self._ewm / self.target_qps_per_replica))


@dataclasses.dataclass
class ConcurrencyPolicy:
    """Little's-law concurrency policy (reference ``ConcurrentQueryPolicy``
    :135): in-flight = qps x latency; one replica sustains
    ``target_concurrency``. ``latency_signal`` picks which latency the
    autoscaler feeds in — ``"p99"`` makes in-flight a tail estimate, so
    the fleet sizes for the slow requests batching directly shapes, not
    the mean the fast ones dominate."""
    target_concurrency: float = 4.0
    latency_signal: str = "mean"    # mean | p50 | p99

    def desired_replicas(self, qps: float, latency_s: float,
                         current: int) -> int:
        inflight = qps * max(latency_s, 1e-6)
        return max(1, math.ceil(inflight / self.target_concurrency))


@dataclasses.dataclass
class LookbackPolicy:
    """Scale on the max QPS seen in a trailing window (reference
    ``MeetTrafficDemandPolicy`` :186 shape): headroom for bursts.
    ``max_latency_s`` adds a tail-latency guard on ``latency_signal``
    (default p99): while the observed tail exceeds it, demand-based
    sizing is overridden upward by one replica per step."""
    target_qps_per_replica: float = 10.0
    window: int = 10
    max_latency_s: float = 0.0      # 0 = QPS-only (original behavior)
    latency_signal: str = "p99"
    _hist: Deque[float] = dataclasses.field(default_factory=deque)

    def desired_replicas(self, qps: float, latency_s: float,
                         current: int) -> int:
        self._hist.append(qps)
        while len(self._hist) > self.window:
            self._hist.popleft()
        peak = max(self._hist)
        desired = max(1, math.ceil(peak / self.target_qps_per_replica))
        if self.max_latency_s > 0 and latency_s > self.max_latency_s:
            desired = max(desired, current + 1)
        return desired


@dataclasses.dataclass
class FleetSLOView:
    """One autoscaler step's aggregated fleet scrape: worst-replica
    TTFT/ITL p99 from each replica's ``/healthz`` ``slo`` payload (exact
    trailing-window percentiles, not bucketed exposition), summed queue
    depth, the MINIMUM KV admission headroom across replicas (the
    replica that will shed first), and the gateway's own p99."""
    ttft_p99_s: float = 0.0
    itl_p99_s: float = 0.0
    queue_depth: int = 0
    kv_headroom_min: Optional[int] = None    # None = no replica reported
    gateway_p99_s: float = 0.0
    replicas: int = 0


@dataclasses.dataclass
class SLOPolicy:
    """SLO-driven autoscaling (ISSUE 17): close the loop from the
    serving SLO instruments to replica count. Scale UP one replica while
    any enabled target is breached — p99 TTFT / p99 ITL over target,
    fleet queue depth past ``queue_depth_per_replica * current``, or
    minimum KV admission headroom under ``kv_headroom_min`` (the
    saturation signal: a replica about to shed). Scale DOWN one replica
    only when every enabled tail sits under ``scale_down_idle_factor``
    of its target AND the fleet queue is empty. ``cooldown_s`` gates
    consecutive moves so one burst cannot staircase the fleet. Targets
    of 0 disable that signal."""
    ttft_p99_s: float = 0.0
    itl_p99_s: float = 0.0
    queue_depth_per_replica: float = 4.0
    kv_headroom_min: int = 1
    scale_down_idle_factor: float = 0.3
    cooldown_s: float = 5.0
    latency_signal: str = "p99"
    _last_scale_ts: float = dataclasses.field(default=0.0, repr=False)

    def breaches(self, fleet: FleetSLOView, current: int) -> List[str]:
        """Which enabled scale-up signals are breached right now."""
        out = []
        if self.ttft_p99_s > 0 and fleet.ttft_p99_s > self.ttft_p99_s:
            out.append("ttft_p99")
        if self.itl_p99_s > 0 and fleet.itl_p99_s > self.itl_p99_s:
            out.append("itl_p99")
        if (self.queue_depth_per_replica > 0
                and fleet.queue_depth > self.queue_depth_per_replica
                * max(current, 1)):
            out.append("queue_depth")
        if (self.kv_headroom_min > 0 and fleet.kv_headroom_min is not None
                and fleet.kv_headroom_min < self.kv_headroom_min):
            out.append("kv_headroom")
        return out

    def desired_from_fleet(self, fleet: FleetSLOView, current: int) -> int:
        now = time.time()
        if now - self._last_scale_ts < self.cooldown_s:
            return current
        if self.breaches(fleet, current):
            self._last_scale_ts = now
            return current + 1
        idle = fleet.queue_depth == 0
        if self.ttft_p99_s > 0:
            idle = idle and (fleet.ttft_p99_s
                             < self.scale_down_idle_factor
                             * self.ttft_p99_s)
        if self.itl_p99_s > 0:
            idle = idle and (fleet.itl_p99_s
                             < self.scale_down_idle_factor
                             * self.itl_p99_s)
        if idle and current > 1:
            self._last_scale_ts = now
            return current - 1
        return current

    def desired_replicas(self, qps: float, latency_s: float,
                         current: int) -> int:
        """Legacy-signature fallback (an Autoscaler wired to a plain
        gateway window): the gateway's ``latency_signal`` percentile
        stands in for TTFT — breach scales up, deep idle scales down."""
        fleet = FleetSLOView(ttft_p99_s=latency_s, gateway_p99_s=latency_s,
                             queue_depth=0, replicas=current)
        return self.desired_from_fleet(fleet, current)


# ---------------------------------------------------------- replica set ----

class SubprocessReplica:
    """One replica as a CHILD PROCESS serving HTTP — the process-isolation
    analogue of the reference's per-replica docker container
    (``device_model_deployment.py:61-333``): a crash (up to ``kill -9``)
    kills only this process; the controller's health check replaces it.
    Same surface as FedMLInferenceRunner: ``start()``/``stop()``/``port``.
    """

    def __init__(self, spec_path: str, startup_wait_s: float = 30.0):
        self.spec_path = spec_path
        self.startup_wait_s = float(startup_wait_s)
        self.port: Optional[int] = None
        self.proc = None

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def start(self) -> int:
        import subprocess
        import sys
        import tempfile
        import os
        fd, port_file = tempfile.mkstemp(suffix=".port")
        os.close(fd)
        os.unlink(port_file)
        with open(self.spec_path) as f:
            spec = json.load(f)
        spec["port_file"] = port_file
        child_spec = self.spec_path + f".{os.getpid()}.{id(self)}"
        try:
            with open(child_spec, "w") as f:
                json.dump(spec, f)
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "fedml_tpu.serving.replica_main",
                 child_spec],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            deadline = time.time() + self.startup_wait_s
            while time.time() < deadline:
                if os.path.exists(port_file):
                    with open(port_file) as f:
                        self.port = int(f.read().strip())
                    return self.port
                if self.proc.poll() is not None:
                    break
                time.sleep(0.05)
            self.stop()
            raise RuntimeError(
                "subprocess replica never published its port")
        finally:
            # a crash-looping replica replaced by the health check every
            # few seconds must not accumulate temp spec/port files
            for p in (port_file, child_spec):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    def stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5.0)
            except Exception:
                self.proc.kill()
                try:  # reap: an ignored SIGTERM must not leave a zombie
                    self.proc.wait(timeout=5.0)
                except Exception:
                    pass


def subprocess_replica_factory(args, params_path: str, output_dim: int,
                               workdir: str, platform: str = "cpu",
                               kind: str = "classifier"):
    """Build a ``replica_factory`` for :class:`ReplicaSet`: each call
    yields a fresh un-started :class:`SubprocessReplica` serving the given
    model artifact. ``kind='causal_lm'`` makes every replica an LLM
    template server (chat route mounted) instead of a classifier."""
    import os
    spec = {"args": {k: v for k, v in vars(args).items()
                     if isinstance(v, (str, int, float, bool, type(None)))},
            "params_path": os.path.abspath(params_path),
            "output_dim": int(output_dim), "platform": platform,
            "kind": kind}
    os.makedirs(workdir, exist_ok=True)
    spec_path = os.path.join(workdir, "replica_spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    return lambda: SubprocessReplica(spec_path)


class ReplicaSet:
    """N live inference replicas over one factory (the container-fleet
    analogue; ``scale_to`` is the rolling update). Replicas are in-process
    runners via ``predictor_factory``, or isolated child processes via
    ``replica_factory`` (see :class:`SubprocessReplica`)."""

    def __init__(self, predictor_factory=None, min_replicas: int = 1,
                 max_replicas: int = 8, replica_factory=None,
                 runner_cls=None, drain_grace_s: float = 0.0):
        from . import FedMLInferenceRunner
        if (predictor_factory is None) == (replica_factory is None):
            raise ValueError("pass exactly one of predictor_factory / "
                             "replica_factory")
        # runner_cls lets templates mount extra routes on every replica
        # (e.g. the LLM template's ChatCompletionRunner)
        self._runner_cls = runner_cls or FedMLInferenceRunner
        self.predictor_factory = predictor_factory
        self.replica_factory = replica_factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        # drain-before-kill on scale-down: a shrink victim leaves
        # rotation immediately but gets this long to finish in-flight
        # streams before stop() (0 = legacy immediate stop)
        self.drain_grace_s = float(drain_grace_s)
        self.replicas: List = []
        self._lock = threading.Lock()
        # ports the gateway must route around while their replica
        # finishes in-flight work before a restart (the drain seam)
        self._draining: set = set()
        self.scale_to(self.min_replicas)

    def _new_replica(self):
        if self.replica_factory is not None:
            return self.replica_factory()
        return self._runner_cls(self.predictor_factory())

    def _await_idle(self, port: int, grace_s: float) -> bool:
        """Poll a shrink victim's ``/healthz`` until its in-flight work
        drains (occupancy and queue depth both 0) or the grace expires.
        The victim already left rotation — no new traffic lands on it —
        so this only waits out streams it is mid-way through. A replica
        that stopped answering (or one without the engine fields) reads
        as idle: there is nothing left to wait for."""
        deadline = time.time() + float(grace_s)
        while time.time() < deadline:
            try:
                try:
                    r = urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=1.0)
                except urllib.error.HTTPError as e:
                    r = e      # a 503 body still carries the health JSON
                with r:
                    h = json.load(r)
            except Exception:  # noqa: BLE001 — gone/unreadable = idle
                return True
            busy = (int(h.get("occupancy", 0) or 0)
                    + int(h.get("queue_depth", 0) or 0))
            if not busy:
                return True
            time.sleep(0.05)
        return False

    def scale_to(self, n: int, drain_grace_s: Optional[float] = None
                 ) -> int:
        """Grow/shrink to ``n``. Replica start/stop happens OUTSIDE the
        set lock — a subprocess replica takes seconds to come up, and the
        gateway needs the same lock for every request; scaling up under
        load must not stall the traffic it is scaling for.

        ``drain_grace_s`` (None = the set default) > 0 makes shrink
        drain-before-kill: the victim leaves rotation at once, then gets
        up to the grace for in-flight streams to finish before stop."""
        n = min(max(n, self.min_replicas), self.max_replicas)
        grace = (getattr(self, "drain_grace_s", 0.0)
                 if drain_grace_s is None else float(drain_grace_s))
        while True:
            victim = None
            with self._lock:
                cur = len(self.replicas)
                if cur > n:
                    victim = self.replicas.pop()
                    # replicas bind ephemeral ports a successor may be
                    # handed again — a stale drain mark would hide it
                    self._draining.discard(victim.port)
            if victim is not None:
                if grace > 0 and victim.port is not None:
                    if not self._await_idle(victim.port, grace):
                        logger.warning(
                            "replica :%d still busy after %.1fs drain "
                            "grace — stopping anyway", victim.port, grace)
                victim.stop()
                logger.info("replica down (%d left)", len(self))
                continue
            if cur >= n:
                return n
            runner = self._new_replica()
            runner.start()
            with self._lock:
                if len(self.replicas) < n:
                    self.replicas.append(runner)
                    logger.info("replica up on :%d (%d total)", runner.port,
                                len(self.replicas))
                    continue
            runner.stop()  # target shrank underneath us

    def ports(self, include_draining: bool = False) -> List[int]:
        with self._lock:
            if include_draining:
                return [r.port for r in self.replicas]
            return [r.port for r in self.replicas
                    if r.port not in self._draining]

    # --- drain / zero-downtime restart ------------------------------------
    def drain(self, port: int) -> None:
        """Take ``port`` out of gateway rotation WITHOUT stopping it: the
        replica finishes its in-flight requests while new traffic routes
        around it."""
        with self._lock:
            self._draining.add(int(port))

    def undrain(self, port: int) -> None:
        with self._lock:
            self._draining.discard(int(port))

    def draining(self) -> List[int]:
        with self._lock:
            return sorted(self._draining)

    def restart_replica(self, port: int, grace_s: float = 0.5,
                        ready_wait_s: float = 10.0) -> int:
        """Drain -> finish-in-flight -> restart, one replica: the
        zero-downtime reload seam. The victim leaves rotation first, a
        grace period lets requests already routed to it complete, the
        replacement comes up READY before the victim dies, and only then
        is the old process stopped. Returns the fresh replica's port.
        Subprocess replicas re-read their spec/artifact from disk, so
        this is also how an updated on-disk model (or adapter bank) goes
        live with zero dropped requests."""
        with self._lock:
            idx = next((i for i, r in enumerate(self.replicas)
                        if r.port == int(port)), None)
            if idx is None:
                raise ValueError(f"no replica on port {port}")
            victim = self.replicas[idx]
        self.drain(victim.port)
        try:
            if grace_s > 0:
                time.sleep(grace_s)   # in-flight finishes off-rotation
            fresh = self._start_ready(wait_s=ready_wait_s)
        except Exception:
            self.undrain(victim.port)   # failed swap: keep serving
            raise
        with self._lock:
            if idx < len(self.replicas) and self.replicas[idx] is victim:
                self.replicas[idx] = fresh
            else:   # set changed underneath (scale event): keep both
                self.replicas.append(fresh)
        self.undrain(victim.port)
        try:
            victim.stop()
        except Exception:
            logger.exception("drained replica on :%d failed to stop",
                             victim.port)
        logger.info("replica :%d drained and restarted as :%d",
                    victim.port, fresh.port)
        return fresh.port

    def rolling_restart(self, grace_s: float = 0.5) -> None:
        """Drain-restart every replica one at a time from the CURRENT
        factory — the rolling reload the adapter hot-swap flow needs."""
        for port in list(self.ports(include_draining=True)):
            try:
                self.restart_replica(port, grace_s=grace_s)
            except ValueError:
                continue   # scaled away mid-rollout

    # --- health + rolling update (reference
    # ``device_replica_controller.py``: health-based replacement, one-at-a-
    # time rollout) -------------------------------------------------------
    def _probe(self, port: int, timeout: float = 2.0) -> bool:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/ready", timeout=timeout) as r:
                return r.status == 200
        except Exception:
            return False

    def _start_ready(self, wait_s: float = 10.0):
        """Start a fresh replica and wait until it answers /ready —
        traffic must never be pointed at a cold server."""
        runner = self._new_replica()
        runner.start()
        deadline = time.time() + wait_s
        while time.time() < deadline:
            if self._probe(runner.port, timeout=1.0):
                return runner
            time.sleep(0.05)
        runner.stop()
        raise RuntimeError("replacement replica never became ready")

    def health_check(self) -> int:
        """Probe every replica; replace dead ones with fresh ready servers.
        Returns the number replaced. The autoscaler calls this each step —
        the set HEALS, it does not just resize."""
        with self._lock:
            snapshot = list(enumerate(self.replicas))
        replaced = 0
        for i, runner in snapshot:
            if self._probe(runner.port):
                continue
            logger.warning("replica on :%d failed health check — replacing",
                           runner.port)
            fresh = self._start_ready()
            with self._lock:
                if i < len(self.replicas) and self.replicas[i] is runner:
                    self.replicas[i] = fresh
                    self._draining.discard(runner.port)  # port may recycle
                    replaced += 1
                else:  # set changed underneath (scale event): discard
                    fresh.stop()
                    continue
            try:
                runner.stop()
            except Exception:
                pass
        return replaced

    def rolling_update(self, predictor_factory=None,
                       replica_factory=None) -> None:
        """Replace every replica with one built from the new factory,
        one at a time, new-up-and-ready before old-down — the gateway keeps
        serving throughout (reference rolling-upgrade flow)."""
        if replica_factory is not None:
            self.replica_factory = replica_factory
        elif predictor_factory is not None:
            if self.replica_factory is not None:
                # subprocess mode: a bare positional factory is a replica
                # factory
                self.replica_factory = predictor_factory
            else:
                self.predictor_factory = predictor_factory
        # both None: respawn from the CURRENT factory (subprocess mode
        # re-reads the spec/artifact from disk on every start, so a bare
        # rolling_update() rolls an updated on-disk model out)
        with self._lock:
            n = len(self.replicas)
        for i in range(n):
            fresh = self._start_ready()
            with self._lock:
                if i >= len(self.replicas):  # shrunk mid-rollout
                    fresh.stop()
                    return
                old = self.replicas[i]
                self.replicas[i] = fresh
            # drain: a request the gateway routed to `old` JUST before the
            # swap is still in flight — stopping immediately resets it.
            time.sleep(0.25)
            old.stop()

    def stop(self) -> None:
        with self._lock:
            for r in self.replicas:
                r.stop()
            self.replicas.clear()
            self._draining.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self.replicas)


# -------------------------------------------------------------- gateway ----

@dataclasses.dataclass
class GatewayMetrics:
    """Trailing-window request metrics. Iterates as the legacy
    ``(qps, mean_latency)`` pair so existing unpacking call sites keep
    working; ``p50``/``p99`` carry the tail the autoscaler policies can
    target."""
    qps: float
    latency_s: float     # mean
    p50: float
    p99: float
    count: int

    def __iter__(self):
        return iter((self.qps, self.latency_s))

    def signal(self, name: str) -> float:
        return {"mean": self.latency_s, "p50": self.p50,
                "p99": self.p99}[str(name)]


class Gateway:
    """Health-aware round-robin HTTP front over a ReplicaSet that records
    the QPS/latency series policies consume (reference inference
    gateway).

    Windowed tail stats live in ONE place: the ``core/obs``
    :class:`~fedml_tpu.core.obs.metrics.LatencyWindow` (exact
    nearest-rank percentiles over the trailing window — the autoscaler's
    signal is never bucket-quantized). Every request's latency also
    lands in the registry histogram (``serving_gateway_latency_seconds``)
    for the ``/metrics`` exposition and JSONL snapshots. An active span
    on the calling thread is forwarded to the replica as a W3C
    ``traceparent`` header, so the replica-side request trace joins the
    caller's.

    Failover (ISSUE 11): routing consults replica health — a port that
    failed a connect or answered ``/healthz`` non-200 is quarantined for
    ``unhealthy_ttl_s`` and routed around; a retry never re-picks the
    port that just failed while an untried port remains (only once EVERY
    live port has failed this request does it fall back to re-picking —
    on a small fleet a transient flake beats refusing outright);
    draining replicas are excluded by ``ReplicaSet.ports()``. Retries
    pace themselves on the shared ``communication/backoff`` policy, and
    a replica 503 (load shed / parked-unhealthy engine) is routed around
    too — the request never reached a predictor, so re-routing is safe.
    Read timeouts and other HTTP errors DID reach a replica and surface
    unchanged."""

    def __init__(self, replica_set: ReplicaSet, window_s: float = 5.0,
                 unhealthy_ttl_s: float = 2.0, max_failovers: int = 3,
                 backoff_seed: Optional[int] = None, chaos=None,
                 cache_aware: bool = False, digest_chars: int = 128,
                 scrape_ttl_s: float = 1.0, spill_headroom: int = 1,
                 heal_probe: bool = False):
        from ..core.obs import metrics as obs_metrics
        self.replica_set = replica_set
        self.window_s = float(window_s)
        self.unhealthy_ttl_s = float(unhealthy_ttl_s)
        self.max_failovers = int(max_failovers)
        self.backoff_seed = backoff_seed
        self._chaos = chaos      # optional ServingChaosInjector
        # cache-aware routing (ISSUE 17, OFF = byte-identical routing):
        # a digest of the request's leading prompt bytes maps to the
        # replica whose prefix cache is warm for it; the warm pick is
        # admission-checked against the replica's KV headroom (a cheap
        # ttl-cached /healthz scrape) and spills to round-robin — without
        # rehoming — when the warm replica is saturated
        self.cache_aware = bool(cache_aware)
        self.digest_chars = int(digest_chars)
        self.scrape_ttl_s = float(scrape_ttl_s)
        self.spill_headroom = int(spill_headroom)
        # quarantine heal (satellite): OFF = legacy TTL-only rejoin;
        # ON = a quarantined port stays out past its TTL until heal()
        # probes it healthy (a sick replica can't flap back on a timer)
        self.heal_probe = bool(heal_probe)
        self._i = 0
        self._lock = threading.Lock()
        self._window = obs_metrics.LatencyWindow(window_s=self.window_s)
        self._unhealthy: dict = {}   # port -> quarantine expiry ts
        from collections import OrderedDict
        self._warm: "OrderedDict[str, int]" = OrderedDict()
        self._warm_cap = 4096
        self._slo_cache: dict = {}   # port -> (scrape_ts, headroom|None)
        # routing-decision tally (mirrors the obs counters; first-class
        # so benches/tests can read the split without registry scrapes)
        self.route_counts = {"warm_hit": 0, "warm_spill": 0, "cold": 0}

    # --- health cache ------------------------------------------------------
    def _mark_unhealthy(self, port: int, reason: str) -> None:
        from ..core.obs import metrics as obs_metrics
        with self._lock:
            self._unhealthy[int(port)] = time.time() + self.unhealthy_ttl_s
        obs_metrics.record_gateway_failover(reason)
        logger.warning("gateway: replica :%d quarantined (%s)", port,
                       reason)

    def _is_quarantined(self, port: int) -> bool:
        with self._lock:
            exp = self._unhealthy.get(int(port))
            if exp is None:
                return False
            if time.time() < exp:
                return True
            if not self.heal_probe:
                del self._unhealthy[int(port)]
                return False
        # TTL expired under heal_probe: the port is eligible — probe it
        # NOW (heal-on-demand). Routing must not depend on an external
        # heal() loop running: without this, one conn-drop quarantines
        # a warm home until the next autoscaler step, spilling every
        # request homed there. Self-rate-limited — a failing probe
        # re-arms the TTL, so a sick port costs at most one probe per
        # TTL window.
        return not self._heal_port(int(port))

    def heal(self) -> int:
        """Probe quarantined replicas whose TTL expired: a passing
        ``/healthz`` rejoins the port to rotation; a failing one re-arms
        the quarantine for another TTL. No-op with ``heal_probe`` off
        (legacy timer-only rejoin). Returns the number healed. The
        autoscaler calls this each step."""
        if not self.heal_probe:
            return 0
        now = time.time()
        with self._lock:
            expired = [p for p, exp in self._unhealthy.items()
                       if now >= exp]
        return sum(1 for port in expired if self._heal_port(port))

    def _heal_port(self, port: int) -> bool:
        """Probe ONE quarantine-expired port: a passing ``/healthz``
        rejoins it to rotation (True); a failing one re-arms the
        quarantine for another TTL (False). Called from ``heal()`` and
        inline from ``_is_quarantined`` (heal-on-demand at pick time)."""
        from ..core.obs import metrics as obs_metrics
        ok = False
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz",
                    timeout=1.0) as r:
                ok = r.status == 200
        except Exception:  # noqa: BLE001 — any failure = still sick
            ok = False
        with self._lock:
            if port not in self._unhealthy:
                return True   # raced with a concurrent heal/mark
            if ok:
                del self._unhealthy[port]
            else:
                self._unhealthy[port] = (time.time()
                                         + self.unhealthy_ttl_s)
        if ok:
            obs_metrics.record_gateway_heal(port)
            logger.info("gateway: replica :%d healed — rejoining "
                        "rotation", port)
        return ok

    def probe_health(self, port: int, timeout: float = 1.0) -> bool:
        """GET the replica's ``/healthz``; non-200 (a tripped watchdog,
        a parked engine) or no answer quarantines the port."""
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz",
                    timeout=timeout) as r:
                if r.status == 200:
                    return True
        except Exception:  # noqa: BLE001 — any failure = unhealthy
            pass
        self._mark_unhealthy(port, "healthz")
        return False

    # --- cache-aware routing ----------------------------------------------
    def _routing_digest(self, request: dict) -> Optional[str]:
        """Digest of the request's leading prompt bytes. Under the byte
        tokenizer one char is one token, so the first ``digest_chars``
        characters ARE the leading token blocks — same-system-prompt
        (and same-conversation-head) traffic shares a digest and sticks
        to the replica whose prefix cache already holds those blocks."""
        try:
            msgs = request.get("messages")
            if msgs:
                text = "\n".join(str(m.get("content", ""))
                                 for m in msgs if isinstance(m, dict))
            else:
                text = str(request.get("prompt")
                           or request.get("inputs") or "")
        except Exception:  # noqa: BLE001 — routing must never raise
            return None
        if not text:
            return None
        import hashlib
        return hashlib.sha1(
            text[:self.digest_chars].encode("utf-8", "replace")
        ).hexdigest()[:16]

    def _remember_warm(self, digest: str, port: int) -> None:
        with self._lock:
            self._warm[digest] = int(port)
            self._warm.move_to_end(digest)
            while len(self._warm) > self._warm_cap:
                self._warm.popitem(last=False)

    def _replica_headroom(self, port: int) -> Optional[int]:
        """KV admission headroom from a ttl-cached ``/healthz`` scrape —
        the warm pick's saturation check. None = unknown (no engine slo
        payload, or the replica did not answer); unknown never blocks
        routing."""
        now = time.time()
        with self._lock:
            ent = self._slo_cache.get(int(port))
            if ent is not None and now - ent[0] < self.scrape_ttl_s:
                return ent[1]
        headroom: Optional[int] = None
        try:
            try:
                r = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=0.5)
            except urllib.error.HTTPError as e:
                r = e          # 503 still carries the health JSON body
            with r:
                h = json.load(r)
            hr = (h.get("slo") or {}).get("kv_headroom_requests")
            if hr is not None and int(hr) >= 0:
                headroom = int(hr)
        except Exception:  # noqa: BLE001
            headroom = None
        with self._lock:
            self._slo_cache[int(port)] = (now, headroom)
        return headroom

    def _pick_port(self, tried: set, verify_health: bool,
                   digest: Optional[str] = None) -> Optional[int]:
        """Next routable port: round-robin over live, non-draining,
        non-quarantined ports the request has not tried yet. With
        ``verify_health`` (retry attempts), the candidate's ``/healthz``
        is consulted before traffic lands on it. Falls back to
        quarantined-but-untried ports rather than refusing — a wrong
        quarantine must not 503 the fleet.

        With cache-aware routing and a ``digest``, the digest's warm
        replica wins while it is routable and has KV admission headroom;
        a saturated warm replica spills this request to round-robin
        WITHOUT rehoming the digest (its cache stays warm where it is);
        a digest whose home left the fleet — or one never seen — records
        the round-robin pick as its new home."""
        ports = self.replica_set.ports()
        route_outcome: Optional[str] = None
        if self.cache_aware and digest is not None and ports:
            from ..core.obs import metrics as obs_metrics
            with self._lock:
                warm = self._warm.get(digest)
            if warm is not None and warm in ports:
                if warm not in tried and not self._is_quarantined(warm):
                    headroom = self._replica_headroom(warm)
                    if headroom is None \
                            or headroom >= self.spill_headroom:
                        with self._lock:
                            self.route_counts["warm_hit"] += 1
                        obs_metrics.record_gateway_route("warm_hit")
                        return warm
                # saturated / tried / quarantined: spill, keep the home
                route_outcome = "warm_spill"
            else:
                route_outcome = "cold"   # new digest or home scaled away
        candidates = [p for p in ports
                      if p not in tried and not self._is_quarantined(p)]
        if not candidates:
            candidates = [p for p in ports if p not in tried]
        if not candidates:
            # every live port already failed this request once: a
            # last-resort re-pick (transient connect flake on a small
            # fleet) beats refusing while retry budget remains
            candidates = list(ports)
        while candidates:
            with self._lock:
                port = candidates[self._i % len(candidates)]
                self._i += 1
            if verify_health and len(candidates) > 1 \
                    and not self.probe_health(port):
                candidates.remove(port)
                continue
            if route_outcome is not None:
                from ..core.obs import metrics as obs_metrics
                if route_outcome == "cold":
                    self._remember_warm(digest, port)
                with self._lock:
                    self.route_counts[route_outcome] += 1
                obs_metrics.record_gateway_route(route_outcome)
            return port
        return None

    def _connect(self, request: dict, timeout: float, path: str):
        """The ONE failover loop (predict and stream share it): pick a
        routable port, ride out chaos connection drops, quarantine 503
        sheds (honoring the replica's Retry-After) and connection-phase
        failures, and return an OPEN ``HTTPResponse`` from the first
        replica that starts answering. Raises the last failure (or
        RuntimeError) once every attempt is spent."""
        from ..core.distributed.communication.backoff import backoff_delays
        from ..core.obs import trace as obs_trace
        body = json.dumps(request).encode()
        headers = {"Content-Type": "application/json"}
        cur = obs_trace.current_span()
        if cur is not None and cur.traceparent():
            headers["traceparent"] = cur.traceparent()
        delays = backoff_delays(base_s=0.05, factor=2.0, max_s=0.5,
                                seed=self.backoff_seed)
        digest = (self._routing_digest(request)
                  if self.cache_aware else None)
        tried: set = set()
        last_exc: Optional[Exception] = None
        for attempt in range(self.max_failovers + 1):
            port = self._pick_port(tried, verify_health=attempt > 0,
                                   digest=digest)
            if port is None:
                break   # every live port tried (or none live)
            tried.add(port)
            if self._chaos is not None and self._chaos.connection_drop():
                # injected gateway->replica connection drop: the fault
                # the failover path exists for, at its exact seam
                last_exc = ConnectionError(
                    f"chaos: injected connection drop to :{port}")
                self._mark_unhealthy(port, "conn_drop")
                time.sleep(next(delays))
                continue
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", data=body,
                headers=headers)
            try:
                return urllib.request.urlopen(req, timeout=timeout)
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    # shed or parked-unhealthy replica: the request was
                    # refused before any predictor ran — routing around
                    # is safe, and the replica asked us to back off
                    self._mark_unhealthy(port, "http_503")
                    last_exc = e
                    retry_after = e.headers.get("Retry-After")
                    e.close()
                    delay = next(delays)
                    if retry_after:
                        try:
                            delay = min(float(retry_after), 2.0)
                        except ValueError:
                            pass
                    time.sleep(delay)
                    continue
                raise  # the replica answered; its answer stands
            except (urllib.error.URLError, OSError) as e:
                reason = getattr(e, "reason", e)
                if not isinstance(reason, ConnectionError):
                    raise   # read timeout etc: reached a replica
                # connection-phase failure: never re-pick this port for
                # THIS request (satellite 1), quarantine it for others
                self._mark_unhealthy(port, "connect")
                last_exc = e
                time.sleep(next(delays))
                continue
        if last_exc is not None:
            raise last_exc
        raise RuntimeError("no live replicas")

    def _observe_latency(self, t0: float) -> None:
        from ..core.obs import metrics as obs_metrics
        dt = time.perf_counter() - t0
        obs_metrics.record_gateway_latency(dt)
        self._window.observe(dt)

    def predict(self, request: dict, timeout: float = 30.0,
                path: str = "/predict") -> dict:
        """Route one request to a replica; ``path`` selects the replica
        route (e.g. ``/v1/chat/completions`` on LLM replicas)."""
        t0 = time.perf_counter()
        with self._connect(request, timeout, path) as r:
            out = json.load(r)
        self._observe_latency(t0)
        return out

    def stream(self, request: dict, timeout: float = 30.0,
               path: str = "/v1/chat/completions"):
        """Streaming pass-through: route one SSE request to a replica
        and yield each ``data:`` payload string as it arrives (the
        ``[DONE]`` terminator is consumed, not yielded). Failover (dead
        connect, 503 shed) applies only until the response starts —
        once frames are flowing the stream belongs to that replica and
        an error surfaces to the caller. A replica answering plain JSON
        (its ``llm_stream`` knob off) degrades gracefully: the whole
        body is yielded as the single event."""
        t0 = time.perf_counter()
        resp = self._connect(request, timeout, path)
        try:
            ctype = resp.headers.get("Content-Type", "")
            if "text/event-stream" not in ctype:
                yield resp.read().decode("utf-8", "replace")
            else:
                for raw in resp:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line.startswith("data: "):
                        continue
                    data = line[len("data: "):]
                    if data == "[DONE]":
                        break
                    yield data
        finally:
            resp.close()
        self._observe_latency(t0)

    def metrics(self) -> GatewayMetrics:
        """Trailing-window :class:`GatewayMetrics` from the shared
        :class:`~fedml_tpu.core.obs.metrics.LatencyWindow`. Unpacks as
        the legacy ``(qps, mean)`` pair."""
        qps, mean, p50, p99, n = self._window.stats()
        return GatewayMetrics(qps=qps, latency_s=mean,
                              p50=p50, p99=p99, count=n)


# ------------------------------------------------------------ autoscaler ----

class Autoscaler:
    """Applies a policy on a cadence (reference autoscaler daemon loop)."""

    def __init__(self, gateway: Gateway, policy, interval_s: float = 1.0):
        self.gateway = gateway
        self.policy = policy
        self.interval_s = float(interval_s)
        self.scale_events = 0            # replica-count changes applied
        self.last_fleet: Optional[FleetSLOView] = None
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def _fleet_slo(self) -> FleetSLOView:
        """Scrape every replica's ``/healthz`` ``slo`` payload into one
        :class:`FleetSLOView` (worst-replica tails, summed queue, min
        headroom). Draining replicas are included — their in-flight tail
        is still the user's latency."""
        ports = self.gateway.replica_set.ports(include_draining=True)
        ttft: List[float] = []
        itl: List[float] = []
        queue = 0
        headrooms: List[int] = []
        for port in ports:
            try:
                try:
                    r = urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=1.0)
                except urllib.error.HTTPError as e:
                    r = e      # a 503 body still carries the health JSON
                with r:
                    h = json.load(r)
            except Exception:  # noqa: BLE001 — a dead replica scores 0
                continue
            queue += int(h.get("queue_depth", 0) or 0)
            slo = h.get("slo") or {}
            if int(slo.get("ttft_n", 0) or 0) > 0:
                ttft.append(float(slo.get("ttft_p99_s", 0.0)))
            if int(slo.get("itl_n", 0) or 0) > 0:
                itl.append(float(slo.get("itl_p99_s", 0.0)))
            hr = slo.get("kv_headroom_requests")
            if hr is not None and int(hr) >= 0:
                headrooms.append(int(hr))
        m = self.gateway.metrics()
        return FleetSLOView(
            ttft_p99_s=max(ttft) if ttft else 0.0,
            itl_p99_s=max(itl) if itl else 0.0,
            queue_depth=queue,
            kv_headroom_min=min(headrooms) if headrooms else None,
            gateway_p99_s=m.p99, replicas=len(ports))

    def step(self) -> int:
        """One evaluation: heal -> metrics -> desired -> scale. Returns the
        new replica count (also usable directly, without the daemon
        thread). Policies declaring a ``latency_signal`` ("mean" | "p50" |
        "p99") are fed that percentile from the gateway window — tail-
        latency-targeting autoscaling. A policy with a
        ``desired_from_fleet`` method (:class:`SLOPolicy`) is instead fed
        the aggregated per-replica SLO scrape."""
        from ..core.obs import metrics as obs_metrics
        self.gateway.replica_set.health_check()
        heal = getattr(self.gateway, "heal", None)
        if callable(heal):
            heal()
        current = len(self.gateway.replica_set)
        if hasattr(self.policy, "desired_from_fleet"):
            self.last_fleet = self._fleet_slo()
            desired = self.policy.desired_from_fleet(
                self.last_fleet, current)
        else:
            m = self.gateway.metrics()
            lat = m.signal(getattr(self.policy, "latency_signal", "mean"))
            desired = self.policy.desired_replicas(m.qps, lat, current)
        got = self.gateway.replica_set.scale_to(desired)
        after = len(self.gateway.replica_set)
        if after != current:
            self.scale_events += 1
            obs_metrics.record_fleet_scale(
                "up" if after > current else "down", after)
            logger.info("autoscaler: scaled %d -> %d replicas",
                        current, after)
        return got

    def start(self) -> None:
        self._running = True

        def loop():
            while self._running:
                try:
                    self.step()
                except Exception:  # noqa: BLE001 — daemon must survive
                    logger.exception("autoscaler step failed")
                time.sleep(self.interval_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
