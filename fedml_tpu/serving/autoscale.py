"""Serving autoscaler: policies + replica set + scale-out gateway.

Parity target: reference ``model_scheduler/autoscaler/autoscaler.py``
(policy classes :20,70,135,186 — EWM of QPS, concurrency, traffic
lookback — consulted by the deploy agents to resize endpoint replicas)
and the inference gateway (``device_model_inference.py``). Local-first
redesign: replicas are in-process :class:`FedMLInferenceRunner` instances
(the docker-container analogue without a container runtime); the
:class:`Gateway` fronts them with round-robin dispatch and records the
QPS/latency series the policies consume; :class:`Autoscaler` applies a
policy on a cadence and grows/shrinks the replica set.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import threading
import time
import urllib.request
from collections import deque
from typing import Deque, List, Optional, Tuple

logger = logging.getLogger(__name__)


# ------------------------------------------------------------- policies ----

@dataclasses.dataclass
class EWMPolicy:
    """Exponentially-weighted moving average of per-replica QPS (reference
    ``EWMPolicy`` :70): scale so that EWM(qps)/replica stays under
    ``target_qps_per_replica``."""
    target_qps_per_replica: float = 10.0
    alpha: float = 0.5
    _ewm: Optional[float] = None

    def desired_replicas(self, qps: float, latency_s: float,
                         current: int) -> int:
        self._ewm = (qps if self._ewm is None
                     else self.alpha * qps + (1 - self.alpha) * self._ewm)
        return max(1, math.ceil(self._ewm / self.target_qps_per_replica))


@dataclasses.dataclass
class ConcurrencyPolicy:
    """Little's-law concurrency policy (reference ``ConcurrentQueryPolicy``
    :135): in-flight = qps x latency; one replica sustains
    ``target_concurrency``."""
    target_concurrency: float = 4.0

    def desired_replicas(self, qps: float, latency_s: float,
                         current: int) -> int:
        inflight = qps * max(latency_s, 1e-6)
        return max(1, math.ceil(inflight / self.target_concurrency))


@dataclasses.dataclass
class LookbackPolicy:
    """Scale on the max QPS seen in a trailing window (reference
    ``MeetTrafficDemandPolicy`` :186 shape): headroom for bursts."""
    target_qps_per_replica: float = 10.0
    window: int = 10
    _hist: Deque[float] = dataclasses.field(default_factory=deque)

    def desired_replicas(self, qps: float, latency_s: float,
                         current: int) -> int:
        self._hist.append(qps)
        while len(self._hist) > self.window:
            self._hist.popleft()
        peak = max(self._hist)
        return max(1, math.ceil(peak / self.target_qps_per_replica))


# ---------------------------------------------------------- replica set ----

class ReplicaSet:
    """N live inference runners over one predictor-factory (the
    container-fleet analogue; ``scale_to`` is the rolling update)."""

    def __init__(self, predictor_factory, min_replicas: int = 1,
                 max_replicas: int = 8):
        from . import FedMLInferenceRunner
        self._runner_cls = FedMLInferenceRunner
        self.predictor_factory = predictor_factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.replicas: List = []
        self._lock = threading.Lock()
        self.scale_to(self.min_replicas)

    def scale_to(self, n: int) -> int:
        n = min(max(n, self.min_replicas), self.max_replicas)
        with self._lock:
            while len(self.replicas) < n:
                runner = self._runner_cls(self.predictor_factory())
                runner.start()
                self.replicas.append(runner)
                logger.info("replica up on :%d (%d total)", runner.port,
                            len(self.replicas))
            while len(self.replicas) > n:
                runner = self.replicas.pop()
                runner.stop()
                logger.info("replica down (%d left)", len(self.replicas))
        return n

    def ports(self) -> List[int]:
        with self._lock:
            return [r.port for r in self.replicas]

    # --- health + rolling update (reference
    # ``device_replica_controller.py``: health-based replacement, one-at-a-
    # time rollout) -------------------------------------------------------
    def _probe(self, port: int, timeout: float = 2.0) -> bool:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/ready", timeout=timeout) as r:
                return r.status == 200
        except Exception:
            return False

    def _start_ready(self, wait_s: float = 10.0):
        """Start a fresh replica and wait until it answers /ready —
        traffic must never be pointed at a cold server."""
        runner = self._runner_cls(self.predictor_factory())
        runner.start()
        deadline = time.time() + wait_s
        while time.time() < deadline:
            if self._probe(runner.port, timeout=1.0):
                return runner
            time.sleep(0.05)
        runner.stop()
        raise RuntimeError("replacement replica never became ready")

    def health_check(self) -> int:
        """Probe every replica; replace dead ones with fresh ready servers.
        Returns the number replaced. The autoscaler calls this each step —
        the set HEALS, it does not just resize."""
        with self._lock:
            snapshot = list(enumerate(self.replicas))
        replaced = 0
        for i, runner in snapshot:
            if self._probe(runner.port):
                continue
            logger.warning("replica on :%d failed health check — replacing",
                           runner.port)
            fresh = self._start_ready()
            with self._lock:
                if i < len(self.replicas) and self.replicas[i] is runner:
                    self.replicas[i] = fresh
                    replaced += 1
                else:  # set changed underneath (scale event): discard
                    fresh.stop()
                    continue
            try:
                runner.stop()
            except Exception:
                pass
        return replaced

    def rolling_update(self, predictor_factory) -> None:
        """Replace every replica with one built from the new factory,
        one at a time, new-up-and-ready before old-down — the gateway keeps
        serving throughout (reference rolling-upgrade flow)."""
        self.predictor_factory = predictor_factory
        with self._lock:
            n = len(self.replicas)
        for i in range(n):
            fresh = self._start_ready()
            with self._lock:
                if i >= len(self.replicas):  # shrunk mid-rollout
                    fresh.stop()
                    return
                old = self.replicas[i]
                self.replicas[i] = fresh
            old.stop()

    def stop(self) -> None:
        with self._lock:
            for r in self.replicas:
                r.stop()
            self.replicas.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self.replicas)


# -------------------------------------------------------------- gateway ----

class Gateway:
    """Round-robin HTTP front over a ReplicaSet that records the
    QPS/latency series policies consume (reference inference gateway)."""

    def __init__(self, replica_set: ReplicaSet, window_s: float = 5.0):
        self.replica_set = replica_set
        self.window_s = float(window_s)
        self._i = 0
        self._lock = threading.Lock()
        self._events: Deque[Tuple[float, float]] = deque()  # (ts, latency)

    def predict(self, request: dict, timeout: float = 30.0) -> dict:
        ports = self.replica_set.ports()
        if not ports:
            raise RuntimeError("no live replicas")
        with self._lock:
            port = ports[self._i % len(ports)]
            self._i += 1
        t0 = time.perf_counter()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict",
            data=json.dumps(request).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            out = json.load(r)
        dt = time.perf_counter() - t0
        now = time.time()
        with self._lock:
            self._events.append((now, dt))
            cutoff = now - self.window_s
            while self._events and self._events[0][0] < cutoff:
                self._events.popleft()
        return out

    def metrics(self) -> Tuple[float, float]:
        """(qps, mean latency seconds) over the trailing window."""
        now = time.time()
        with self._lock:
            cutoff = now - self.window_s
            while self._events and self._events[0][0] < cutoff:
                self._events.popleft()
            n = len(self._events)
            lat = (sum(l for _, l in self._events) / n) if n else 0.0
        return n / self.window_s, lat


# ------------------------------------------------------------ autoscaler ----

class Autoscaler:
    """Applies a policy on a cadence (reference autoscaler daemon loop)."""

    def __init__(self, gateway: Gateway, policy, interval_s: float = 1.0):
        self.gateway = gateway
        self.policy = policy
        self.interval_s = float(interval_s)
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def step(self) -> int:
        """One evaluation: heal -> metrics -> desired -> scale. Returns the
        new replica count (also usable directly, without the daemon
        thread)."""
        self.gateway.replica_set.health_check()
        qps, lat = self.gateway.metrics()
        desired = self.policy.desired_replicas(
            qps, lat, len(self.gateway.replica_set))
        return self.gateway.replica_set.scale_to(desired)

    def start(self) -> None:
        self._running = True

        def loop():
            while self._running:
                try:
                    self.step()
                except Exception:  # noqa: BLE001 — daemon must survive
                    logger.exception("autoscaler step failed")
                time.sleep(self.interval_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
