"""Serving autoscaler: policies + replica set + scale-out gateway.

Parity target: reference ``model_scheduler/autoscaler/autoscaler.py``
(policy classes :20,70,135,186 — EWM of QPS, concurrency, traffic
lookback — consulted by the deploy agents to resize endpoint replicas)
and the inference gateway (``device_model_inference.py``). Local-first
redesign: replicas are in-process :class:`FedMLInferenceRunner` instances
(the docker-container analogue without a container runtime); the
:class:`Gateway` fronts them with round-robin dispatch and records the
QPS/latency series the policies consume; :class:`Autoscaler` applies a
policy on a cadence and grows/shrinks the replica set.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import threading
import time
import urllib.request
from collections import deque
from typing import Deque, List, Optional

logger = logging.getLogger(__name__)


# ------------------------------------------------------------- policies ----

@dataclasses.dataclass
class EWMPolicy:
    """Exponentially-weighted moving average of per-replica QPS (reference
    ``EWMPolicy`` :70): scale so that EWM(qps)/replica stays under
    ``target_qps_per_replica``."""
    target_qps_per_replica: float = 10.0
    alpha: float = 0.5
    _ewm: Optional[float] = None

    def desired_replicas(self, qps: float, latency_s: float,
                         current: int) -> int:
        self._ewm = (qps if self._ewm is None
                     else self.alpha * qps + (1 - self.alpha) * self._ewm)
        return max(1, math.ceil(self._ewm / self.target_qps_per_replica))


@dataclasses.dataclass
class ConcurrencyPolicy:
    """Little's-law concurrency policy (reference ``ConcurrentQueryPolicy``
    :135): in-flight = qps x latency; one replica sustains
    ``target_concurrency``. ``latency_signal`` picks which latency the
    autoscaler feeds in — ``"p99"`` makes in-flight a tail estimate, so
    the fleet sizes for the slow requests batching directly shapes, not
    the mean the fast ones dominate."""
    target_concurrency: float = 4.0
    latency_signal: str = "mean"    # mean | p50 | p99

    def desired_replicas(self, qps: float, latency_s: float,
                         current: int) -> int:
        inflight = qps * max(latency_s, 1e-6)
        return max(1, math.ceil(inflight / self.target_concurrency))


@dataclasses.dataclass
class LookbackPolicy:
    """Scale on the max QPS seen in a trailing window (reference
    ``MeetTrafficDemandPolicy`` :186 shape): headroom for bursts.
    ``max_latency_s`` adds a tail-latency guard on ``latency_signal``
    (default p99): while the observed tail exceeds it, demand-based
    sizing is overridden upward by one replica per step."""
    target_qps_per_replica: float = 10.0
    window: int = 10
    max_latency_s: float = 0.0      # 0 = QPS-only (original behavior)
    latency_signal: str = "p99"
    _hist: Deque[float] = dataclasses.field(default_factory=deque)

    def desired_replicas(self, qps: float, latency_s: float,
                         current: int) -> int:
        self._hist.append(qps)
        while len(self._hist) > self.window:
            self._hist.popleft()
        peak = max(self._hist)
        desired = max(1, math.ceil(peak / self.target_qps_per_replica))
        if self.max_latency_s > 0 and latency_s > self.max_latency_s:
            desired = max(desired, current + 1)
        return desired


# ---------------------------------------------------------- replica set ----

class SubprocessReplica:
    """One replica as a CHILD PROCESS serving HTTP — the process-isolation
    analogue of the reference's per-replica docker container
    (``device_model_deployment.py:61-333``): a crash (up to ``kill -9``)
    kills only this process; the controller's health check replaces it.
    Same surface as FedMLInferenceRunner: ``start()``/``stop()``/``port``.
    """

    def __init__(self, spec_path: str, startup_wait_s: float = 30.0):
        self.spec_path = spec_path
        self.startup_wait_s = float(startup_wait_s)
        self.port: Optional[int] = None
        self.proc = None

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def start(self) -> int:
        import subprocess
        import sys
        import tempfile
        import os
        fd, port_file = tempfile.mkstemp(suffix=".port")
        os.close(fd)
        os.unlink(port_file)
        with open(self.spec_path) as f:
            spec = json.load(f)
        spec["port_file"] = port_file
        child_spec = self.spec_path + f".{os.getpid()}.{id(self)}"
        try:
            with open(child_spec, "w") as f:
                json.dump(spec, f)
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "fedml_tpu.serving.replica_main",
                 child_spec],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            deadline = time.time() + self.startup_wait_s
            while time.time() < deadline:
                if os.path.exists(port_file):
                    with open(port_file) as f:
                        self.port = int(f.read().strip())
                    return self.port
                if self.proc.poll() is not None:
                    break
                time.sleep(0.05)
            self.stop()
            raise RuntimeError(
                "subprocess replica never published its port")
        finally:
            # a crash-looping replica replaced by the health check every
            # few seconds must not accumulate temp spec/port files
            for p in (port_file, child_spec):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    def stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5.0)
            except Exception:
                self.proc.kill()
                try:  # reap: an ignored SIGTERM must not leave a zombie
                    self.proc.wait(timeout=5.0)
                except Exception:
                    pass


def subprocess_replica_factory(args, params_path: str, output_dim: int,
                               workdir: str, platform: str = "cpu",
                               kind: str = "classifier"):
    """Build a ``replica_factory`` for :class:`ReplicaSet`: each call
    yields a fresh un-started :class:`SubprocessReplica` serving the given
    model artifact. ``kind='causal_lm'`` makes every replica an LLM
    template server (chat route mounted) instead of a classifier."""
    import os
    spec = {"args": {k: v for k, v in vars(args).items()
                     if isinstance(v, (str, int, float, bool, type(None)))},
            "params_path": os.path.abspath(params_path),
            "output_dim": int(output_dim), "platform": platform,
            "kind": kind}
    os.makedirs(workdir, exist_ok=True)
    spec_path = os.path.join(workdir, "replica_spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    return lambda: SubprocessReplica(spec_path)


class ReplicaSet:
    """N live inference replicas over one factory (the container-fleet
    analogue; ``scale_to`` is the rolling update). Replicas are in-process
    runners via ``predictor_factory``, or isolated child processes via
    ``replica_factory`` (see :class:`SubprocessReplica`)."""

    def __init__(self, predictor_factory=None, min_replicas: int = 1,
                 max_replicas: int = 8, replica_factory=None,
                 runner_cls=None):
        from . import FedMLInferenceRunner
        if (predictor_factory is None) == (replica_factory is None):
            raise ValueError("pass exactly one of predictor_factory / "
                             "replica_factory")
        # runner_cls lets templates mount extra routes on every replica
        # (e.g. the LLM template's ChatCompletionRunner)
        self._runner_cls = runner_cls or FedMLInferenceRunner
        self.predictor_factory = predictor_factory
        self.replica_factory = replica_factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.replicas: List = []
        self._lock = threading.Lock()
        self.scale_to(self.min_replicas)

    def _new_replica(self):
        if self.replica_factory is not None:
            return self.replica_factory()
        return self._runner_cls(self.predictor_factory())

    def scale_to(self, n: int) -> int:
        """Grow/shrink to ``n``. Replica start/stop happens OUTSIDE the
        set lock — a subprocess replica takes seconds to come up, and the
        gateway needs the same lock for every request; scaling up under
        load must not stall the traffic it is scaling for."""
        n = min(max(n, self.min_replicas), self.max_replicas)
        while True:
            victim = None
            with self._lock:
                cur = len(self.replicas)
                if cur > n:
                    victim = self.replicas.pop()
            if victim is not None:
                victim.stop()
                logger.info("replica down (%d left)", len(self))
                continue
            if cur >= n:
                return n
            runner = self._new_replica()
            runner.start()
            with self._lock:
                if len(self.replicas) < n:
                    self.replicas.append(runner)
                    logger.info("replica up on :%d (%d total)", runner.port,
                                len(self.replicas))
                    continue
            runner.stop()  # target shrank underneath us

    def ports(self) -> List[int]:
        with self._lock:
            return [r.port for r in self.replicas]

    # --- health + rolling update (reference
    # ``device_replica_controller.py``: health-based replacement, one-at-a-
    # time rollout) -------------------------------------------------------
    def _probe(self, port: int, timeout: float = 2.0) -> bool:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/ready", timeout=timeout) as r:
                return r.status == 200
        except Exception:
            return False

    def _start_ready(self, wait_s: float = 10.0):
        """Start a fresh replica and wait until it answers /ready —
        traffic must never be pointed at a cold server."""
        runner = self._new_replica()
        runner.start()
        deadline = time.time() + wait_s
        while time.time() < deadline:
            if self._probe(runner.port, timeout=1.0):
                return runner
            time.sleep(0.05)
        runner.stop()
        raise RuntimeError("replacement replica never became ready")

    def health_check(self) -> int:
        """Probe every replica; replace dead ones with fresh ready servers.
        Returns the number replaced. The autoscaler calls this each step —
        the set HEALS, it does not just resize."""
        with self._lock:
            snapshot = list(enumerate(self.replicas))
        replaced = 0
        for i, runner in snapshot:
            if self._probe(runner.port):
                continue
            logger.warning("replica on :%d failed health check — replacing",
                           runner.port)
            fresh = self._start_ready()
            with self._lock:
                if i < len(self.replicas) and self.replicas[i] is runner:
                    self.replicas[i] = fresh
                    replaced += 1
                else:  # set changed underneath (scale event): discard
                    fresh.stop()
                    continue
            try:
                runner.stop()
            except Exception:
                pass
        return replaced

    def rolling_update(self, predictor_factory=None,
                       replica_factory=None) -> None:
        """Replace every replica with one built from the new factory,
        one at a time, new-up-and-ready before old-down — the gateway keeps
        serving throughout (reference rolling-upgrade flow)."""
        if replica_factory is not None:
            self.replica_factory = replica_factory
        elif predictor_factory is not None:
            if self.replica_factory is not None:
                # subprocess mode: a bare positional factory is a replica
                # factory
                self.replica_factory = predictor_factory
            else:
                self.predictor_factory = predictor_factory
        # both None: respawn from the CURRENT factory (subprocess mode
        # re-reads the spec/artifact from disk on every start, so a bare
        # rolling_update() rolls an updated on-disk model out)
        with self._lock:
            n = len(self.replicas)
        for i in range(n):
            fresh = self._start_ready()
            with self._lock:
                if i >= len(self.replicas):  # shrunk mid-rollout
                    fresh.stop()
                    return
                old = self.replicas[i]
                self.replicas[i] = fresh
            # drain: a request the gateway routed to `old` JUST before the
            # swap is still in flight — stopping immediately resets it.
            time.sleep(0.25)
            old.stop()

    def stop(self) -> None:
        with self._lock:
            for r in self.replicas:
                r.stop()
            self.replicas.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self.replicas)


# -------------------------------------------------------------- gateway ----

@dataclasses.dataclass
class GatewayMetrics:
    """Trailing-window request metrics. Iterates as the legacy
    ``(qps, mean_latency)`` pair so existing unpacking call sites keep
    working; ``p50``/``p99`` carry the tail the autoscaler policies can
    target."""
    qps: float
    latency_s: float     # mean
    p50: float
    p99: float
    count: int

    def __iter__(self):
        return iter((self.qps, self.latency_s))

    def signal(self, name: str) -> float:
        return {"mean": self.latency_s, "p50": self.p50,
                "p99": self.p99}[str(name)]


class Gateway:
    """Round-robin HTTP front over a ReplicaSet that records the
    QPS/latency series policies consume (reference inference gateway).

    Windowed tail stats live in ONE place: the ``core/obs``
    :class:`~fedml_tpu.core.obs.metrics.LatencyWindow` (exact
    nearest-rank percentiles over the trailing window — the autoscaler's
    signal is never bucket-quantized). Every request's latency also
    lands in the registry histogram (``serving_gateway_latency_seconds``)
    for the ``/metrics`` exposition and JSONL snapshots. An active span
    on the calling thread is forwarded to the replica as a W3C
    ``traceparent`` header, so the replica-side request trace joins the
    caller's."""

    def __init__(self, replica_set: ReplicaSet, window_s: float = 5.0):
        from ..core.obs import metrics as obs_metrics
        self.replica_set = replica_set
        self.window_s = float(window_s)
        self._i = 0
        self._lock = threading.Lock()
        self._window = obs_metrics.LatencyWindow(window_s=self.window_s)

    def predict(self, request: dict, timeout: float = 30.0,
                path: str = "/predict") -> dict:
        """Route one request to a replica; ``path`` selects the replica
        route (e.g. ``/v1/chat/completions`` on LLM replicas)."""
        from ..core.obs import metrics as obs_metrics
        from ..core.obs import trace as obs_trace
        body = json.dumps(request).encode()
        headers = {"Content-Type": "application/json"}
        cur = obs_trace.current_span()
        if cur is not None and cur.traceparent():
            headers["traceparent"] = cur.traceparent()
        t0 = time.perf_counter()
        # one retry on a CONNECTION-PHASE failure only (replica swapped or
        # crashed between routing and connect — the request never reached
        # a predictor, so re-routing it is safe). HTTP errors and read
        # timeouts DID reach a replica and must surface, not double the
        # load on a saturated fleet.
        for attempt in range(2):
            ports = self.replica_set.ports()
            if not ports:
                raise RuntimeError("no live replicas")
            with self._lock:
                port = ports[self._i % len(ports)]
                self._i += 1
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", data=body,
                headers=headers)
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    out = json.load(r)
                break
            except urllib.error.HTTPError:
                raise  # the replica answered; its answer stands
            except (urllib.error.URLError, OSError) as e:
                reason = getattr(e, "reason", e)
                if (attempt == 1
                        or not isinstance(reason, ConnectionError)):
                    raise
        dt = time.perf_counter() - t0
        obs_metrics.record_gateway_latency(dt)
        self._window.observe(dt)
        return out

    def metrics(self) -> GatewayMetrics:
        """Trailing-window :class:`GatewayMetrics` from the shared
        :class:`~fedml_tpu.core.obs.metrics.LatencyWindow`. Unpacks as
        the legacy ``(qps, mean)`` pair."""
        qps, mean, p50, p99, n = self._window.stats()
        return GatewayMetrics(qps=qps, latency_s=mean,
                              p50=p50, p99=p99, count=n)


# ------------------------------------------------------------ autoscaler ----

class Autoscaler:
    """Applies a policy on a cadence (reference autoscaler daemon loop)."""

    def __init__(self, gateway: Gateway, policy, interval_s: float = 1.0):
        self.gateway = gateway
        self.policy = policy
        self.interval_s = float(interval_s)
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def step(self) -> int:
        """One evaluation: heal -> metrics -> desired -> scale. Returns the
        new replica count (also usable directly, without the daemon
        thread). Policies declaring a ``latency_signal`` ("mean" | "p50" |
        "p99") are fed that percentile from the gateway window — tail-
        latency-targeting autoscaling."""
        self.gateway.replica_set.health_check()
        m = self.gateway.metrics()
        lat = m.signal(getattr(self.policy, "latency_signal", "mean"))
        desired = self.policy.desired_replicas(
            m.qps, lat, len(self.gateway.replica_set))
        return self.gateway.replica_set.scale_to(desired)

    def start(self) -> None:
        self._running = True

        def loop():
            while self._running:
                try:
                    self.step()
                except Exception:  # noqa: BLE001 — daemon must survive
                    logger.exception("autoscaler step failed")
                time.sleep(self.interval_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
