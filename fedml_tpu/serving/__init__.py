"""Serving SDK: ``FedMLPredictor`` + ``FedMLInferenceRunner``.

Parity target: the reference's user-facing serving SDK —
``serving/fedml_predictor.py:4`` (ABC with ``predict``) and
``serving/fedml_inference_runner.py:8`` (FastAPI wrapper exposing
``/predict`` and ``/ready``). TPU-first redesign choices:

* the HTTP layer is the stdlib ``ThreadingHTTPServer`` (no FastAPI/uvicorn
  dependency) — the contract (POST ``/predict`` with a JSON body, GET
  ``/ready``) is what matters for parity, not the web framework;
* :class:`CheckpointPredictor` jits the model's forward once and serves
  batched JAX inference from a saved training checkpoint, so the path from
  ``run_simulation`` to a live endpoint is two lines;
* model artifacts are msgpack-encoded numpy pytrees (``save_model`` /
  ``load_model``) — the same codec as the wire format
  (:mod:`..core.distributed.communication.message`), NOT pickle: loading
  a served artifact must never be a code-execution vector, and the trust
  story should match the wire's (reference streams pickled state dicts;
  we deliberately do not).
"""

from __future__ import annotations

import json
import logging
import threading
from abc import ABC, abstractmethod
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import numpy as np

from ..core.distributed.communication.message import dumps_tree, loads_tree

logger = logging.getLogger(__name__)

PyTree = Any

# artifact magic: lets load_model fail loudly (instead of unpacking
# garbage) on foreign files, and marks the format as the msgpack codec
_ARTIFACT_MAGIC = b"FMTPU1\n"


class Overloaded(RuntimeError):
    """Load-shed verdict: the serving queue is past its depth bound, so
    the request is refused AT SUBMIT instead of wedging the queue —
    overload is a signal, not a hang. ``retry_after_s`` (derived from
    queue depth and KV admission headroom) rides out as the HTTP 503's
    ``Retry-After`` header so well-behaved clients back off usefully."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = max(float(retry_after_s), 0.0)


class SSEStream:
    """A route handler's STREAMING verdict: instead of one JSON body,
    the HTTP layer writes each yielded event as a ``text/event-stream``
    ``data:`` frame (dicts are JSON-encoded; strings pass through),
    closing with ``data: [DONE]`` — the OpenAI streaming wire shape, so
    existing OpenAI streaming clients consume a served federated
    fine-tune unchanged. Errors raised by the iterator AFTER the headers
    went out surface as a final ``data: {"error": ...}`` frame (the
    status line is already on the wire; a mid-stream 500 is not a thing
    HTTP has)."""

    def __init__(self, events, headers: Optional[dict] = None):
        self.events = events
        self.headers = dict(headers or {})


def save_model(params: PyTree, path: str) -> str:
    """Persist model params with the wire codec (``dumps_tree``). No
    pickle: artifacts may cross trust boundaries (device uploads, served
    model pulls)."""
    import os
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_ARTIFACT_MAGIC)
        f.write(dumps_tree(params))
    os.replace(tmp, path)
    return path


def check_model_magic(path: str) -> None:
    """Cheap receive-time validation: existence + magic header, without
    unpacking the whole artifact (which the consumer will do anyway)."""
    with open(path, "rb") as f:
        if f.read(len(_ARTIFACT_MAGIC)) != _ARTIFACT_MAGIC:
            raise ValueError(
                f"{path}: not a fedml_tpu model artifact (bad magic)")


def load_model(path: str) -> PyTree:
    with open(path, "rb") as f:
        head = f.read(len(_ARTIFACT_MAGIC))
        if head != _ARTIFACT_MAGIC:
            raise ValueError(
                f"{path}: not a fedml_tpu model artifact (bad magic); "
                "legacy pickle artifacts are not loaded — re-save with "
                "save_model")
        return loads_tree(f.read())


class FedMLPredictor(ABC):
    """User-implemented predictor (reference ``fedml_predictor.py:4``)."""

    @abstractmethod
    def predict(self, request: Any) -> Any:
        """Map one JSON-decoded request to a JSON-encodable response."""

    def ready(self) -> bool:
        return True


class CheckpointPredictor(FedMLPredictor):
    """Serve a trained fedml_tpu model: request ``{"inputs": [[...], ...]}``
    → response ``{"outputs": logits, "classes": argmax}``."""

    def __init__(self, bundle, params: PyTree):
        import jax
        self.bundle = bundle
        self.params = params
        self._fwd = jax.jit(lambda p, x: bundle.apply(p, x))

    @classmethod
    def from_files(cls, args, params_path: str, output_dim: int):
        from ..model import create
        bundle = create(args, output_dim)
        return cls(bundle, load_model(params_path))

    def predict(self, request: Any) -> Any:
        import jax.numpy as jnp
        x = jnp.asarray(np.asarray(request["inputs"], np.float32))
        logits = np.asarray(self._fwd(self.params, x))
        return {"outputs": logits.tolist(),
                "classes": logits.argmax(-1).tolist()}


class FedMLInferenceRunner:
    """HTTP wrapper: POST /predict, GET /ready (reference
    ``fedml_inference_runner.py:8-39``). ``start()`` serves on a background
    thread and returns the bound port; ``run()`` blocks.

    Operator surface (the serving observability plane):

    * ``GET /metrics`` — Prometheus text exposition of the process-wide
      ``core/obs`` registry (TTFT/ITL histograms, KV-pool gauges, ...);
    * ``GET /healthz`` — liveness JSON from the predictor's ``health()``
      when it has one (503 on a non-``ok`` status — the watchdog's view);
    * ``GET /debug/state`` — the predictor's ``debug_state()`` (slot
      matrix, block-table summary, queue snapshot) for live inspection.

    Tracing: a ``POST`` carrying a W3C ``traceparent`` header joins the
    caller's trace — the handler wraps the route in a ``serving.http``
    span parented on the header (or a fresh root), active on the handler
    thread so the engine's per-request spans nest under it, and echoes
    the span's ``traceparent`` on the response."""

    def __init__(self, predictor: FedMLPredictor, host: str = "127.0.0.1",
                 port: int = 0,
                 extra_routes: Optional[dict] = None,
                 chaos=None):
        from ..core.obs import metrics as obs_metrics
        from ..core.obs import trace as obs_trace

        self.predictor = predictor
        # optional ServingChaosInjector: replica crash-at-request-N lands
        # HERE (the request seam) — hard_crash kills the process (the
        # subprocess-replica analogue of a container OOM-kill), otherwise
        # the connection is severed mid-request so in-process tests see
        # the same client-visible failure without losing the test process
        self.chaos = chaos
        # POST routes: path -> callable(json_request) -> json_response.
        # /predict is always mounted; templates mount more (e.g. the LLM
        # template's /v1/chat/completions)
        self.routes = {"/predict": predictor.predict}
        self.routes.update(extra_routes or {})
        runner = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args_):  # quiet by default
                logger.debug("serving: " + fmt, *args_)

            def _reply(self, code: int, payload: Any,
                       traceparent: Optional[str] = None,
                       extra_headers: Optional[dict] = None) -> None:
                blob = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                if traceparent:
                    self.send_header("traceparent", traceparent)
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(blob)

            def _reply_text(self, code: int, text: str) -> None:
                blob = text.encode()
                self.send_response(code)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def _reply_stream(self, stream: SSEStream,
                              traceparent: Optional[str] = None) -> None:
                """Write an SSE event stream (no Content-Length; the
                HTTP/1.0 connection close delimits the body, so plain
                read-to-EOF clients work)."""
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                if traceparent:
                    self.send_header("traceparent", traceparent)
                for k, v in stream.headers.items():
                    self.send_header(k, str(v))
                self.end_headers()
                events = iter(stream.events)
                try:
                    for ev in events:
                        blob = ev if isinstance(ev, str) else json.dumps(ev)
                        self.wfile.write(f"data: {blob}\n\n".encode())
                        self.wfile.flush()
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    # client went away mid-stream: stop generating
                    close = getattr(events, "close", None)
                    if close is not None:
                        close()
                except Exception as e:  # noqa: BLE001 — headers are out
                    logger.exception("stream handler failed mid-stream")
                    try:
                        self.wfile.write(
                            ("data: " + json.dumps({"error": str(e)})
                             + "\n\n").encode())
                        self.wfile.flush()
                    except OSError:
                        pass

            def do_GET(self):
                if self.path == "/ready":
                    ok = runner.predictor.ready()
                    self._reply(200 if ok else 503, {"ready": ok})
                elif self.path == "/metrics":
                    self._reply_text(200, obs_metrics.REGISTRY.exposition())
                elif self.path == "/healthz":
                    health = runner.health()
                    self._reply(200 if health.get("status") == "ok"
                                else 503, health)
                elif self.path == "/debug/state":
                    self._reply(200, runner.debug_state())
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                handler = runner.routes.get(self.path)
                if handler is None:
                    self._reply(404, {"error": "not found"})
                    return
                if runner.chaos is not None \
                        and runner.chaos.request_crash_due():
                    if runner.chaos.hard_crash:  # subprocess replica only
                        logger.error("chaos: replica crash-at-request "
                                     "(hard) — exiting")
                        import os
                        os._exit(23)
                    # in-process analogue: sever the connection so the
                    # client sees exactly what a process kill looks like
                    logger.error("chaos: replica crash-at-request — "
                                 "severing connection")
                    try:
                        self.connection.close()
                    except OSError:
                        pass
                    return
                parent = obs_trace.parse_traceparent(
                    self.headers.get("traceparent"))
                with obs_trace.span("serving.http", parent=parent,
                                    attrs={"path": self.path}) as sp:
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        request = json.loads(self.rfile.read(n) or b"{}")
                        resp = handler(request)
                        if isinstance(resp, SSEStream):
                            self._reply_stream(
                                resp, traceparent=sp.traceparent())
                        else:
                            self._reply(200, resp,
                                        traceparent=sp.traceparent())
                    except Overloaded as e:
                        # shed (or parked-unhealthy engine), not failed:
                        # 503 + Retry-After tells the client — and the
                        # gateway's failover — to go elsewhere
                        sp.set_attr("error", "overloaded")
                        self._reply(
                            503,
                            {"error": str(e),
                             "retry_after_s": e.retry_after_s},
                            traceparent=sp.traceparent(),
                            extra_headers={"Retry-After": max(
                                1, int(round(e.retry_after_s)))})
                    except Exception as e:
                        logger.exception("predict failed")
                        sp.set_attr("error", type(e).__name__)
                        self._reply(500, {"error": str(e)},
                                    traceparent=sp.traceparent())

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def health(self) -> dict:
        """Predictor ``health()`` when present, else readiness only."""
        fn = getattr(self.predictor, "health", None)
        if callable(fn):
            try:
                return fn()
            except Exception as e:  # health must answer, not raise
                return {"status": "error", "error": str(e)}
        ok = self.predictor.ready()
        return {"status": "ok" if ok else "not_ready"}

    def debug_state(self) -> dict:
        fn = getattr(self.predictor, "debug_state", None)
        if callable(fn):
            try:
                return fn()
            except Exception as e:
                return {"error": str(e)}
        return {"routes": sorted(self.routes),
                "predictor": type(self.predictor).__name__}

    def start(self) -> int:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        logger.info("inference runner listening on :%d", self.port)
        return self.port

    def run(self) -> None:
        self.start()
        self._thread.join()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
