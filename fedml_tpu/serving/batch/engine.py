"""BatchingEngine — threaded request queue over the DecodeScheduler.

The serving surface (HTTP handler threads) submits requests and blocks on
per-request futures; ONE worker thread owns the scheduler and runs the
admit → step → evict loop. Iteration-level scheduling: a finishing
request frees its slot at the very next step boundary and a queued
request is admitted into it — no batch barriers, no head-of-line
blocking behind long generations (Orca's core idea).

Deadlines: a request past its deadline is EVICTED at the next step
boundary and resolves with what it has, ``finish_reason: "length"`` —
tail-latency control the autoscaler's p99 policies can rely on.

Observability (the full request lifecycle through the ``core/obs``
planes):

* one trace per request — ``serving.request`` (child of the HTTP
  surface's span when one is active, so an inbound W3C ``traceparent``
  joins the caller's trace) containing ``serving.queue`` (submit →
  admission), ``serving.prefill`` (chunked prefill), and
  ``serving.decode`` (first token → finish/evict, decode progress as
  step-bucketed events, never per-token);
* shared engine-side ``serving.decode_steps`` spans — one per block of
  decode steps, LINKING the in-flight request spans they advanced (the
  fan-in idiom async pours use for their contributing uploads);
* SLO metrics — TTFT, inter-token latency (one observation per decode
  STEP), per-request tokens/s + queue wait, KV block-pool occupancy/
  fragmentation/admission headroom, evictions and rejections by reason;
* a black-box :class:`~fedml_tpu.core.obs.flight.FlightRecorder` ring of
  the last N lifecycle/step records, dumped on engine crash or when the
  :class:`~fedml_tpu.core.obs.flight.Watchdog` trips (no decode progress
  for ``watchdog_s`` while occupancy > 0, or NaN/inf decode logits).
"""

from __future__ import annotations

import collections
import logging
import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Deque, Dict, List, Optional

from ...core.obs import flight as obs_flight
from ...core.obs import metrics as obs_metrics
from ...core.obs import trace as obs_trace
from ...llm.data import EOS

logger = logging.getLogger(__name__)

# decode progress lands on the request span every this-many tokens (an
# event per token would make span records O(completion) large)
PROGRESS_EVERY_TOKENS = 16
# one shared serving.decode_steps span per this-many decode steps
DECODE_SPAN_STEPS = 32


class _Request:
    __slots__ = ("ids", "max_new", "temperature", "seed", "adapter_idx",
                 "deadline_ts", "future", "span", "out_ids", "slot",
                 "submitted_ts", "queue_span", "decode_span", "admit_ts",
                 "decode_ts")

    def __init__(self, ids, max_new, temperature, seed, adapter_idx,
                 deadline_ts, span):
        self.ids = ids
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.adapter_idx = int(adapter_idx)
        self.deadline_ts = deadline_ts
        self.future: Future = Future()
        self.span = span
        self.out_ids: List[int] = []
        self.slot: Optional[int] = None
        self.submitted_ts = time.time()
        self.queue_span = None
        self.decode_span = None
        self.admit_ts: Optional[float] = None   # queue end (prefill start)
        self.decode_ts: Optional[float] = None  # first token (decode start)


class BatchingEngine:
    """Continuous-batching front over one :class:`DecodeScheduler`."""

    def __init__(self, scheduler, default_deadline_s: float = 0.0,
                 rate_window_s: float = 2.0, watchdog_s: float = 30.0,
                 flight_records: int = 256,
                 flight_dir: Optional[str] = None):
        self.scheduler = scheduler
        self.default_deadline_s = float(default_deadline_s)
        self.rate_window_s = float(rate_window_s)
        self._q: "queue.Queue[_Request]" = queue.Queue()
        self._pending: Deque[_Request] = collections.deque()
        self._inflight: Dict[int, _Request] = {}
        self._tokens: Deque = collections.deque()   # (ts, n) for tokens/s
        self._running = True
        # --- black box + watchdog ------------------------------------------
        self.flight = obs_flight.FlightRecorder(
            "serving_engine", capacity=int(flight_records))
        self._flight_path = None
        if flight_dir:
            # the fallback dir is args.log_file_dir, whose schema default
            # is '~/...' — without expansion the dump lands in a literal
            # './~/' directory and the post-mortem artifact goes missing
            self._flight_path = os.path.join(
                os.path.expanduser(flight_dir),
                f"flight_serving_engine_{os.getpid()}.jsonl")
        self.last_progress_ts = time.time()
        self.watchdog = obs_flight.Watchdog(
            "serving_engine", self._watchdog_probe, recorder=self.flight,
            stall_s=float(watchdog_s), dump_path=self._flight_path)
        self.watchdog.start()
        # shared decode-step block span (bare handle, worker thread only)
        self._steps_span = None
        self._steps_in_span = 0
        self._span_tokens = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-batch-engine")
        self._thread.start()

    # ------------------------------------------------------------- submit --
    def submit(self, prompt_ids, max_new_tokens: int = 64,
               temperature: float = 0.0, seed: int = 0,
               adapter_idx: int = 0,
               deadline_s: Optional[float] = None,
               parent: Any = None) -> Future:
        """Enqueue one request; the future resolves to ``{"ids",
        "finish_reason", "prompt_tokens", "completion_tokens"}``.

        ``parent`` optionally parents the request trace (a Span,
        SpanContext, or raw traceparent string — e.g. an inbound HTTP
        header); with no parent the request joins the submitting
        thread's current span (the HTTP surface's ``serving.http``) or
        roots a fresh trace."""
        if not self._running:
            obs_metrics.record_llm_reject("engine_stopped")
            raise RuntimeError("engine stopped")
        span = obs_trace.tracer.start_span(
            "serving.request", parent=parent,
            attrs={"prompt_tokens": len(prompt_ids),
                   "adapter_idx": int(adapter_idx)})
        dl = self.default_deadline_s if deadline_s is None \
            else float(deadline_s)
        req = _Request(list(map(int, prompt_ids)), max_new_tokens,
                       temperature, seed, adapter_idx,
                       time.time() + dl if dl > 0 else None, span)
        if req.max_new <= 0 or not req.ids:
            self._finish(req, "length")
            return req.future
        if len(req.ids) >= self.scheduler.cfg.max_seq_len:
            err = ValueError(
                f"prompt of {len(req.ids)} tokens >= max_seq_len "
                f"{self.scheduler.cfg.max_seq_len}")
            self._reject(req, "prompt_too_long", err)
            return req.future
        ccfg = self.scheduler.cache_cfg
        need = ccfg.blocks_needed(min(len(req.ids) + req.max_new,
                                      ccfg.max_seq_len))
        if need > ccfg.num_blocks:
            # can_admit() would be False forever: failing it now beats
            # wedging the queue head until the caller's timeout
            err = ValueError(
                f"request needs {need} KV blocks, pool has only "
                f"{ccfg.num_blocks} (raise num_blocks or shrink the "
                "request)")
            self._reject(req, "kv_pool_too_small", err)
            return req.future
        req.queue_span = obs_trace.tracer.start_span(
            "serving.queue", parent=span)
        # stitch: the queue phase starts when the request does — the
        # microseconds between the two start_span calls must not read as
        # unattributed wall in the waterfall
        if req.queue_span.span_id is not None:
            req.queue_span.start_ts = span.start_ts
        self.flight.note("submit", prompt_tokens=len(req.ids),
                         max_new=req.max_new, adapter_idx=req.adapter_idx,
                         trace_id=span.trace_id)
        self._q.put(req)
        return req.future

    def _reject(self, req: _Request, reason: str, err: Exception) -> None:
        obs_metrics.record_llm_reject(reason)
        self.flight.note("reject", reason=reason)
        req.span.set_attr("error", reason).end()
        req.future.set_exception(err)

    def queue_depth(self) -> int:
        return self._q.qsize() + len(self._pending)

    # --------------------------------------------------------------- loop --
    def _loop(self) -> None:
        while self._running:
            try:
                self._drain_queue()
                self._admit()
                self._evict_deadlines()
                if not self._inflight:
                    self._close_steps_span()  # idle: don't span the wait
                    if not self._pending:
                        try:
                            self._pending.append(self._q.get(timeout=0.05))
                        except queue.Empty:
                            pass
                    else:
                        # pending but unadmittable (pool too small for the
                        # request) with nothing in flight: don't busy-spin
                        time.sleep(0.005)
                    continue
                self.last_progress_ts = time.time()  # entering the step:
                # only a step that HANGS past stall_s reads as a stall,
                # not a slow first-compile that returns
                t0 = time.perf_counter()
                toks = self.scheduler.step()
                self._observe_step(len(toks), time.perf_counter() - t0)
                self._collect(toks)
            except Exception:  # noqa: BLE001 — serving loop must survive
                logger.exception("batch engine step failed")
                self.flight.note("engine_crash")
                self.flight.dump(self._flight_path, reason="crash")
                self._fail_all(RuntimeError("batch engine step failed"))
        # drain on shutdown
        self._close_steps_span()
        self._fail_all(RuntimeError("engine stopped"))

    def _drain_queue(self) -> None:
        while True:
            try:
                self._pending.append(self._q.get_nowait())
            except queue.Empty:
                return

    def _admit(self) -> None:
        while self._pending:
            req = self._pending[0]
            if req.deadline_ts is not None and time.time() > req.deadline_ts:
                self._pending.popleft()
                obs_metrics.record_llm_evict("deadline_queued")
                req.span.add_event("evict", reason="deadline_queued")
                self.flight.note("evict", reason="deadline_queued")
                self._finish(req, "length")
                continue
            if not self.scheduler.can_admit(len(req.ids), req.max_new):
                return
            self._pending.popleft()
            dequeue_ts = time.time()
            if req.queue_span is not None:
                req.queue_span.end()
                req.queue_span = None
            prefill_span = obs_trace.tracer.start_span(
                "serving.prefill", parent=req.span,
                attrs={"prompt_tokens": len(req.ids)})
            if prefill_span.span_id is not None:
                prefill_span.start_ts = dequeue_ts  # stitch to queue end
            try:
                slot, first = self.scheduler.admit(
                    req.ids, adapter_idx=req.adapter_idx,
                    temperature=req.temperature, seed=req.seed,
                    max_new_tokens=req.max_new)
            except Exception as e:  # noqa: BLE001
                prefill_span.set_attr("error", type(e).__name__).end()
                req.span.set_attr("error", type(e).__name__).end()
                req.future.set_exception(e)
                continue
            now = time.time()
            self.last_progress_ts = now  # a slow prefill is not a stall
            prefill_span.set_attr("slot", slot)
            req.slot = slot
            req.admit_ts = dequeue_ts
            req.decode_ts = now
            req.span.add_event("admit", slot=slot)
            # first token exists the moment prefill returns: TTFT is
            # submit -> here (queue wait + chunked prefill, Orca's SLO)
            req.span.set_attr("ttft_s", round(now - req.submitted_ts, 6))
            obs_metrics.record_llm_ttft(now - req.submitted_ts)
            obs_metrics.record_llm_admit()
            self._note_kv_pool()
            self.flight.note(
                "admit", slot=slot,
                queue_wait_s=round(dequeue_ts - req.submitted_ts, 6))
            self._inflight[slot] = req
            req.decode_span = obs_trace.tracer.start_span(
                "serving.decode", parent=req.span, attrs={"slot": slot})
            if req.decode_span.span_id is not None:
                req.decode_span.start_ts = now  # stitch to prefill end
            prefill_span.end()
            self._note_tokens(1)
            if not self._append_token(req, first):
                self._retire(req)

    def _append_token(self, req: _Request, token: int) -> bool:
        """Append one generated token; False when the request finished."""
        if token == EOS:
            self._finish(req, "stop")
            return False
        req.out_ids.append(int(token))
        if (len(req.out_ids) % PROGRESS_EVERY_TOKENS == 0
                and req.decode_span is not None):
            req.decode_span.add_event("decode.progress",
                                      tokens=len(req.out_ids))
        if (len(req.out_ids) >= req.max_new
                or (req.slot is not None
                    and self.scheduler.slot_position(req.slot) + 1
                    >= self.scheduler.cfg.max_seq_len)):
            self._finish(req, "length")
            return False
        return True

    def _collect(self, toks: Dict[int, int]) -> None:
        self._note_tokens(len(toks))
        for slot, token in toks.items():
            req = self._inflight.get(slot)
            if req is None:
                continue
            if not self._append_token(req, token):
                self._retire(req)

    def _evict_deadlines(self) -> None:
        now = time.time()
        for slot, req in list(self._inflight.items()):
            if req.deadline_ts is not None and now > req.deadline_ts:
                obs_metrics.record_llm_evict("deadline")
                req.span.add_event("evict", reason="deadline", slot=slot)
                self.flight.note("evict", reason="deadline", slot=slot)
                self._finish(req, "length")
                self._retire(req)

    def _retire(self, req: _Request) -> None:
        if req.slot is not None:
            self._inflight.pop(req.slot, None)
            self.scheduler.release(req.slot)
            req.slot = None
            self._note_kv_pool()

    def _finish(self, req: _Request, reason: str) -> None:
        if req.future.done():
            return
        now = time.time()
        req.span.set_attr("completion_tokens", len(req.out_ids))
        req.span.set_attr("finish_reason", reason)
        if req.admit_ts is not None:
            queue_wait = req.admit_ts - req.submitted_ts
            decode_wall = max(now - (req.decode_ts or req.admit_ts), 1e-9)
            tps = len(req.out_ids) / decode_wall
            req.span.set_attr("queue_wait_s", round(queue_wait, 6))
            req.span.set_attr("tokens_per_s", round(tps, 2))
            obs_metrics.record_llm_request(tps, queue_wait)
        # the request span ends FIRST: the still-open phase span's end_ts
        # then lands at-or-after the request's, and the report's clipping
        # attributes the request window tail to it instead of leaving the
        # span-emission write latency unexplained
        req.span.end()
        if req.queue_span is not None:  # evicted before admission
            req.queue_span.end()
            req.queue_span = None
        if req.decode_span is not None:
            req.decode_span.set_attr("completion_tokens", len(req.out_ids))
            req.decode_span.end()
            req.decode_span = None
        self.flight.note("finish", reason=reason,
                         completion_tokens=len(req.out_ids))
        req.future.set_result({
            "ids": list(req.out_ids), "finish_reason": reason,
            "prompt_tokens": len(req.ids),
            "completion_tokens": len(req.out_ids)})

    def _fail_all(self, err: Exception) -> None:
        self._drain_queue()   # a submit racing stop() must fail too
        for req in list(self._inflight.values()):
            self._retire(req)
            if not req.future.done():
                self._end_spans_on_error(req)
                req.future.set_exception(err)
        for req in list(self._pending):
            if not req.future.done():
                self._end_spans_on_error(req)
                req.future.set_exception(err)
        self._pending.clear()

    @staticmethod
    def _end_spans_on_error(req: _Request) -> None:
        for sp in (req.queue_span, req.decode_span):
            if sp is not None:
                sp.set_attr("error", "engine_failure").end()
        req.queue_span = req.decode_span = None
        req.span.set_attr("error", "engine_failure").end()

    # ------------------------------------------------------------ metrics --
    def _note_tokens(self, n: int) -> None:
        now = time.time()
        self._tokens.append((now, n))
        cutoff = now - self.rate_window_s
        while self._tokens and self._tokens[0][0] < cutoff:
            self._tokens.popleft()

    def tokens_per_s(self) -> float:
        now = time.time()
        total = sum(n for ts, n in self._tokens
                    if ts >= now - self.rate_window_s)
        return total / self.rate_window_s

    def _note_kv_pool(self) -> None:
        st = self.scheduler.kv_pool_stats()
        obs_metrics.record_llm_kv_pool(
            st["used_blocks"], st["free_blocks"],
            st["headroom_requests"], st["fragmentation"])

    def _observe_step(self, tokens_out: int, wall_s: float) -> None:
        self.last_progress_ts = time.time()
        obs_metrics.record_llm_serving_step(
            tokens_out=tokens_out,
            occupancy=self.scheduler.active_count(),
            queue_depth=self.queue_depth(),
            tokens_per_s=self.tokens_per_s())
        # one ITL observation per STEP: every in-flight request
        # experienced this inter-token gap (per-step, not per-slot, so
        # the hot loop stays one bisect regardless of occupancy)
        obs_metrics.record_llm_itl(wall_s)
        self.flight.note("step", tokens=tokens_out,
                         occupancy=self.scheduler.active_count(),
                         queue_depth=self.queue_depth(),
                         wall_s=round(wall_s, 6),
                         finite=bool(self.scheduler.last_step_finite))
        self._advance_steps_span(tokens_out)

    # shared decode-step block spans: the engine's side of the request
    # trace — each block span LINKS the request spans it advanced, the
    # same fan-in idiom async pours use for their contributing uploads
    def _advance_steps_span(self, tokens_out: int) -> None:
        if self._steps_span is None:
            self._steps_span = obs_trace.tracer.start_span(
                "serving.decode_steps", root=True)
            self._steps_in_span = 0
            self._span_tokens = 0
            for req in self._inflight.values():
                self._steps_span.add_link(req.span, slot=req.slot)
        else:
            # requests admitted since the block opened fan in too
            linked = {ln["span_id"]
                      for ln in getattr(self._steps_span, "links", ())}
            for req in self._inflight.values():
                ctx = req.span.context
                if ctx is not None and ctx.span_id not in linked:
                    self._steps_span.add_link(req.span, slot=req.slot)
        self._steps_in_span += 1
        self._span_tokens += tokens_out
        if self._steps_in_span >= DECODE_SPAN_STEPS:
            self._close_steps_span()

    def _close_steps_span(self) -> None:
        if self._steps_span is None:
            return
        self._steps_span.set_attr("steps", self._steps_in_span)
        self._steps_span.set_attr("tokens", self._span_tokens)
        self._steps_span.end()
        self._steps_span = None

    # ------------------------------------------------------------- health --
    def _watchdog_probe(self) -> Dict[str, Any]:
        return {"occupancy": self.scheduler.active_count(),
                "queue_depth": self.queue_depth(),
                "last_progress_ts": self.last_progress_ts,
                "poisoned": not self.scheduler.last_step_finite}

    def health(self) -> Dict[str, Any]:
        """Liveness summary for ``/healthz``: ``status`` is ``ok`` until
        the watchdog has tripped without progress since."""
        now = time.time()
        age = now - self.last_progress_ts
        status = "ok"
        if not self._running:
            status = "stopped"
        elif not self.scheduler.last_step_finite:
            status = "nan_logits"
        elif (self.watchdog.stall_s > 0
              and self.scheduler.active_count() > 0
              and age > self.watchdog.stall_s):
            status = "stalled"
        return {"status": status,
                "occupancy": self.scheduler.active_count(),
                "queue_depth": self.queue_depth(),
                "last_step_age_s": round(age, 3),
                "steps_run": int(self.scheduler.steps_run),
                "tokens_per_s": round(self.tokens_per_s(), 2),
                "watchdog_trips": int(self.watchdog.trips),
                "flight_records": len(self.flight)}

    def debug_state(self) -> Dict[str, Any]:
        """``/debug/state`` payload: the scheduler's slot matrix +
        block-table summary and a snapshot of the waiting queue."""
        # the engine thread mutates _pending concurrently; copying a
        # deque mid-mutation raises RuntimeError in CPython, and exactly
        # a busy queue is when the operator wants this endpoint
        for _ in range(8):
            try:
                head = list(self._pending)[:32]
                break
            except RuntimeError:
                continue
        else:
            head = []
        pending = [{"prompt_tokens": len(r.ids), "max_new": r.max_new,
                    "adapter_idx": r.adapter_idx,
                    "waiting_s": round(time.time() - r.submitted_ts, 3)}
                   for r in head]
        return {"engine": self.health(),
                "scheduler": self.scheduler.debug_state(),
                "queue": {"depth": self.queue_depth(),
                          "pending_head": pending}}

    # ------------------------------------------------------------- control --
    def stop(self) -> None:
        self._running = False
        self.watchdog.stop()
        self._thread.join(timeout=5.0)
        # serving has no round boundary: without this final snapshot a
        # short session's TTFT/ITL histograms never reach the run log
        # (the wall-clock flusher only covers sessions longer than its
        # cadence)
        obs_metrics.flush_final()
