"""BatchingEngine — threaded request queue over the DecodeScheduler.

The serving surface (HTTP handler threads) submits requests and blocks on
per-request futures; ONE worker thread owns the scheduler and runs the
admit → step → evict loop. Iteration-level scheduling: a finishing
request frees its slot at the very next step boundary and a queued
request is admitted into it — no batch barriers, no head-of-line
blocking behind long generations (Orca's core idea).

Deadlines: a request past its deadline is EVICTED at the next step
boundary and resolves with what it has, ``finish_reason: "deadline"`` —
tail-latency control the autoscaler's p99 policies can rely on, and a
reason clients can tell apart from an honest ``"length"`` budget stop.

Fault tolerance (crash-only recovery, Candea & Fox): a watchdog trip
(NaN/inf logits, or a decode stall the loop can observe — an injected
chaos stall, or any wedge between steps) triggers a CONTROLLED RESET
instead of a permanent 503 — every in-flight request is snapshotted
(prompt, tokens so far, remaining budget, seed, adapter index), the slot
matrix + paged KV pool + scheduler state are rebuilt (same geometry,
zero recompiles), and the snapshots are requeued at the queue front for
recompute-from-prompt. A step that hard-hangs INSIDE the XLA dispatch
cannot be interrupted from this thread: /healthz stays 503 "stalled"
and recovery is the replica level's job (gateway routes around it,
``ReplicaSet.health_check``/drain-restart replaces the process) — and a
slow step that eventually RETURNS is progress, so its stale trip is
deliberately dropped rather than resetting a healthy engine. Sampling is stateless per (seed, position), so a replayed
sampled decode regenerates bit-identical tokens. Resets are budgeted
(``max_resets`` per ``reset_window_s``); past the budget the engine
stays unhealthy, dumps its flight ring, and resolves survivors with
``finish_reason: "preempted"`` (partial progress) or the same
Overloaded 503 a fresh submit gets (zero tokens — an empty "success"
would dodge the gateway's failover). Graceful degradation: when the queue
head starves past ``preempt_after_s`` the YOUNGEST slot is preempted and
requeued (it keeps its progress), and past ``shed_queue_depth`` submits
fail fast with :class:`~fedml_tpu.serving.Overloaded` (HTTP 503 +
``Retry-After``) instead of wedging.

Observability (the full request lifecycle through the ``core/obs``
planes):

* one trace per request — ``serving.request`` (child of the HTTP
  surface's span when one is active, so an inbound W3C ``traceparent``
  joins the caller's trace) containing ``serving.queue`` (submit →
  admission), ``serving.prefill`` (chunked prefill), and
  ``serving.decode`` (first token → finish/evict, decode progress as
  step-bucketed events, never per-token);
* shared engine-side ``serving.decode_steps`` spans — one per block of
  decode steps, LINKING the in-flight request spans they advanced (the
  fan-in idiom async pours use for their contributing uploads);
* SLO metrics — TTFT, inter-token latency (one observation per decode
  STEP), per-request tokens/s + queue wait, KV block-pool occupancy/
  fragmentation/admission headroom, evictions and rejections by reason;
* a black-box :class:`~fedml_tpu.core.obs.flight.FlightRecorder` ring of
  the last N lifecycle/step records, dumped on engine crash or when the
  :class:`~fedml_tpu.core.obs.flight.Watchdog` trips (no decode progress
  for ``watchdog_s`` while occupancy > 0, or NaN/inf decode logits).
"""

from __future__ import annotations

import collections
import logging
import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Deque, Dict, List, Optional

from ...core.obs import flight as obs_flight
from ...core.obs import metrics as obs_metrics
from ...core.obs import trace as obs_trace
from ...llm.data import EOS

logger = logging.getLogger(__name__)

# decode progress lands on the request span every this-many tokens (an
# event per token would make span records O(completion) large)
PROGRESS_EVERY_TOKENS = 16
# one shared serving.decode_steps span per this-many decode steps
DECODE_SPAN_STEPS = 32


class _Request:
    __slots__ = ("ids", "max_new", "temperature", "seed", "adapter_idx",
                 "deadline_ts", "future", "span", "out_ids", "slot",
                 "submitted_ts", "queue_span", "decode_span", "admit_ts",
                 "decode_ts", "requeues", "admit_seq", "queue_wait_start",
                 "stream_q", "adapter_pinned")

    def __init__(self, ids, max_new, temperature, seed, adapter_idx,
                 deadline_ts, span):
        self.ids = ids
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.adapter_idx = int(adapter_idx)
        self.deadline_ts = deadline_ts
        self.future: Future = Future()
        self.span = span
        self.out_ids: List[int] = []
        self.slot: Optional[int] = None
        self.submitted_ts = time.time()
        self.queue_span = None
        self.decode_span = None
        self.admit_ts: Optional[float] = None   # queue end (prefill start)
        self.decode_ts: Optional[float] = None  # first token (decode start)
        self.requeues = 0       # reset/preempt recompute cycles so far
        self.admit_seq = -1     # admission order stamp; max = youngest
        # starvation clock: when THIS queue wait began (reset on every
        # requeue, else a once-preempted request instantly reads as
        # starved and preempts its preemptor — ping-pong)
        self.queue_wait_start = self.submitted_ts
        # SSE streaming: tokens are pushed here as they decode; a
        # requeue/recovery replays transparently (the kept prefix is
        # never re-emitted — only genuinely new tokens flow)
        self.stream_q = None
        # hot-swap safety: a pinned adapter row is never reused while
        # this request (including its requeued replays) is in flight
        self.adapter_pinned = False


class BatchingEngine:
    """Continuous-batching front over one :class:`DecodeScheduler`."""

    def __init__(self, scheduler, default_deadline_s: float = 0.0,
                 rate_window_s: float = 2.0, watchdog_s: float = 30.0,
                 flight_records: int = 256,
                 flight_dir: Optional[str] = None,
                 max_resets: int = 3, reset_window_s: float = 300.0,
                 max_requeues: int = 2, preempt_after_s: float = 0.0,
                 shed_queue_depth: int = 0, chaos=None):
        self.scheduler = scheduler
        self.default_deadline_s = float(default_deadline_s)
        self.rate_window_s = float(rate_window_s)
        self._q: "queue.Queue[_Request]" = queue.Queue()
        self._pending: Deque[_Request] = collections.deque()
        self._inflight: Dict[int, _Request] = {}
        self._tokens: Deque = collections.deque()   # (ts, n) for tokens/s
        self._running = True
        # --- fault tolerance ----------------------------------------------
        self.max_resets = int(max_resets)
        self.reset_window_s = float(reset_window_s)
        self.max_requeues = int(max_requeues)
        self.preempt_after_s = float(preempt_after_s)
        self.shed_queue_depth = int(shed_queue_depth)
        self._chaos = chaos      # optional ServingChaosInjector
        self._reset_requested: Optional[str] = None   # watchdog -> loop
        self._reset_times: List[float] = []
        self._last_reset_ts = 0.0
        self.resets_total = 0
        self._failed: Optional[str] = None   # reset budget exhausted
        self._admit_counter = 0
        self._wave_seq = 0       # piggybacked-prefill wave stamp
        self._req_wall_ema: Optional[float] = None   # Retry-After input
        self._last_fault_step = -1   # one plan consult per step index
        # SLO instruments for /healthz: trailing exact-percentile TTFT /
        # ITL windows the autoscaler's SLOPolicy and the gateway's
        # saturation check read without Prometheus parsing
        self._ttft_window = obs_metrics.LatencyWindow(window_s=30.0)
        self._itl_window = obs_metrics.LatencyWindow(window_s=10.0)
        # --- black box + watchdog ------------------------------------------
        self.flight = obs_flight.FlightRecorder(
            "serving_engine", capacity=int(flight_records))
        self._flight_path = None
        if flight_dir:
            # the fallback dir is args.log_file_dir, whose schema default
            # is '~/...' — without expansion the dump lands in a literal
            # './~/' directory and the post-mortem artifact goes missing
            self._flight_path = os.path.join(
                os.path.expanduser(flight_dir),
                f"flight_serving_engine_{os.getpid()}.jsonl")
        self.last_progress_ts = time.time()
        self.watchdog = obs_flight.Watchdog(
            "serving_engine", self._watchdog_probe, recorder=self.flight,
            stall_s=float(watchdog_s), dump_path=self._flight_path,
            on_trip=self._on_watchdog_trip)
        self.watchdog.start()
        # shared decode-step block span (bare handle, worker thread only)
        self._steps_span = None
        self._steps_in_span = 0
        self._span_tokens = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-batch-engine")
        self._thread.start()

    # ------------------------------------------------------------- submit --
    def submit(self, prompt_ids, max_new_tokens: int = 64,
               temperature: float = 0.0, seed: int = 0,
               adapter_idx: int = 0,
               deadline_s: Optional[float] = None,
               parent: Any = None, stream_q=None,
               adapter_pre_pinned: bool = False) -> Future:
        """Enqueue one request; the future resolves to ``{"ids",
        "finish_reason", "prompt_tokens", "completion_tokens"}``.

        ``parent`` optionally parents the request trace (a Span,
        SpanContext, or raw traceparent string — e.g. an inbound HTTP
        header); with no parent the request joins the submitting
        thread's current span (the HTTP surface's ``serving.http``) or
        roots a fresh trace.

        ``stream_q``: an optional queue; each generated token is put as
        ``("token", id)`` the step it decodes, followed by one
        ``("finish", reason)`` after the future resolves (``("error",
        msg)`` on failure). A preempt/reset replay is transparent
        mid-stream: the kept prefix is never re-emitted.

        ``adapter_pre_pinned``: the caller already holds the adapter
        row's pin (an atomic name-resolve + retain — the template's
        hot-swap-safe path); ownership transfers to the request and is
        released at resolution. Raises before the request object exists
        (stopped/failed/shed) leave the pin with the caller."""
        if not self._running:
            obs_metrics.record_llm_reject("engine_stopped")
            raise RuntimeError("engine stopped")
        if self._failed is not None:
            # typed 503, not a bare RuntimeError: the HTTP runner maps
            # Overloaded to 503 + Retry-After, which is what lets the
            # gateway quarantine this replica and route around it — a
            # 500 here would surface to the client as a replica answer
            from .. import Overloaded
            obs_metrics.record_llm_reject("engine_failed")
            raise Overloaded(
                f"engine unhealthy (reset budget exhausted after "
                f"{self._failed}); drain and restart the replica",
                retry_after_s=30.0)
        if self.shed_queue_depth > 0:
            depth = self.queue_depth()
            if depth >= self.shed_queue_depth:
                # overload is a SIGNAL: fail fast with a Retry-After
                # hint derived from queue depth and KV admission
                # headroom instead of wedging the caller in the queue
                from .. import Overloaded
                retry_after = self._retry_after_s(depth)
                obs_metrics.record_llm_reject("overloaded")
                self.flight.note("shed", queue_depth=depth,
                                 retry_after_s=round(retry_after, 3))
                raise Overloaded(
                    f"queue depth {depth} >= shed bound "
                    f"{self.shed_queue_depth}",
                    retry_after_s=retry_after)
        span = obs_trace.tracer.start_span(
            "serving.request", parent=parent,
            attrs={"prompt_tokens": len(prompt_ids),
                   "adapter_idx": int(adapter_idx)})
        dl = self.default_deadline_s if deadline_s is None \
            else float(deadline_s)
        req = _Request(list(map(int, prompt_ids)), max_new_tokens,
                       temperature, seed, adapter_idx,
                       time.time() + dl if dl > 0 else None, span)
        req.stream_q = stream_q
        # from here on the request owns the caller's pin: every early
        # resolution below (_finish/_reject) releases it
        req.adapter_pinned = bool(adapter_pre_pinned)
        if req.max_new <= 0 or not req.ids:
            self._finish(req, "length")
            return req.future
        if len(req.ids) >= self.scheduler.cfg.max_seq_len:
            err = ValueError(
                f"prompt of {len(req.ids)} tokens >= max_seq_len "
                f"{self.scheduler.cfg.max_seq_len}")
            self._reject(req, "prompt_too_long", err)
            return req.future
        ccfg = self.scheduler.cache_cfg
        need = ccfg.blocks_needed(min(len(req.ids) + req.max_new,
                                      ccfg.max_seq_len))
        if need > ccfg.num_blocks:
            # can_admit() would be False forever: failing it now beats
            # wedging the queue head until the caller's timeout
            err = ValueError(
                f"request needs {need} KV blocks, pool has only "
                f"{ccfg.num_blocks} (raise num_blocks or shrink the "
                "request)")
            self._reject(req, "kv_pool_too_small", err)
            return req.future
        req.queue_span = obs_trace.tracer.start_span(
            "serving.queue", parent=span)
        # stitch: the queue phase starts when the request does — the
        # microseconds between the two start_span calls must not read as
        # unattributed wall in the waterfall
        if req.queue_span.span_id is not None:
            req.queue_span.start_ts = span.start_ts
        # pin the adapter row for the request's whole lifetime (incl.
        # requeued replays): a hot-swap repoints the NAME to a new row,
        # but this row is not reused until the pin drops — in-flight
        # requests keep the version they started with. (A pre-pinned
        # caller already did this atomically with name resolution.)
        if not req.adapter_pinned:
            bank = getattr(self.scheduler, "bank", None)
            if bank is not None and hasattr(bank, "retain_row"):
                bank.retain_row(req.adapter_idx)
                req.adapter_pinned = True
        self.flight.note("submit", prompt_tokens=len(req.ids),
                         max_new=req.max_new, adapter_idx=req.adapter_idx,
                         trace_id=span.trace_id)
        self._q.put(req)
        return req.future

    def _reject(self, req: _Request, reason: str, err: Exception) -> None:
        obs_metrics.record_llm_reject(reason)
        self.flight.note("reject", reason=reason)
        req.span.set_attr("error", reason).end()
        self._release_adapter_pin(req)
        self._stream_error(req, err)
        req.future.set_exception(err)

    def _release_adapter_pin(self, req: _Request) -> None:
        if not req.adapter_pinned:
            return
        req.adapter_pinned = False
        bank = getattr(self.scheduler, "bank", None)
        if bank is not None and hasattr(bank, "release_row"):
            try:
                bank.release_row(req.adapter_idx)
            except Exception:  # noqa: BLE001 — resolution must not raise
                logger.exception("adapter pin release failed")

    @staticmethod
    def _stream_error(req: _Request, err: Exception) -> None:
        if req.stream_q is not None:
            try:
                req.stream_q.put(("error", str(err)))
            except Exception:  # noqa: BLE001
                pass

    def queue_depth(self) -> int:
        return self._q.qsize() + len(self._pending)

    # --------------------------------------------------------------- loop --
    def _loop(self) -> None:
        while self._running:
            try:
                if self._reset_requested is not None:
                    self._recover(self._reset_requested)
                    continue
                if self._failed is not None:
                    # unhealthy but alive: answer /healthz, resolve any
                    # racing submits, never wedge a caller
                    self._drain_queue()
                    while self._pending:
                        self._resolve_parked(self._pending.popleft())
                    time.sleep(0.05)
                    continue
                self._drain_queue()
                self._admit()
                self._evict_deadlines()
                if not self._inflight:
                    self._close_steps_span()  # idle: don't span the wait
                    if not self._pending:
                        try:
                            self._pending.append(self._q.get(timeout=0.05))
                        except queue.Empty:
                            pass
                    else:
                        # pending but unadmittable (pool too small for the
                        # request) with nothing in flight: don't busy-spin
                        time.sleep(0.005)
                    continue
                if self._chaos is not None and not self._inject_chaos():
                    continue   # injected fault aborted this step
                self.last_progress_ts = time.time()  # entering the step:
                # only a step that HANGS past stall_s reads as a stall,
                # not a slow first-compile that returns
                t0 = time.perf_counter()
                toks = self.scheduler.step()
                if not self.scheduler.last_step_finite:
                    # poisoned step: the tokens are garbage — discard
                    # them and run the controlled reset (requeue +
                    # recompute); a persistent poison source exhausts the
                    # reset budget and parks the engine unhealthy
                    self.flight.note("poisoned_step",
                                     step=int(self.scheduler.steps_run))
                    self._recover("nan_logits")
                    continue
                self._observe_step(len(toks), time.perf_counter() - t0)
                self._collect(toks)
            except Exception:  # noqa: BLE001 — serving loop must survive
                logger.exception("batch engine step failed")
                self.flight.note("engine_crash")
                self.flight.dump(self._flight_path, reason="crash")
                self._fail_all(RuntimeError("batch engine step failed"))
        # drain on shutdown
        self._close_steps_span()
        self._fail_all(RuntimeError("engine stopped"))

    def _inject_chaos(self) -> bool:
        """Consult the serving fault plan for the NEXT decode step.
        Returns False when the injected fault aborted the step (stall
        interrupted by a watchdog-requested reset, or NaN poison).

        One consult per step INDEX: a reset doesn't advance
        ``steps_run`` (the aborted step never ran), so without the
        latch the same scheduled fault would re-fire on every recovery
        attempt and a single injected NaN would read as a permanent
        poison source."""
        step_idx = int(self.scheduler.steps_run)
        if step_idx == self._last_fault_step:
            return True
        self._last_fault_step = step_idx
        kind = self._chaos.decode_fault(step_idx)
        if kind is None:
            return True
        if kind == "nan":
            # poison the step flag exactly like non-finite logits would:
            # the loop's finite check turns this into a controlled reset
            self.flight.note("chaos_nan",
                             step=int(self.scheduler.steps_run))
            self.scheduler.last_step_finite = False
            self._recover("nan_logits")
            return False
        # stall: wedge interruptibly — last_progress_ts stops moving, the
        # watchdog trips, and its reset request cuts the stall short the
        # way a process restart would. A stall shorter than the watchdog
        # leash just rides out (tolerated without a reset).
        stall_s = self._chaos.stall_s()
        self.flight.note("chaos_stall", step=int(self.scheduler.steps_run),
                         stall_s=stall_s)
        deadline = time.time() + stall_s
        while (time.time() < deadline and self._running
               and self._reset_requested is None):
            time.sleep(0.01)
        return self._reset_requested is None and self._running

    def _drain_queue(self) -> None:
        while True:
            try:
                self._pending.append(self._q.get_nowait())
            except queue.Empty:
                return

    def _admit(self) -> None:
        wave_w = int(getattr(self.scheduler, "prefill_batch", 0) or 0)
        use_wave = wave_w > 1 and hasattr(self.scheduler, "begin_admit")
        wave: List[tuple] = []   # (req, pending, dequeue_ts, span)
        while self._pending:
            req = self._pending[0]
            now = time.time()
            if req.deadline_ts is not None and now > req.deadline_ts:
                self._pending.popleft()
                obs_metrics.record_llm_evict("deadline_queued")
                req.span.add_event("evict", reason="deadline_queued")
                self.flight.note("evict", reason="deadline_queued")
                self._finish(req, "deadline")
                continue
            # recompute-from-prompt: a requeued request re-prefills its
            # prompt PLUS the tokens it already generated — sampling is
            # stateless per (seed, absolute position), so the remaining
            # decode replays bit-identically; the budget shrinks by the
            # prefix it keeps
            admit_ids = req.ids + req.out_ids
            remaining = req.max_new - len(req.out_ids)
            if remaining <= 0:   # requeued at exactly its budget edge
                self._pending.popleft()
                self._finish(req, "length")
                continue
            if not self.scheduler.can_admit(len(admit_ids), remaining):
                if wave:
                    break   # flush the collected wave; retry next pass
                if not self._maybe_preempt_for(req, now):
                    break
                if not self.scheduler.can_admit(len(admit_ids),
                                                remaining):
                    break
            if not use_wave:
                self._pending.popleft()
                self._admit_one(req, admit_ids, remaining)
                continue
            # piggybacked admission: reserve now, prefill as one wave
            self._pending.popleft()
            dequeue_ts = time.time()
            if req.queue_span is not None:
                req.queue_span.end()
                req.queue_span = None
            prefill_span = obs_trace.tracer.start_span(
                "serving.prefill", parent=req.span,
                attrs={"prompt_tokens": len(admit_ids)})
            if prefill_span.span_id is not None:
                prefill_span.start_ts = dequeue_ts  # stitch to queue end
            try:
                pending = self.scheduler.begin_admit(
                    admit_ids, adapter_idx=req.adapter_idx,
                    temperature=req.temperature, seed=req.seed,
                    max_new_tokens=remaining)
            except Exception as e:  # noqa: BLE001
                prefill_span.set_attr("error", type(e).__name__).end()
                req.span.set_attr("error", type(e).__name__).end()
                self._release_adapter_pin(req)
                self._stream_error(req, e)
                req.future.set_exception(e)
                continue
            if pending is None:   # raced out of space since can_admit
                prefill_span.end()
                self._requeue_front(req)
                break
            wave.append((req, pending, dequeue_ts, prefill_span))
            if len(wave) >= wave_w:
                self._flush_wave(wave)
                wave = []
        if wave:
            self._flush_wave(wave)

    def _requeue_front(self, req: _Request) -> None:
        """Put an unadmittable dequeued head back where it was, with a
        fresh queue span so the renewed wait stays attributed."""
        req.queue_span = obs_trace.tracer.start_span(
            "serving.queue", parent=req.span)
        self._pending.appendleft(req)

    def _admit_one(self, req: _Request, admit_ids: List[int],
                   remaining: int) -> None:
        """The serial (non-wave) admission path — one chunked prefill
        per request, today's default."""
        dequeue_ts = time.time()
        if req.queue_span is not None:
            req.queue_span.end()
            req.queue_span = None
        prefill_span = obs_trace.tracer.start_span(
            "serving.prefill", parent=req.span,
            attrs={"prompt_tokens": len(admit_ids)})
        if prefill_span.span_id is not None:
            prefill_span.start_ts = dequeue_ts  # stitch to queue end
        try:
            slot, first = self.scheduler.admit(
                admit_ids, adapter_idx=req.adapter_idx,
                temperature=req.temperature, seed=req.seed,
                max_new_tokens=remaining)
        except Exception as e:  # noqa: BLE001
            prefill_span.set_attr("error", type(e).__name__).end()
            req.span.set_attr("error", type(e).__name__).end()
            self._release_adapter_pin(req)
            self._stream_error(req, e)
            req.future.set_exception(e)
            return
        info = getattr(self.scheduler, "last_admit_info", None)
        self._post_admit(req, slot, first, dequeue_ts, prefill_span,
                         info)

    def _flush_wave(self, wave: List[tuple]) -> None:
        """Run one piggybacked prefill over the collected admissions and
        complete their per-request bookkeeping."""
        self._wave_seq += 1
        obs_metrics.record_llm_prefill_wave(len(wave))
        try:
            firsts = self.scheduler.finish_admits(
                [pending for _, pending, _, _ in wave])
        except Exception as e:  # noqa: BLE001
            logger.exception("piggybacked prefill wave failed")
            for req, pending, _, span in wave:
                try:
                    self.scheduler.abort_admit(pending)
                except Exception:  # noqa: BLE001
                    pass
                span.set_attr("error", type(e).__name__).end()
                req.span.set_attr("error", type(e).__name__).end()
                self._release_adapter_pin(req)
                self._stream_error(req, e)
                req.future.set_exception(e)
            return
        for (req, pending, dequeue_ts, span), first in zip(wave, firsts):
            self._post_admit(req, pending.slot, first, dequeue_ts, span,
                             pending.info, wave_id=self._wave_seq,
                             wave_size=len(wave))

    def _post_admit(self, req: _Request, slot: int, first: int,
                    dequeue_ts: float, prefill_span,
                    info: Optional[Dict[str, Any]],
                    wave_id: Optional[int] = None,
                    wave_size: int = 1) -> None:
        now = time.time()
        self.last_progress_ts = now  # a slow prefill is not a stall
        prefill_span.set_attr("slot", slot)
        if info:
            # the serving_report waterfall's prefix-cache annotation:
            # tokens served from resident blocks vs actually prefilled
            prefill_span.set_attr("cached_tokens",
                                  int(info.get("cached_tokens", 0)))
            prefill_span.set_attr("novel_tokens",
                                  int(info.get("novel_tokens", 0)))
        if wave_id is not None:
            prefill_span.set_attr("wave", int(wave_id))
            prefill_span.set_attr("wave_size", int(wave_size))
        first_admit = req.decode_ts is None
        req.slot = slot
        self._admit_counter += 1
        req.admit_seq = self._admit_counter
        if first_admit:
            req.admit_ts = dequeue_ts
            req.decode_ts = now
            # first token exists the moment prefill returns: TTFT is
            # submit -> here (queue wait + chunked prefill, Orca's
            # SLO). A RE-admission keeps the original TTFT — the
            # user saw their first token before the reset.
            req.span.set_attr("ttft_s",
                              round(now - req.submitted_ts, 6))
            obs_metrics.record_llm_ttft(now - req.submitted_ts)
            self._ttft_window.observe(now - req.submitted_ts)
        req.span.add_event("admit", slot=slot,
                           recompute=not first_admit)
        obs_metrics.record_llm_admit()
        self._note_kv_pool()
        note = {"slot": slot, "recompute": not first_admit,
                "queue_wait_s": round(dequeue_ts - req.submitted_ts, 6)}
        if info:
            note["cached_tokens"] = int(info.get("cached_tokens", 0))
            note["aliased_blocks"] = int(info.get("aliased_blocks", 0))
        self.flight.note("admit", **note)
        self._inflight[slot] = req
        req.decode_span = obs_trace.tracer.start_span(
            "serving.decode", parent=req.span, attrs={"slot": slot})
        if req.decode_span.span_id is not None:
            req.decode_span.start_ts = now  # stitch to prefill end
        prefill_span.end()
        self._note_tokens(1)
        if not self._append_token(req, first):
            self._retire(req)

    def _maybe_preempt_for(self, starved: _Request, now: float) -> bool:
        """Graceful degradation: when the queue head has starved past
        ``preempt_after_s``, preempt-and-requeue the YOUNGEST slot (it
        keeps its generated prefix and recomputes later) instead of
        letting the head deadline-expire in the queue. Returns True when
        a slot was freed."""
        if (self.preempt_after_s <= 0 or not self._inflight
                or now - starved.queue_wait_start < self.preempt_after_s):
            return False
        # ping-pong between two long requests is bounded by the per-
        # request requeue budget: a victim past it resolves "preempted"
        # instead of cycling forever
        victim = max(self._inflight.values(), key=lambda r: r.admit_seq)
        obs_metrics.record_llm_evict("preempted")
        victim.span.add_event("preempt", slot=victim.slot,
                              for_queue_wait_s=round(
                                  now - starved.queue_wait_start, 3))
        self.flight.note("preempt", slot=victim.slot,
                         tokens_kept=len(victim.out_ids))
        self._inflight.pop(victim.slot, None)
        # suffix-seam release: the victim's generated blocks stay warm,
        # so its requeue re-admits against its own cached chain
        self._release_slot(victim)
        victim.slot = None
        self._note_kv_pool()
        if self._requeue(victim, "pressure"):
            # _requeue appendlefts; the starved head must stay at
            # the front — rotate the victim to just behind it
            self._pending.popleft()           # the victim
            head = self._pending.popleft()    # the starved request
            self._pending.appendleft(victim)
            self._pending.appendleft(head)
        return True

    def _append_token(self, req: _Request, token: int) -> bool:
        """Append one generated token; False when the request finished."""
        if token == EOS:
            self._finish(req, "stop")
            return False
        req.out_ids.append(int(token))
        if req.stream_q is not None:
            req.stream_q.put(("token", int(token)))
        if (len(req.out_ids) % PROGRESS_EVERY_TOKENS == 0
                and req.decode_span is not None):
            req.decode_span.add_event("decode.progress",
                                      tokens=len(req.out_ids))
        if (len(req.out_ids) >= req.max_new
                or (req.slot is not None
                    and self.scheduler.slot_position(req.slot) + 1
                    >= self.scheduler.cfg.max_seq_len)):
            self._finish(req, "length")
            return False
        return True

    def _collect(self, toks: Dict[int, int]) -> None:
        self._note_tokens(len(toks))
        for slot, token in toks.items():
            req = self._inflight.get(slot)
            if req is None:
                continue
            if not self._append_token(req, token):
                self._retire(req)

    def _evict_deadlines(self) -> None:
        now = time.time()
        for slot, req in list(self._inflight.items()):
            if req.deadline_ts is not None and now > req.deadline_ts:
                obs_metrics.record_llm_evict("deadline")
                req.span.add_event("evict", reason="deadline", slot=slot)
                self.flight.note("evict", reason="deadline", slot=slot)
                self._finish(req, "deadline")
                self._retire(req)

    # ----------------------------------------------------------- recovery --
    def _on_watchdog_trip(self, reason: str) -> None:
        """Watchdog thread → worker loop: request a controlled reset.
        The flag (not the recovery itself) crosses the thread boundary;
        the worker owns every piece of scheduler state."""
        if self.max_resets > 0 and self._failed is None:
            self._reset_requested = reason

    def _recover(self, reason: str) -> None:
        """The controlled reset: snapshot in-flight requests, rebuild the
        scheduler (slot matrix + paged KV pool, same compiled programs),
        requeue the snapshots at the queue FRONT for recompute-from-
        prompt. Bounded by ``max_resets`` per ``reset_window_s``."""
        self._reset_requested = None
        now = time.time()
        # drop a STALE watchdog trip that raced a recovery the loop
        # already ran: if the condition the trip fired on no longer
        # holds (logits finite again / progress since resumed), a second
        # reset would only burn budget and requeue healthy work
        if reason == "nan_logits" and self.scheduler.last_step_finite:
            return
        if reason == "stalled" \
                and now - self.last_progress_ts < self.watchdog.stall_s:
            return
        self._reset_times = [t for t in self._reset_times
                             if now - t < self.reset_window_s]
        if len(self._reset_times) >= self.max_resets:
            self._give_up(reason)
            return
        self._reset_times.append(now)
        self._last_reset_ts = now
        self.resets_total += 1
        obs_metrics.record_llm_reset(reason)
        self.flight.note("engine_reset", reason=reason,
                         resets_in_window=len(self._reset_times),
                         inflight=len(self._inflight))
        # post-mortem of this episode first — the dump path gets a
        # monotonic suffix, so earlier episodes survive on disk
        self.flight.dump(self._flight_path, reason=f"reset:{reason}")
        self._close_steps_span()
        # youngest requeued first so the OLDEST lands at the queue head
        # (each _requeue appendlefts): admission order is preserved
        victims = sorted(self._inflight.values(),
                         key=lambda r: r.admit_seq, reverse=True)
        self._inflight.clear()
        requeued = 0
        for req in victims:
            req.slot = None
            if self._requeue(req, reason):
                requeued += 1
        self.scheduler.reset()
        self._note_kv_pool()
        self.flight.note("engine_reset_done", requeued=requeued)
        self.last_progress_ts = time.time()   # progress resumed: re-arm

    def _requeue(self, req: _Request, reason: str) -> bool:
        """Snapshot one in-flight request back into the pending queue
        (front, caller preserves order) for recompute-from-prompt; a
        request past its requeue budget resolves ``"preempted"`` with
        the tokens it has. Returns True when requeued."""
        if req.decode_span is not None:
            req.decode_span.set_attr("requeued", reason)
            req.decode_span.set_attr("completion_tokens",
                                     len(req.out_ids))
            req.decode_span.end()
            req.decode_span = None
        if req.requeues >= self.max_requeues:
            obs_metrics.record_llm_evict("requeue_exhausted")
            req.span.add_event("evict", reason="requeue_exhausted")
            self.flight.note("evict", reason="requeue_exhausted",
                             requeues=req.requeues)
            self._finish(req, "preempted")
            return False
        req.requeues += 1
        obs_metrics.record_llm_requeue(reason)
        req.span.add_event("requeue", reason=reason,
                           requeues=req.requeues,
                           tokens_kept=len(req.out_ids))
        self.flight.note("requeue", reason=reason,
                         requeues=req.requeues,
                         tokens_kept=len(req.out_ids))
        # the re-wait is queue time again — open a fresh queue span so
        # the waterfall attributes the recovery gap instead of losing it
        req.queue_span = obs_trace.tracer.start_span(
            "serving.queue", parent=req.span)
        req.queue_wait_start = time.time()
        self._pending.appendleft(req)
        return True

    def _give_up(self, reason: str) -> None:
        """Reset budget exhausted: park the engine unhealthy (/healthz
        503), dump the ring, and resolve every survivor ``"preempted"``
        — degraded, never wedged."""
        self._failed = reason
        logger.error("batch engine: reset budget exhausted (%d resets "
                     "in %.0fs window) after %s — staying unhealthy",
                     len(self._reset_times), self.reset_window_s, reason)
        self.flight.note("engine_failed", reason=reason,
                         resets_in_window=len(self._reset_times))
        self.flight.dump(self._flight_path,
                         reason="reset_budget_exhausted")
        self._close_steps_span()
        for req in list(self._inflight.values()):
            self._retire(req)
            self._resolve_parked(req)
        self._drain_queue()
        while self._pending:
            self._resolve_parked(self._pending.popleft())

    def _resolve_parked(self, req: _Request) -> None:
        """Close out one request on a parked engine: partial progress
        resolves ``"preempted"`` (the tokens it has are real work worth
        returning), but a ZERO-token request gets the same Overloaded
        503 a fresh submit would — an empty 200 "success" would read as
        a served completion and the gateway would never fail it over."""
        if req.future.done():
            return
        if req.out_ids:
            self._finish(req, "preempted")
            return
        from .. import Overloaded
        obs_metrics.record_llm_reject("engine_failed")
        self.flight.note("reject", reason="engine_failed")
        self._end_spans_on_error(req)
        err = Overloaded(
            f"engine unhealthy (reset budget exhausted after "
            f"{self._failed}); drain and restart the replica",
            retry_after_s=30.0)
        self._release_adapter_pin(req)
        self._stream_error(req, err)
        req.future.set_exception(err)

    def _retry_after_s(self, depth: int) -> float:
        """Retry-After from the live gauges: how long until the queue
        ahead of a new arrival drains, estimated as (depth / admission
        headroom) request-walls. Headroom comes from the KV pool's
        worst-case admission gauge; the wall EMA from finished
        requests."""
        wall = self._req_wall_ema if self._req_wall_ema else 1.0
        try:
            headroom = max(
                int(self.scheduler.kv_pool_stats()["headroom_requests"]),
                1)
        except Exception:  # noqa: BLE001 — shedding must never raise
            headroom = 1
        waves = max(float(depth) / float(headroom), 1.0)
        return min(max(waves * wall, 0.5), 60.0)

    def _retire(self, req: _Request) -> None:
        if req.slot is not None:
            self._inflight.pop(req.slot, None)
            self._release_slot(req)
            req.slot = None
            self._note_kv_pool()

    def _release_slot(self, req: _Request) -> None:
        """Release through the suffix-cache seam when the scheduler has
        one: the full token chain (prompt + generated) rides along so
        fully-written decode blocks get indexed for follow-up/requeued
        aliasing. getattr-gated — stub schedulers keep their single-arg
        ``release``."""
        if getattr(self.scheduler, "suffix_cache", False):
            self.scheduler.release(req.slot,
                                   final_ids=req.ids + req.out_ids)
        else:
            self.scheduler.release(req.slot)

    def _finish(self, req: _Request, reason: str) -> None:
        if req.future.done():
            return
        now = time.time()
        req.span.set_attr("completion_tokens", len(req.out_ids))
        req.span.set_attr("finish_reason", reason)
        if req.admit_ts is not None:
            queue_wait = req.admit_ts - req.submitted_ts
            decode_wall = max(now - (req.decode_ts or req.admit_ts), 1e-9)
            tps = len(req.out_ids) / decode_wall
            req.span.set_attr("queue_wait_s", round(queue_wait, 6))
            req.span.set_attr("tokens_per_s", round(tps, 2))
            obs_metrics.record_llm_request(tps, queue_wait)
            # request-wall EMA feeds the load-shed Retry-After estimate
            wall = now - req.submitted_ts
            self._req_wall_ema = (wall if self._req_wall_ema is None
                                  else 0.3 * wall
                                  + 0.7 * self._req_wall_ema)
        # the request span ends FIRST: the still-open phase span's end_ts
        # then lands at-or-after the request's, and the report's clipping
        # attributes the request window tail to it instead of leaving the
        # span-emission write latency unexplained
        req.span.end()
        if req.queue_span is not None:  # evicted before admission
            req.queue_span.end()
            req.queue_span = None
        if req.decode_span is not None:
            req.decode_span.set_attr("completion_tokens", len(req.out_ids))
            req.decode_span.end()
            req.decode_span = None
        self.flight.note("finish", reason=reason,
                         completion_tokens=len(req.out_ids))
        self._release_adapter_pin(req)
        req.future.set_result({
            "ids": list(req.out_ids), "finish_reason": reason,
            "prompt_tokens": len(req.ids),
            "completion_tokens": len(req.out_ids)})
        if req.stream_q is not None:
            # after set_result: the stream consumer reading the finish
            # frame can immediately collect the resolved usage totals
            req.stream_q.put(("finish", reason))

    def _fail_all(self, err: Exception) -> None:
        self._drain_queue()   # a submit racing stop() must fail too
        for req in list(self._inflight.values()):
            self._retire(req)
            if not req.future.done():
                self._end_spans_on_error(req)
                self._release_adapter_pin(req)
                self._stream_error(req, err)
                req.future.set_exception(err)
        for req in list(self._pending):
            if not req.future.done():
                self._end_spans_on_error(req)
                self._release_adapter_pin(req)
                self._stream_error(req, err)
                req.future.set_exception(err)
        self._pending.clear()

    @staticmethod
    def _end_spans_on_error(req: _Request) -> None:
        for sp in (req.queue_span, req.decode_span):
            if sp is not None:
                sp.set_attr("error", "engine_failure").end()
        req.queue_span = req.decode_span = None
        req.span.set_attr("error", "engine_failure").end()

    # ------------------------------------------------------------ metrics --
    def _note_tokens(self, n: int) -> None:
        now = time.time()
        self._tokens.append((now, n))
        cutoff = now - self.rate_window_s
        while self._tokens and self._tokens[0][0] < cutoff:
            self._tokens.popleft()

    def tokens_per_s(self) -> float:
        now = time.time()
        total = sum(n for ts, n in self._tokens
                    if ts >= now - self.rate_window_s)
        return total / self.rate_window_s

    def _note_kv_pool(self) -> None:
        st = self.scheduler.kv_pool_stats()
        obs_metrics.record_llm_kv_pool(
            st["used_blocks"], st["free_blocks"],
            st["headroom_requests"], st["fragmentation"],
            aliased_blocks=st.get("aliased_blocks"),
            cached_blocks=st.get("cached_blocks"))

    def _observe_step(self, tokens_out: int, wall_s: float) -> None:
        self.last_progress_ts = time.time()
        obs_metrics.record_llm_serving_step(
            tokens_out=tokens_out,
            occupancy=self.scheduler.active_count(),
            queue_depth=self.queue_depth(),
            tokens_per_s=self.tokens_per_s())
        # one ITL observation per STEP: every in-flight request
        # experienced this inter-token gap (per-step, not per-slot, so
        # the hot loop stays one bisect regardless of occupancy)
        obs_metrics.record_llm_itl(wall_s)
        self._itl_window.observe(wall_s)
        self.flight.note("step", tokens=tokens_out,
                         occupancy=self.scheduler.active_count(),
                         queue_depth=self.queue_depth(),
                         wall_s=round(wall_s, 6),
                         finite=bool(self.scheduler.last_step_finite))
        self._advance_steps_span(tokens_out)

    # shared decode-step block spans: the engine's side of the request
    # trace — each block span LINKS the request spans it advanced, the
    # same fan-in idiom async pours use for their contributing uploads
    def _advance_steps_span(self, tokens_out: int) -> None:
        if self._steps_span is None:
            self._steps_span = obs_trace.tracer.start_span(
                "serving.decode_steps", root=True)
            self._steps_in_span = 0
            self._span_tokens = 0
            for req in self._inflight.values():
                self._steps_span.add_link(req.span, slot=req.slot)
        else:
            # requests admitted since the block opened fan in too
            linked = {ln["span_id"]
                      for ln in getattr(self._steps_span, "links", ())}
            for req in self._inflight.values():
                ctx = req.span.context
                if ctx is not None and ctx.span_id not in linked:
                    self._steps_span.add_link(req.span, slot=req.slot)
        self._steps_in_span += 1
        self._span_tokens += tokens_out
        if self._steps_in_span >= DECODE_SPAN_STEPS:
            self._close_steps_span()

    def _close_steps_span(self) -> None:
        if self._steps_span is None:
            return
        self._steps_span.set_attr("steps", self._steps_in_span)
        self._steps_span.set_attr("tokens", self._span_tokens)
        self._steps_span.end()
        self._steps_span = None

    # ------------------------------------------------------------- health --
    def _watchdog_probe(self) -> Dict[str, Any]:
        return {"occupancy": self.scheduler.active_count(),
                "queue_depth": self.queue_depth(),
                "last_progress_ts": self.last_progress_ts,
                "poisoned": not self.scheduler.last_step_finite}

    def health(self) -> Dict[str, Any]:
        """Liveness summary for ``/healthz``: ``status`` is ``ok`` until
        the watchdog has tripped without progress since."""
        now = time.time()
        age = now - self.last_progress_ts
        status = "ok"
        if not self._running:
            status = "stopped"
        elif self._failed is not None:
            status = "failed"
        elif not self.scheduler.last_step_finite:
            status = "nan_logits"
        elif (self.watchdog.stall_s > 0
              and self.scheduler.active_count() > 0
              and age > self.watchdog.stall_s):
            status = "stalled"
        out = {"status": status,
               "occupancy": self.scheduler.active_count(),
               "queue_depth": self.queue_depth(),
               "last_step_age_s": round(age, 3),
               "steps_run": int(self.scheduler.steps_run),
               "tokens_per_s": round(self.tokens_per_s(), 2),
               "watchdog_trips": int(self.watchdog.trips),
               "resets": int(self.resets_total),
               "reset_budget_remaining": max(
                   self.max_resets - len(self._reset_times), 0),
               "flight_records": len(self.flight)}
        # the fleet-control payload: exact trailing percentiles + KV
        # admission headroom in one cheap scrape — what the SLOPolicy
        # autoscaler and the cache-aware gateway's spill check consume
        _, _, _, ttft_p99, ttft_n = self._ttft_window.stats()
        _, _, _, itl_p99, itl_n = self._itl_window.stats()
        try:
            headroom = int(
                self.scheduler.kv_pool_stats()["headroom_requests"])
        except Exception:  # noqa: BLE001 — health must answer, not raise
            headroom = -1
        out["slo"] = {"ttft_p99_s": round(ttft_p99, 6),
                      "ttft_n": int(ttft_n),
                      "itl_p99_s": round(itl_p99, 6),
                      "itl_n": int(itl_n),
                      "kv_headroom_requests": headroom}
        if self._failed is not None:
            out["failed_reason"] = self._failed
        return out

    def debug_state(self) -> Dict[str, Any]:
        """``/debug/state`` payload: the scheduler's slot matrix +
        block-table summary and a snapshot of the waiting queue."""
        # the engine thread mutates _pending concurrently; copying a
        # deque mid-mutation raises RuntimeError in CPython, and exactly
        # a busy queue is when the operator wants this endpoint
        for _ in range(8):
            try:
                head = list(self._pending)[:32]
                break
            except RuntimeError:
                continue
        else:
            head = []
        pending = [{"prompt_tokens": len(r.ids), "max_new": r.max_new,
                    "adapter_idx": r.adapter_idx,
                    "waiting_s": round(time.time() - r.submitted_ts, 3)}
                   for r in head]
        return {"engine": self.health(),
                "scheduler": self.scheduler.debug_state(),
                "queue": {"depth": self.queue_depth(),
                          "pending_head": pending}}

    # ------------------------------------------------------------- control --
    def stop(self) -> None:
        self._running = False
        self.watchdog.stop()
        self._thread.join(timeout=5.0)
        # serving has no round boundary: without this final snapshot a
        # short session's TTFT/ITL histograms never reach the run log
        # (the wall-clock flusher only covers sessions longer than its
        # cadence)
        obs_metrics.flush_final()
