"""BatchingEngine — threaded request queue over the DecodeScheduler.

The serving surface (HTTP handler threads) submits requests and blocks on
per-request futures; ONE worker thread owns the scheduler and runs the
admit → step → evict loop. Iteration-level scheduling: a finishing
request frees its slot at the very next step boundary and a queued
request is admitted into it — no batch barriers, no head-of-line
blocking behind long generations (Orca's core idea).

Deadlines: a request past its deadline is EVICTED at the next step
boundary and resolves with what it has, ``finish_reason: "length"`` —
tail-latency control the autoscaler's p99 policies can rely on.

Instrumented through the PR 8 planes: ``llm_tokens_per_s`` gauge,
queue-depth and slot-occupancy histograms, admit/evict counters, one
span per request (admit/evict recorded as span events).
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Deque, Dict, List, Optional

from ...core.obs import metrics as obs_metrics
from ...core.obs import trace as obs_trace
from ...llm.data import EOS

logger = logging.getLogger(__name__)


class _Request:
    __slots__ = ("ids", "max_new", "temperature", "seed", "adapter_idx",
                 "deadline_ts", "future", "span", "out_ids", "slot",
                 "submitted_ts")

    def __init__(self, ids, max_new, temperature, seed, adapter_idx,
                 deadline_ts, span):
        self.ids = ids
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.adapter_idx = int(adapter_idx)
        self.deadline_ts = deadline_ts
        self.future: Future = Future()
        self.span = span
        self.out_ids: List[int] = []
        self.slot: Optional[int] = None
        self.submitted_ts = time.time()


class BatchingEngine:
    """Continuous-batching front over one :class:`DecodeScheduler`."""

    def __init__(self, scheduler, default_deadline_s: float = 0.0,
                 rate_window_s: float = 2.0):
        self.scheduler = scheduler
        self.default_deadline_s = float(default_deadline_s)
        self.rate_window_s = float(rate_window_s)
        self._q: "queue.Queue[_Request]" = queue.Queue()
        self._pending: Deque[_Request] = collections.deque()
        self._inflight: Dict[int, _Request] = {}
        self._tokens: Deque = collections.deque()   # (ts, n) for tokens/s
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-batch-engine")
        self._thread.start()

    # ------------------------------------------------------------- submit --
    def submit(self, prompt_ids, max_new_tokens: int = 64,
               temperature: float = 0.0, seed: int = 0,
               adapter_idx: int = 0,
               deadline_s: Optional[float] = None) -> Future:
        """Enqueue one request; the future resolves to ``{"ids",
        "finish_reason", "prompt_tokens", "completion_tokens"}``."""
        if not self._running:
            raise RuntimeError("engine stopped")
        span = obs_trace.tracer.start_span(
            "serving.request", root=True,
            attrs={"prompt_tokens": len(prompt_ids),
                   "adapter_idx": int(adapter_idx)})
        dl = self.default_deadline_s if deadline_s is None \
            else float(deadline_s)
        req = _Request(list(map(int, prompt_ids)), max_new_tokens,
                       temperature, seed, adapter_idx,
                       time.time() + dl if dl > 0 else None, span)
        if req.max_new <= 0 or not req.ids:
            self._finish(req, "length")
            return req.future
        if len(req.ids) >= self.scheduler.cfg.max_seq_len:
            err = ValueError(
                f"prompt of {len(req.ids)} tokens >= max_seq_len "
                f"{self.scheduler.cfg.max_seq_len}")
            req.span.set_attr("error", "prompt_too_long").end()
            req.future.set_exception(err)
            return req.future
        ccfg = self.scheduler.cache_cfg
        need = ccfg.blocks_needed(min(len(req.ids) + req.max_new,
                                      ccfg.max_seq_len))
        if need > ccfg.num_blocks:
            # can_admit() would be False forever: failing it now beats
            # wedging the queue head until the caller's timeout
            err = ValueError(
                f"request needs {need} KV blocks, pool has only "
                f"{ccfg.num_blocks} (raise num_blocks or shrink the "
                "request)")
            req.span.set_attr("error", "kv_pool_too_small").end()
            req.future.set_exception(err)
            return req.future
        self._q.put(req)
        return req.future

    def queue_depth(self) -> int:
        return self._q.qsize() + len(self._pending)

    # --------------------------------------------------------------- loop --
    def _loop(self) -> None:
        while self._running:
            try:
                self._drain_queue()
                self._admit()
                self._evict_deadlines()
                if not self._inflight:
                    if not self._pending:
                        try:
                            self._pending.append(self._q.get(timeout=0.05))
                        except queue.Empty:
                            pass
                    else:
                        # pending but unadmittable (pool too small for the
                        # request) with nothing in flight: don't busy-spin
                        time.sleep(0.005)
                    continue
                t0 = time.perf_counter()
                toks = self.scheduler.step()
                self._observe_step(len(toks), time.perf_counter() - t0)
                self._collect(toks)
            except Exception:  # noqa: BLE001 — serving loop must survive
                logger.exception("batch engine step failed")
                self._fail_all(RuntimeError("batch engine step failed"))
        # drain on shutdown
        self._fail_all(RuntimeError("engine stopped"))

    def _drain_queue(self) -> None:
        while True:
            try:
                self._pending.append(self._q.get_nowait())
            except queue.Empty:
                return

    def _admit(self) -> None:
        while self._pending:
            req = self._pending[0]
            if req.deadline_ts is not None and time.time() > req.deadline_ts:
                self._pending.popleft()
                obs_metrics.record_llm_evict("deadline_queued")
                req.span.add_event("evict", reason="deadline_queued")
                self._finish(req, "length")
                continue
            if not self.scheduler.can_admit(len(req.ids), req.max_new):
                return
            self._pending.popleft()
            try:
                slot, first = self.scheduler.admit(
                    req.ids, adapter_idx=req.adapter_idx,
                    temperature=req.temperature, seed=req.seed,
                    max_new_tokens=req.max_new)
            except Exception as e:  # noqa: BLE001
                req.span.set_attr("error", type(e).__name__).end()
                req.future.set_exception(e)
                continue
            req.slot = slot
            req.span.add_event("admit", slot=slot)
            obs_metrics.record_llm_admit()
            self._inflight[slot] = req
            self._note_tokens(1)
            if not self._append_token(req, first):
                self._retire(req)

    def _append_token(self, req: _Request, token: int) -> bool:
        """Append one generated token; False when the request finished."""
        if token == EOS:
            self._finish(req, "stop")
            return False
        req.out_ids.append(int(token))
        if (len(req.out_ids) >= req.max_new
                or (req.slot is not None
                    and self.scheduler.slot_position(req.slot) + 1
                    >= self.scheduler.cfg.max_seq_len)):
            self._finish(req, "length")
            return False
        return True

    def _collect(self, toks: Dict[int, int]) -> None:
        self._note_tokens(len(toks))
        for slot, token in toks.items():
            req = self._inflight.get(slot)
            if req is None:
                continue
            if not self._append_token(req, token):
                self._retire(req)

    def _evict_deadlines(self) -> None:
        now = time.time()
        for slot, req in list(self._inflight.items()):
            if req.deadline_ts is not None and now > req.deadline_ts:
                obs_metrics.record_llm_evict("deadline")
                req.span.add_event("evict", reason="deadline", slot=slot)
                self._finish(req, "length")
                self._retire(req)

    def _retire(self, req: _Request) -> None:
        if req.slot is not None:
            self._inflight.pop(req.slot, None)
            self.scheduler.release(req.slot)
            req.slot = None

    def _finish(self, req: _Request, reason: str) -> None:
        if req.future.done():
            return
        req.span.set_attr("completion_tokens", len(req.out_ids))
        req.span.set_attr("finish_reason", reason)
        req.span.end()
        req.future.set_result({
            "ids": list(req.out_ids), "finish_reason": reason,
            "prompt_tokens": len(req.ids),
            "completion_tokens": len(req.out_ids)})

    def _fail_all(self, err: Exception) -> None:
        self._drain_queue()   # a submit racing stop() must fail too
        for req in list(self._inflight.values()):
            self._retire(req)
            if not req.future.done():
                req.span.set_attr("error", "engine_failure").end()
                req.future.set_exception(err)
        for req in list(self._pending):
            if not req.future.done():
                req.span.set_attr("error", "engine_failure").end()
                req.future.set_exception(err)
        self._pending.clear()

    # ------------------------------------------------------------ metrics --
    def _note_tokens(self, n: int) -> None:
        now = time.time()
        self._tokens.append((now, n))
        cutoff = now - self.rate_window_s
        while self._tokens and self._tokens[0][0] < cutoff:
            self._tokens.popleft()

    def tokens_per_s(self) -> float:
        now = time.time()
        total = sum(n for ts, n in self._tokens
                    if ts >= now - self.rate_window_s)
        return total / self.rate_window_s

    def _observe_step(self, tokens_out: int, wall_s: float) -> None:
        obs_metrics.record_llm_serving_step(
            tokens_out=tokens_out,
            occupancy=self.scheduler.active_count(),
            queue_depth=self.queue_depth(),
            tokens_per_s=self.tokens_per_s())

    # ------------------------------------------------------------- control --
    def stop(self) -> None:
        self._running = False
        self._thread.join(timeout=5.0)
