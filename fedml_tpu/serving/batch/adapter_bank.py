"""Multi-LoRA adapter bank: named per-user/per-silo adapters resident as
ONE stacked pytree the jitted decode step gathers from.

The federated-personalization loop this closes: ``llm/federated.py``
produces per-silo LoRA adapter artifacts (kilobytes each); the bank loads
them side by side over one frozen base model, and every request selects
its adapter by name — the selection becomes a per-slot integer index, the
gather happens inside the compiled step, and serving a new silo's users
costs one bank row, not a model replica (S-LoRA's economics).

The stack is CAPACITY-padded: leaves are ``[capacity, ...]`` from
construction, so registering adapter #2 through #capacity never changes
the compiled step's input shapes (compile-once holds across bank growth).
Index 0 is always the zero adapter — requests with no adapter get the
base model exactly.

Hot-swap (the federated adapter flywheel): :meth:`AdapterBank.swap`
publishes a NEW version of a named adapter by writing a FRESH row and
repointing the name — never by overwriting the live row — so requests
already in flight (which resolved the name to a row index at submit and
pinned it via :meth:`retain_row`) keep the exact version they started
with; the retired row returns to the free pool when its last pin drops.
:meth:`watch_dir` polls a ``save_adapter_artifacts`` export directory
and swaps in changed/new adapters live: a fresh federated round's export
goes live as a row write — zero restart, zero recompile (the capacity
padding keeps the stacked pytree's shapes constant; only a host→device
refresh of the stack happens).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

PyTree = Any
logger = logging.getLogger(__name__)

BASE_ADAPTER = "base"


class AdapterBank:
    """Named LoRA adapters over one base model.

    ``template``: any adapter tree with the served model's LoRA structure
    (``lora_init`` output or a loaded artifact) — defines the leaf shapes;
    its values are NOT registered. ``capacity``: maximum adapters
    (including the reserved zero adapter at index 0)."""

    def __init__(self, template: PyTree, alpha: float = 16.0,
                 capacity: int = 64):
        import jax.numpy as jnp

        leaves, self._treedef = jax.tree_util.tree_flatten(template)
        if not leaves:
            raise ValueError("adapter template has no leaves")
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.alpha = float(alpha)
        # rank from any lora_a leaf: [d_in, r]
        self.rank = int(leaves[0].shape[-1] if leaves[0].ndim == 2 else 0)
        self._lock = threading.Lock()
        self._names: Dict[str, int] = {BASE_ADAPTER: 0}
        # host mirror [capacity, ...] per leaf; row 0 stays zero
        self._host: List[np.ndarray] = [
            np.zeros((self.capacity,) + tuple(l.shape), np.float32)
            for l in leaves]
        self._stack = None   # lazily device-put pytree
        self._jnp = jnp
        # hot-swap bookkeeping: per-row in-flight pins, rows whose name
        # moved on (reusable once unpinned), and the watcher thread
        self._row_refs: Dict[int, int] = {}
        self._retired: set = set()
        self.swaps = 0
        self._watch_thread: Optional[threading.Thread] = None
        self._watch_stop = threading.Event()

    @property
    def scale(self) -> float:
        """The merged path's ``alpha / rank`` factor."""
        r = max(self.rank, 1)
        return self.alpha / r

    def __len__(self) -> int:
        with self._lock:
            return len(self._names)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._names, key=self._names.get)

    def _check_leaves(self, name: str, adapter: PyTree) -> List[np.ndarray]:
        leaves = jax.tree_util.tree_leaves(adapter)
        if len(leaves) != len(self._host):
            raise ValueError(
                f"adapter {name!r}: {len(leaves)} leaves != template's "
                f"{len(self._host)}")
        arrs = []
        for host, leaf in zip(self._host, leaves):
            arr = np.asarray(leaf, np.float32)
            if arr.shape != host.shape[1:]:
                raise ValueError(
                    f"adapter {name!r}: leaf shape {arr.shape} != "
                    f"template {host.shape[1:]} (same targets and "
                    "rank required)")
            arrs.append(arr)
        return arrs

    def _next_row_locked(self) -> int:
        """Smallest unused, unretired row (row 0 reserved). Retired rows
        rejoin the pool only when their last in-flight pin drops."""
        in_use = set(self._names.values()) | self._retired
        for r in range(1, self.capacity):
            if r not in in_use:
                return r
        raise RuntimeError(
            f"adapter bank full ({self.capacity}); raise "
            "serving_max_adapters")

    def add(self, name: str, adapter: PyTree) -> int:
        """Register (or replace IN PLACE) a named adapter; returns its
        index. In-place replacement mutates the live row — use
        :meth:`swap` when requests may be in flight on the old
        version."""
        arrs = self._check_leaves(name, adapter)
        with self._lock:
            if name == BASE_ADAPTER:
                raise ValueError(f"{BASE_ADAPTER!r} is the reserved zero "
                                 "adapter")
            idx = self._names.get(name)
            if idx is None:
                idx = self._next_row_locked()
                self._names[name] = idx
            for host, arr in zip(self._host, arrs):
                host[idx] = arr
            self._stack = None
        return idx

    def swap(self, name: str, adapter: PyTree) -> int:
        """Hot-swap: publish a new version of ``name`` on a FRESH row
        and repoint the name — in-flight requests pinned to the old row
        keep the version they started with; the old row is retired and
        reused only once its last pin drops. A previously unknown name
        is simply added. Returns the (new) index."""
        arrs = self._check_leaves(name, adapter)
        with self._lock:
            if name == BASE_ADAPTER:
                raise ValueError(f"{BASE_ADAPTER!r} is the reserved zero "
                                 "adapter")
            old = self._names.get(name)
            idx = self._next_row_locked()
            for host, arr in zip(self._host, arrs):
                host[idx] = arr
            self._names[name] = idx
            if old is not None and old != 0:
                if self._row_refs.get(old, 0) > 0:
                    self._retired.add(old)
                # unpinned old row: implicitly free (not named, not
                # retired) — _next_row_locked can hand it out again
            self.swaps += 1
            self._stack = None
        from ...core.obs import metrics as obs_metrics
        obs_metrics.record_llm_adapter_swap(name)
        logger.info("adapter bank: hot-swapped %r -> row %d (old row "
                    "%s)", name, idx, old)
        return idx

    def acquire(self, name: str) -> int:
        """Resolve a name to its row AND pin it, under ONE lock hold —
        a separate ``index()`` + ``retain_row()`` pair leaves a window
        where a concurrent swap retires-and-reuses the resolved row and
        the request decodes someone else's weights. Pair with
        :meth:`release_row`. Unknown names raise like :meth:`index`."""
        with self._lock:
            idx = self._names.get(str(name))
            if idx is None:
                loaded = sorted(self._names, key=self._names.get)
                raise KeyError(f"unknown adapter {name!r}; loaded: "
                               f"{loaded}")
            if idx > 0:
                self._row_refs[idx] = self._row_refs.get(idx, 0) + 1
            return idx

    def retain_row(self, idx: int) -> None:
        """Pin a row for an in-flight request (the engine calls this at
        submit): a pinned retired row is never reused. For pinning BY
        NAME use :meth:`acquire` — it closes the resolve-then-pin race
        against a concurrent hot-swap."""
        i = int(idx)
        if i <= 0:
            return   # the zero adapter is immutable
        with self._lock:
            self._row_refs[i] = self._row_refs.get(i, 0) + 1

    def release_row(self, idx: int) -> None:
        i = int(idx)
        if i <= 0:
            return
        with self._lock:
            n = self._row_refs.get(i, 0)
            if n <= 1:
                self._row_refs.pop(i, None)
                self._retired.discard(i)   # now reusable
            else:
                self._row_refs[i] = n - 1

    # --- watched hot-swap ---------------------------------------------------
    def watch_dir(self, manifest_dir: str, poll_s: float = 2.0,
                  swap_existing: bool = False) -> None:
        """Poll a ``save_adapter_artifacts`` dir and hot-swap changed or
        new adapters live. The initial scan only RECORDS mtimes (the
        bank was typically just loaded from this dir) unless
        ``swap_existing``; every subsequent change to the manifest or an
        artifact file triggers :meth:`swap` for the affected names.
        Half-written exports are tolerated (the exporter writes
        atomically via os.replace; a transient read error just waits for
        the next poll)."""
        if self._watch_thread is not None and self._watch_thread.is_alive():
            raise RuntimeError("already watching an adapter dir")
        self._watch_stop.clear()
        seen: Dict[str, float] = {} if swap_existing \
            else self._scan_mtimes(manifest_dir)

        def loop() -> None:
            while not self._watch_stop.wait(float(poll_s)):
                try:
                    self._poll_once(manifest_dir, seen)
                except Exception:  # noqa: BLE001 — watcher must survive
                    logger.exception("adapter watch poll failed (will "
                                     "retry)")

        self._watch_thread = threading.Thread(
            target=loop, daemon=True, name="llm-adapter-watch")
        self._watch_thread.start()
        logger.info("adapter bank: watching %s every %.1fs",
                    manifest_dir, float(poll_s))

    @staticmethod
    def _scan_mtimes(manifest_dir: str) -> Dict[str, float]:
        out: Dict[str, float] = {}
        try:
            import json
            with open(os.path.join(manifest_dir, "manifest.json")) as f:
                manifest = json.load(f)
            for name, fname in (manifest.get("adapters") or {}).items():
                try:
                    out[str(name)] = os.path.getmtime(
                        os.path.join(manifest_dir, fname))
                except OSError:
                    pass
        except Exception:  # noqa: BLE001 — nothing exported yet
            pass
        return out

    def _poll_once(self, manifest_dir: str, seen: Dict[str, float]) -> None:
        import json
        with open(os.path.join(manifest_dir, "manifest.json")) as f:
            manifest = json.load(f)
        changed = []
        for name, fname in (manifest.get("adapters") or {}).items():
            try:
                mtime = os.path.getmtime(os.path.join(manifest_dir, fname))
            except OSError:
                continue   # export in progress
            if seen.get(str(name)) != mtime:
                changed.append((str(name), fname, mtime))
        if not changed:
            return
        from ...serving import load_model
        for name, fname, mtime in changed:
            tree = load_model(os.path.join(manifest_dir, fname))
            self.swap(name, tree)
            seen[name] = mtime

    def stop_watch(self) -> None:
        self._watch_stop.set()
        th = self._watch_thread
        if th is not None:
            th.join(timeout=5.0)
            self._watch_thread = None

    def index(self, name: Optional[str]) -> int:
        """Name → bank index; ``None`` → the zero adapter. Unknown names
        raise — serving a user the WRONG personalization silently is the
        one failure mode a personalization gateway must not have."""
        if name is None:
            return 0
        with self._lock:
            idx = self._names.get(str(name))
        if idx is None:
            raise KeyError(f"unknown adapter {name!r}; loaded: "
                           f"{self.names()}")
        return idx

    def has(self, name: str) -> bool:
        with self._lock:
            return str(name) in self._names

    def stack(self) -> PyTree:
        """The resident ``[capacity, ...]`` device pytree (rebuilt lazily
        after adds; the capacity padding keeps its shapes constant)."""
        with self._lock:
            if self._stack is None:
                self._stack = jax.tree_util.tree_unflatten(
                    self._treedef,
                    [self._jnp.asarray(h) for h in self._host])
            return self._stack

    @classmethod
    def from_artifacts(cls, manifest_dir: str, alpha: float = 16.0,
                       capacity: int = 64) -> "AdapterBank":
        """Build a bank from a ``save_adapter_artifacts`` directory
        (manifest.json + one msgpack artifact per named adapter — the
        layout ``llm/federated.py`` exports per silo)."""
        from ...llm.federated import load_adapter_artifacts
        adapters = load_adapter_artifacts(manifest_dir)
        if not adapters:
            raise ValueError(f"no adapters in {manifest_dir}")
        template = next(iter(adapters.values()))
        # +2: the reserved zero row AND the served artifact's own adapter,
        # which CausalLMPredictor registers as "default" after loading —
        # a manifest that exactly fills `capacity` must not crash there
        bank = cls(template, alpha=alpha,
                   capacity=max(capacity, len(adapters) + 2))
        for name, tree in adapters.items():
            bank.add(name, tree)
        logger.info("adapter bank: loaded %d adapters from %s",
                    len(adapters), manifest_dir)
        return bank
