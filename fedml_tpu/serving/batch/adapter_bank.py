"""Multi-LoRA adapter bank: named per-user/per-silo adapters resident as
ONE stacked pytree the jitted decode step gathers from.

The federated-personalization loop this closes: ``llm/federated.py``
produces per-silo LoRA adapter artifacts (kilobytes each); the bank loads
them side by side over one frozen base model, and every request selects
its adapter by name — the selection becomes a per-slot integer index, the
gather happens inside the compiled step, and serving a new silo's users
costs one bank row, not a model replica (S-LoRA's economics).

The stack is CAPACITY-padded: leaves are ``[capacity, ...]`` from
construction, so registering adapter #2 through #capacity never changes
the compiled step's input shapes (compile-once holds across bank growth).
Index 0 is always the zero adapter — requests with no adapter get the
base model exactly.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

PyTree = Any
logger = logging.getLogger(__name__)

BASE_ADAPTER = "base"


class AdapterBank:
    """Named LoRA adapters over one base model.

    ``template``: any adapter tree with the served model's LoRA structure
    (``lora_init`` output or a loaded artifact) — defines the leaf shapes;
    its values are NOT registered. ``capacity``: maximum adapters
    (including the reserved zero adapter at index 0)."""

    def __init__(self, template: PyTree, alpha: float = 16.0,
                 capacity: int = 64):
        import jax.numpy as jnp

        leaves, self._treedef = jax.tree_util.tree_flatten(template)
        if not leaves:
            raise ValueError("adapter template has no leaves")
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.alpha = float(alpha)
        # rank from any lora_a leaf: [d_in, r]
        self.rank = int(leaves[0].shape[-1] if leaves[0].ndim == 2 else 0)
        self._lock = threading.Lock()
        self._names: Dict[str, int] = {BASE_ADAPTER: 0}
        # host mirror [capacity, ...] per leaf; row 0 stays zero
        self._host: List[np.ndarray] = [
            np.zeros((self.capacity,) + tuple(l.shape), np.float32)
            for l in leaves]
        self._stack = None   # lazily device-put pytree
        self._jnp = jnp

    @property
    def scale(self) -> float:
        """The merged path's ``alpha / rank`` factor."""
        r = max(self.rank, 1)
        return self.alpha / r

    def __len__(self) -> int:
        with self._lock:
            return len(self._names)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._names, key=self._names.get)

    def add(self, name: str, adapter: PyTree) -> int:
        """Register (or replace) a named adapter; returns its index."""
        leaves = jax.tree_util.tree_leaves(adapter)
        if len(leaves) != len(self._host):
            raise ValueError(
                f"adapter {name!r}: {len(leaves)} leaves != template's "
                f"{len(self._host)}")
        with self._lock:
            if name == BASE_ADAPTER:
                raise ValueError(f"{BASE_ADAPTER!r} is the reserved zero "
                                 "adapter")
            idx = self._names.get(name)
            if idx is None:
                idx = len(self._names)
                if idx >= self.capacity:
                    raise RuntimeError(
                        f"adapter bank full ({self.capacity}); raise "
                        "serving_max_adapters")
                self._names[name] = idx
            for host, leaf in zip(self._host, leaves):
                arr = np.asarray(leaf, np.float32)
                if arr.shape != host.shape[1:]:
                    raise ValueError(
                        f"adapter {name!r}: leaf shape {arr.shape} != "
                        f"template {host.shape[1:]} (same targets and "
                        "rank required)")
                host[idx] = arr
            self._stack = None
        return idx

    def index(self, name: Optional[str]) -> int:
        """Name → bank index; ``None`` → the zero adapter. Unknown names
        raise — serving a user the WRONG personalization silently is the
        one failure mode a personalization gateway must not have."""
        if name is None:
            return 0
        with self._lock:
            idx = self._names.get(str(name))
        if idx is None:
            raise KeyError(f"unknown adapter {name!r}; loaded: "
                           f"{self.names()}")
        return idx

    def has(self, name: str) -> bool:
        with self._lock:
            return str(name) in self._names

    def stack(self) -> PyTree:
        """The resident ``[capacity, ...]`` device pytree (rebuilt lazily
        after adds; the capacity padding keeps its shapes constant)."""
        with self._lock:
            if self._stack is None:
                self._stack = jax.tree_util.tree_unflatten(
                    self._treedef,
                    [self._jnp.asarray(h) for h in self._host])
            return self._stack

    @classmethod
    def from_artifacts(cls, manifest_dir: str, alpha: float = 16.0,
                       capacity: int = 64) -> "AdapterBank":
        """Build a bank from a ``save_adapter_artifacts`` directory
        (manifest.json + one msgpack artifact per named adapter — the
        layout ``llm/federated.py`` exports per silo)."""
        from ...llm.federated import load_adapter_artifacts
        adapters = load_adapter_artifacts(manifest_dir)
        if not adapters:
            raise ValueError(f"no adapters in {manifest_dir}")
        template = next(iter(adapters.values()))
        # +2: the reserved zero row AND the served artifact's own adapter,
        # which CausalLMPredictor registers as "default" after loading —
        # a manifest that exactly fills `capacity` must not crash there
        bank = cls(template, alpha=alpha,
                   capacity=max(capacity, len(adapters) + 2))
        for name, tree in adapters.items():
            bank.add(name, tree)
        logger.info("adapter bank: loaded %d adapters from %s",
                    len(adapters), manifest_dir)
        return bank
