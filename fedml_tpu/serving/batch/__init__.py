"""Continuous-batching LLM serving (`serving/batch/`).

Iteration-level scheduling (Orca, Yu et al. OSDI'22) + batched
multi-adapter serving over one resident base model (S-LoRA, Sheng et
al. 2023), TPU-first: ONE compiled decode step over a fixed-shape slot
matrix ``[S]`` where slot occupancy, positions, block tables, and
adapter indices are all DATA — admit/evict/adapter-mix never recompile.

* :class:`~.scheduler.DecodeScheduler` — the synchronous core: paged KV
  cache (``llm/kv_cache.py``), chunked prefill, per-step admit/evict.
* :class:`~.adapter_bank.AdapterBank` — named LoRA adapters stacked into
  a resident ``[A, ...]`` pytree; per-slot selection is a batched gather
  inside the jitted step.
* :class:`~.engine.BatchingEngine` — threaded request queue with
  per-request futures and deadline-based eviction, feeding the scheduler.
"""

from .adapter_bank import AdapterBank
from .engine import BatchingEngine
from .scheduler import DecodeScheduler

__all__ = ["AdapterBank", "BatchingEngine", "DecodeScheduler"]
