"""DecodeScheduler — the compile-once continuous-batching core.

One jitted DECODE step advances every in-flight request by one token:
``[S]`` slots, each step one-token work per slot against the paged KV
cache (vs the old template's full ``[1, max_seq_len]`` forward per
token). One jitted PREFILL program writes a prompt into the cache in
fixed-size chunks. Everything per-request — occupancy, positions, block
tables, adapter indices, temperatures, seeds — enters the programs as
DATA, so the two programs compile exactly once for a given geometry and
stay hot across any admit/evict sequence or adapter mix (the
compile-count regression test pins this).

Sampling is stateless per (seed, position): the token for position ``p``
uses ``fold_in(PRNGKey(seed), p)``, so a request's sample path is
reproducible regardless of which slot it lands in or what else is in
flight — batching must never change a seeded request's output.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...llm import kv_cache as kvc

PyTree = Any
logger = logging.getLogger(__name__)


class DecodeScheduler:
    """Fixed-shape slot matrix over a paged KV cache.

    ``module``/``cfg``: the :class:`~fedml_tpu.llm.model.CausalLM` and its
    config; ``base_params``: the full parameter tree the slots share;
    ``bank``: optional :class:`AdapterBank` (None = no LoRA side paths).
    """

    def __init__(self, module, cfg, base_params, bank=None, *,
                 slots: int = 8, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 prefill_chunk: int = 32):
        import jax
        import jax.numpy as jnp

        self._jax, self._jnp = jax, jnp
        self.module = module
        self.cfg = cfg
        self.params = base_params
        self.bank = bank
        self.slots = int(slots)
        self.prefill_chunk = min(int(prefill_chunk), cfg.max_seq_len)
        self.cache_cfg = kvc.KVCacheConfig(
            num_layers=cfg.num_layers, kv_heads=cfg.kv_heads,
            head_dim=cfg.head_dim, max_seq_len=cfg.max_seq_len,
            block_size=int(block_size),
            # default pool: every slot can hold a full sequence
            num_blocks=int(num_blocks) if num_blocks is not None
            else self.slots * (cfg.max_seq_len // int(block_size)))
        self.alloc = kvc.BlockAllocator(self.cache_cfg)
        self._kp, self._vp = kvc.init_pools(self.cache_cfg,
                                            cfg.compute_dtype)
        s, mb = self.slots, self.cache_cfg.max_blocks_per_slot
        # host mirrors of per-slot state — all DATA to the jitted step
        self._active = np.zeros(s, bool)
        self._tables = np.full((s, mb), self.cache_cfg.trash_block,
                               np.int32)
        self._pos = np.zeros(s, np.int32)       # position of last_tok
        self._last = np.zeros(s, np.int32)      # token awaiting its step
        self._temp = np.zeros(s, np.float32)
        self._seed = np.zeros(s, np.int32)
        self._aidx = np.zeros(s, np.int32)
        self.steps_run = 0
        self.resets = 0
        # True until a decode step observes NaN/inf in an active slot's
        # logits — the watchdog's poison signal
        self.last_step_finite = True
        self._build_programs()

    # ------------------------------------------------------------- reset --
    def reset(self) -> None:
        """Crash-only recovery (Candea & Fox): discard every piece of
        per-request state — block allocator, slot mirrors, paged KV
        pools — and come back empty, WITHOUT touching the compiled
        programs. Geometry is unchanged, so the rebuilt pools slot
        straight into the cached executables: a reset costs two pool
        allocations and zero recompiles. ``steps_run`` keeps counting
        (the chaos plan's step index is monotonic across resets);
        ``resets`` counts the episodes for /healthz."""
        self.alloc = kvc.BlockAllocator(self.cache_cfg)
        self._kp, self._vp = kvc.init_pools(self.cache_cfg,
                                            self.cfg.compute_dtype)
        self._active[:] = False
        self._tables[:] = self.cache_cfg.trash_block
        self._pos[:] = 0
        self._last[:] = 0
        self._temp[:] = 0.0
        self._seed[:] = 0
        self._aidx[:] = 0
        self.last_step_finite = True
        self.resets += 1

    # ------------------------------------------------------------ programs --
    def _build_programs(self) -> None:
        jax, jnp = self._jax, self._jnp
        cfg, ccfg = self.cfg, self.cache_cfg
        n_layers = cfg.num_layers
        bs, trash = ccfg.block_size, ccfg.trash_block
        sentinel = ccfg.max_blocks_per_slot * bs   # OOB position: drop
        scale = self.bank.scale if self.bank is not None else 1.0

        def sample(row, temp, seed, position):
            """The single-request step's formula, per slot: greedy at
            temp 0, else categorical on logits/temp with a per-(seed,
            position) key."""
            key = jax.random.fold_in(jax.random.PRNGKey(seed), position)
            greedy = jnp.argmax(row).astype(jnp.int32)
            sampled = jax.random.categorical(
                key, row / jnp.maximum(temp, 1e-6)).astype(jnp.int32)
            return jnp.where(temp > 0, sampled, greedy)

        def decode_step(params, stack, kp, vp, tables, pos, active, aidx,
                        last_tok, temps, seeds):
            views = [(kvc.gather_view(kp[i], tables),
                      kvc.gather_view(vp[i], tables))
                     for i in range(n_layers)]
            adapters = None
            if stack is not None:
                from ...llm.lora import lora_select
                adapters = lora_select(stack, aidx)
            q_pos = jnp.where(active, pos, sentinel)
            logits, kvs = self.module.apply(
                {"params": params}, last_tok[:, None],
                positions=q_pos[:, None], kv_view=views,
                adapters=adapters, lora_scale=scale)
            row = logits[:, 0]
            # black-box poison flag: one scalar riding the same transfer
            # as the tokens — the watchdog reads it for free (an inactive
            # slot's row may be garbage; only active rows count)
            finite = jnp.all(jnp.where(active[:, None],
                                       jnp.isfinite(row), True))
            nxt = jax.vmap(sample)(row, temps, seeds, pos + 1)
            for i, (kc, vc) in enumerate(kvs):
                kp = kp.at[i].set(kvc.scatter_token(
                    kp[i], tables, pos, kc[:, 0], active, bs, trash))
                vp = vp.at[i].set(kvc.scatter_token(
                    vp[i], tables, pos, vc[:, 0], active, bs, trash))
            return nxt, finite, kp, vp

        def prefill_chunk(params, stack, kp, vp, table_row, tokens, p0,
                          n_valid, aidx):
            c = tokens.shape[0]
            offs = jnp.arange(c, dtype=jnp.int32)
            positions = p0 + offs
            valid = offs < n_valid
            q_pos = jnp.where(valid, positions, sentinel)
            views = [(kvc.gather_view(kp[i], table_row[None]),
                      kvc.gather_view(vp[i], table_row[None]))
                     for i in range(n_layers)]
            adapters = None
            if stack is not None:
                from ...llm.lora import lora_select
                adapters = lora_select(stack, aidx)   # shared 2-D leaves
            logits, kvs = self.module.apply(
                {"params": params}, tokens[None], positions=q_pos[None],
                kv_view=views, adapters=adapters, lora_scale=scale)
            for i, (kc, vc) in enumerate(kvs):
                kp = kp.at[i].set(kvc.scatter_chunk(
                    kp[i], table_row, positions, kc[0], valid, bs, trash))
                vp = vp.at[i].set(kvc.scatter_chunk(
                    vp[i], table_row, positions, vc[0], valid, bs, trash))
            return logits[0], kp, vp

        self._step_fn = jax.jit(decode_step, donate_argnums=(2, 3))
        self._prefill_fn = jax.jit(prefill_chunk, donate_argnums=(2, 3))
        self._sample_fn = jax.jit(sample)

    def _stack(self):
        return self.bank.stack() if self.bank is not None else None

    # ---------------------------------------------------------- admission --
    def free_slots(self) -> List[int]:
        return [int(i) for i in np.flatnonzero(~self._active)]

    def can_admit(self, prompt_len: int, max_new_tokens: int) -> bool:
        if not (self._active < 1).any():
            return False
        total = min(int(prompt_len) + int(max_new_tokens),
                    self.cfg.max_seq_len)
        return self.alloc.can_alloc(total)

    def admit(self, prompt_ids, *, adapter_idx: int = 0,
              temperature: float = 0.0, seed: int = 0,
              max_new_tokens: int = 64) -> Tuple[int, int]:
        """Prefill one request into the lowest free slot; returns
        ``(slot, first_generated_token)``. Deterministic: the same admit
        sequence always lands in the same slots with the same cache
        layout."""
        jnp = self._jnp
        ids = list(map(int, prompt_ids))
        if not ids:
            raise ValueError("empty prompt")
        if len(ids) >= self.cfg.max_seq_len:
            raise ValueError(
                f"prompt of {len(ids)} tokens >= max_seq_len "
                f"{self.cfg.max_seq_len}")
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free slot")
        slot = free[0]
        total = min(len(ids) + int(max_new_tokens), self.cfg.max_seq_len)
        table_row = self.alloc.alloc(slot, total)
        c = self.prefill_chunk
        row_dev = jnp.asarray(table_row)
        stack = self._stack()
        logits_last = None
        for j in range(0, len(ids), c):
            chunk = ids[j:j + c]
            n_valid = len(chunk)
            chunk = chunk + [0] * (c - n_valid)
            logits_last, self._kp, self._vp = self._prefill_fn(
                self.params, stack, self._kp, self._vp, row_dev,
                jnp.asarray(chunk, jnp.int32), jnp.int32(j),
                jnp.int32(n_valid), jnp.int32(adapter_idx))
            last_valid = n_valid
        first = int(self._sample_fn(
            logits_last[last_valid - 1], jnp.float32(temperature),
            jnp.int32(int(seed) & 0x7FFFFFFF), jnp.int32(len(ids))))
        self._active[slot] = True
        self._tables[slot] = table_row
        self._pos[slot] = len(ids)
        self._last[slot] = first
        self._temp[slot] = float(temperature)
        self._seed[slot] = int(seed) & 0x7FFFFFFF
        self._aidx[slot] = int(adapter_idx)
        return slot, first

    def release(self, slot: int) -> None:
        self.alloc.free(int(slot))
        self._active[slot] = False
        self._tables[slot] = self.cache_cfg.trash_block

    # --------------------------------------------------------------- step --
    def active_count(self) -> int:
        return int(self._active.sum())

    def step(self) -> Dict[int, int]:
        """One decode step for every active slot → ``{slot: next_token}``.
        Each slot's ``last_tok`` is written into the cache at its position
        and the following token is sampled; positions advance by one."""
        jnp = self._jnp
        if not self._active.any():
            return {}
        nxt, finite, self._kp, self._vp = self._step_fn(
            self.params, self._stack(), self._kp, self._vp,
            jnp.asarray(self._tables), jnp.asarray(self._pos),
            jnp.asarray(self._active), jnp.asarray(self._aidx),
            jnp.asarray(self._last), jnp.asarray(self._temp),
            jnp.asarray(self._seed))
        toks = np.asarray(nxt)
        self.last_step_finite = bool(finite)
        self.steps_run += 1
        out: Dict[int, int] = {}
        for slot in np.flatnonzero(self._active):
            slot = int(slot)
            self._pos[slot] += 1
            self._last[slot] = toks[slot]
            out[slot] = int(toks[slot])
        return out

    def slot_position(self, slot: int) -> int:
        return int(self._pos[slot])

    # ------------------------------------------------------- observability --
    def kv_pool_stats(self) -> Dict[str, Any]:
        """Paged-pool state for the SLO gauges: used/free blocks, how
        many WORST-CASE (max_seq_len) requests the free list can still
        admit, and internal fragmentation — the reserved-but-unwritten
        fraction of allocated blocks (admission reserves prompt+max_new
        up front, so a short generation strands block tail capacity
        until release)."""
        ccfg = self.cache_cfg
        free = self.alloc.free_blocks
        used = ccfg.num_blocks - free
        per_req = ccfg.blocks_needed(ccfg.max_seq_len)
        written = int(self._pos[self._active].sum()) if used else 0
        capacity = used * ccfg.block_size
        frag = 1.0 - written / capacity if capacity else 0.0
        return {"used_blocks": used, "free_blocks": free,
                "headroom_requests": free // per_req,
                "fragmentation": round(max(frag, 0.0), 4)}

    def debug_state(self) -> Dict[str, Any]:
        """The slot matrix + block-table summary, host-side mirrors only
        (no device sync) — the ``/debug/state`` payload."""
        slots = []
        for s in range(self.slots):
            row = {"slot": s, "active": bool(self._active[s])}
            if self._active[s]:
                table = self._tables[s]
                row.update({
                    "position": int(self._pos[s]),
                    "adapter_idx": int(self._aidx[s]),
                    "temperature": float(self._temp[s]),
                    "blocks": int((table != self.cache_cfg.trash_block)
                                  .sum())})
            slots.append(row)
        return {"slots": slots, "steps_run": int(self.steps_run),
                "resets": int(self.resets),
                "last_step_finite": bool(self.last_step_finite),
                "kv_pool": self.kv_pool_stats(),
                "geometry": {
                    "num_slots": self.slots,
                    "block_size": self.cache_cfg.block_size,
                    "num_blocks": self.cache_cfg.num_blocks,
                    "max_seq_len": self.cfg.max_seq_len,
                    "prefill_chunk": self.prefill_chunk}}
