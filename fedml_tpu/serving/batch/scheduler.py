"""DecodeScheduler — the compile-once continuous-batching core.

One jitted DECODE step advances every in-flight request by one token:
``[S]`` slots, each step one-token work per slot against the paged KV
cache (vs the old template's full ``[1, max_seq_len]`` forward per
token). One jitted PREFILL program writes a prompt into the cache in
fixed-size chunks. Everything per-request — occupancy, positions, block
tables, adapter indices, temperatures, seeds — enters the programs as
DATA, so the programs compile exactly once for a given geometry and
stay hot across any admit/evict sequence or adapter mix (the
compile-count regression test pins this).

Sampling is stateless per (seed, position): the token for position ``p``
uses ``fold_in(PRNGKey(seed), p)``, so a request's sample path is
reproducible regardless of which slot it lands in or what else is in
flight — batching must never change a seeded request's output.

Shared-prefix cache (``prefix_cache=True``): admissions consult a
:class:`~fedml_tpu.llm.kv_cache.PrefixIndex` keyed on exact block token
content. Fully matched prompt blocks are ALIASED into the new slot's
table (refcounted — never copied, never written by the new slot); the
first partially matched block is copied once (copy-on-write) and only
the genuinely novel suffix is prefilled, so TTFT scales with the novel
tokens, not the whole prompt. Aliasing changes where KV lives, never its
values: greedy decode stays bit-identical to the cache-off path.

Piggybacked prefill (``prefill_batch > 1``): an admission wave's chunks
run through ONE ``[B, C]`` batched prefill program — K admits cost ~one
pass over the longest novel suffix instead of K serial passes. Chunk
metadata (tables, offsets, valid counts, adapter rows) is DATA, so the
wave program also compiles exactly once.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...core.obs import metrics as obs_metrics
from ...core.obs import roofline as obs_roofline
from ...llm import kv_cache as kvc

PyTree = Any
logger = logging.getLogger(__name__)


@dataclasses.dataclass
class _PendingAdmit:
    """Blocks + slot reserved, prefix matched, COW applied — everything
    host-side an admission needs before its (possibly batched) prefill
    runs. Produced by :meth:`DecodeScheduler.begin_admit`, consumed by
    :meth:`DecodeScheduler.finish_admits`."""

    slot: int
    row: np.ndarray          # the slot's block-table row
    ids: List[int]
    novel_start: int         # first position actually prefilled
    aidx: int
    temp: float
    seed: int
    info: Dict[str, Any]     # cached/novel token counts for observability


class DecodeScheduler:
    """Fixed-shape slot matrix over a paged KV cache.

    ``module``/``cfg``: the :class:`~fedml_tpu.llm.model.CausalLM` and its
    config; ``base_params``: the full parameter tree the slots share;
    ``bank``: optional :class:`AdapterBank` (None = no LoRA side paths).
    """

    def __init__(self, module, cfg, base_params, bank=None, *,
                 slots: int = 8, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 prefill_chunk: int = 32,
                 prefix_cache: bool = False,
                 prefill_batch: int = 0,
                 suffix_cache: bool = False):
        import jax
        import jax.numpy as jnp

        self._jax, self._jnp = jax, jnp
        self.module = module
        self.cfg = cfg
        self.params = base_params
        self.bank = bank
        self.slots = int(slots)
        self.prefill_chunk = min(int(prefill_chunk), cfg.max_seq_len)
        # piggybacked-prefill wave width (0/1 = off, the serial path);
        # clamped to the slot count — a wave can never admit more
        self.prefill_batch = min(max(int(prefill_batch or 0), 0),
                                 self.slots)
        self.cache_cfg = kvc.KVCacheConfig(
            num_layers=cfg.num_layers, kv_heads=cfg.kv_heads,
            head_dim=cfg.head_dim, max_seq_len=cfg.max_seq_len,
            block_size=int(block_size),
            # default pool: every slot can hold a full sequence
            num_blocks=int(num_blocks) if num_blocks is not None
            else self.slots * (cfg.max_seq_len // int(block_size)))
        self.alloc = kvc.BlockAllocator(self.cache_cfg)
        # suffix caching (generated-token reuse at release) needs the
        # same exact-content index to match follow-up prompts against,
        # so turning it on implies the prefix index
        self.suffix_cache = bool(suffix_cache)
        self._index = (kvc.PrefixIndex(self.cache_cfg.block_size)
                       if (prefix_cache or self.suffix_cache) else None)
        self._kp, self._vp = kvc.init_pools(self.cache_cfg,
                                            cfg.compute_dtype)
        s, mb = self.slots, self.cache_cfg.max_blocks_per_slot
        # host mirrors of per-slot state — all DATA to the jitted step
        self._active = np.zeros(s, bool)
        self._tables = np.full((s, mb), self.cache_cfg.trash_block,
                               np.int32)
        self._pos = np.zeros(s, np.int32)       # position of last_tok
        self._last = np.zeros(s, np.int32)      # token awaiting its step
        self._temp = np.zeros(s, np.float32)
        self._seed = np.zeros(s, np.int32)
        self._aidx = np.zeros(s, np.int32)
        self._reserved: set = set()   # slots between begin_ and finish_
        self.last_admit_info: Optional[Dict[str, Any]] = None
        self.steps_run = 0
        self.resets = 0
        # True until a decode step observes NaN/inf in an active slot's
        # logits — the watchdog's poison signal
        self.last_step_finite = True
        # compute plane at the serving dispatch seam: always-on recompile
        # forensics for the decode/prefill programs (steady-state zero
        # recompiles is the engine's core invariant) + opt-in roofline
        # capture (obs_roofline via core/obs configure — the scheduler
        # has no args object, so the module default is the knob)
        from ...core import mlops
        mlops.install_compile_counter()
        self._roofline = obs_roofline.DispatchTracker(
            n_devices=max(len(self._jax.devices()), 1))
        self._build_programs()

    def _dispatch(self, name: str, fn, *args):
        """Run one jitted serving program through the compute-plane seam:
        signature before the call (kp/vp are donated), forensics after."""
        from ...core import mlops
        sig = obs_roofline.dispatch_signature(args)
        self._roofline.maybe_capture(name, fn, args, sig=sig)
        c0 = mlops.compile_count()
        out = fn(*args)
        self._roofline.observe(name, sig, mlops.compile_count() - c0)
        return out

    # ------------------------------------------------------------- reset --
    def reset(self) -> None:
        """Crash-only recovery (Candea & Fox): discard every piece of
        per-request state — block allocator, slot mirrors, paged KV
        pools — and come back empty, WITHOUT touching the compiled
        programs. Geometry is unchanged, so the rebuilt pools slot
        straight into the cached executables: a reset costs two pool
        allocations and zero recompiles. ``steps_run`` keeps counting
        (the chaos plan's step index is monotonic across resets);
        ``resets`` counts the episodes for /healthz."""
        self.alloc = kvc.BlockAllocator(self.cache_cfg)
        if self._index is not None:
            # the pools the cached chains pointed into are gone — a
            # stale index entry would alias zeroed blocks
            self._index = kvc.PrefixIndex(self.cache_cfg.block_size)
        self._kp, self._vp = kvc.init_pools(self.cache_cfg,
                                            self.cfg.compute_dtype)
        self._active[:] = False
        self._tables[:] = self.cache_cfg.trash_block
        self._pos[:] = 0
        self._last[:] = 0
        self._temp[:] = 0.0
        self._seed[:] = 0
        self._aidx[:] = 0
        self._reserved.clear()
        self.last_step_finite = True
        self.resets += 1

    # ------------------------------------------------------------ programs --
    def _build_programs(self) -> None:
        jax, jnp = self._jax, self._jnp
        cfg, ccfg = self.cfg, self.cache_cfg
        n_layers = cfg.num_layers
        bs, trash = ccfg.block_size, ccfg.trash_block
        sentinel = ccfg.max_blocks_per_slot * bs   # OOB position: drop
        scale = self.bank.scale if self.bank is not None else 1.0

        def sample(row, temp, seed, position):
            """The single-request step's formula, per slot: greedy at
            temp 0, else categorical on logits/temp with a per-(seed,
            position) key."""
            key = jax.random.fold_in(jax.random.PRNGKey(seed), position)
            greedy = jnp.argmax(row).astype(jnp.int32)
            sampled = jax.random.categorical(
                key, row / jnp.maximum(temp, 1e-6)).astype(jnp.int32)
            return jnp.where(temp > 0, sampled, greedy)

        # Every serving program is SPLIT into a read-only compute pass
        # (pools are plain operands — gathers cost O(touched blocks))
        # and a write-only scatter pass with the pools DONATED. A fused
        # read+write program defeats XLA's in-place aliasing — it
        # cannot prove the gathered rows and scattered rows are
        # disjoint, so it copies the ENTIRE pool every dispatch:
        # O(num_blocks) per step, ~300 ms at an 8k-block pool on CPU.
        # Split, the write pass is a bare coordinate scatter XLA applies
        # in place (O(slots)), and ordering is enforced by data flow:
        # the write consumes the compute pass's outputs. Cost: one
        # extra dispatch (~0.1 ms) per step/chunk/COW.

        def decode_step(params, stack, kp, vp, tables, pos, active, aidx,
                        last_tok, temps, seeds):
            views = [(kvc.gather_view(kp[i], tables),
                      kvc.gather_view(vp[i], tables))
                     for i in range(n_layers)]
            adapters = None
            if stack is not None:
                from ...llm.lora import lora_select
                adapters = lora_select(stack, aidx)
            q_pos = jnp.where(active, pos, sentinel)
            logits, kvs = self.module.apply(
                {"params": params}, last_tok[:, None],
                positions=q_pos[:, None], kv_view=views,
                adapters=adapters, lora_scale=scale)
            row = logits[:, 0]
            # black-box poison flag: one scalar riding the same transfer
            # as the tokens — the watchdog reads it for free (an inactive
            # slot's row may be garbage; only active rows count)
            finite = jnp.all(jnp.where(active[:, None],
                                       jnp.isfinite(row), True))
            nxt = jax.vmap(sample)(row, temps, seeds, pos + 1)
            kcs = jnp.stack([kc[:, 0] for kc, _ in kvs])   # [L, S, H, D]
            vcs = jnp.stack([vc[:, 0] for _, vc in kvs])
            return nxt, finite, kcs, vcs

        def decode_write(kp, vp, tables, pos, active, kcs, vcs):
            for i in range(n_layers):
                kp = kvc.scatter_token(kp, i, tables, pos, kcs[i],
                                       active, bs, trash)
                vp = kvc.scatter_token(vp, i, tables, pos, vcs[i],
                                       active, bs, trash)
            return kp, vp

        def prefill_chunk(params, stack, kp, vp, table_row, tokens, p0,
                          n_valid, aidx):
            c = tokens.shape[0]
            offs = jnp.arange(c, dtype=jnp.int32)
            positions = p0 + offs
            valid = offs < n_valid
            q_pos = jnp.where(valid, positions, sentinel)
            views = [(kvc.gather_view(kp[i], table_row[None]),
                      kvc.gather_view(vp[i], table_row[None]))
                     for i in range(n_layers)]
            adapters = None
            if stack is not None:
                from ...llm.lora import lora_select
                adapters = lora_select(stack, aidx)   # shared 2-D leaves
            logits, kvs = self.module.apply(
                {"params": params}, tokens[None], positions=q_pos[None],
                kv_view=views, adapters=adapters, lora_scale=scale)
            kcs = jnp.stack([kc[0] for kc, _ in kvs])   # [L, C, H, D]
            vcs = jnp.stack([vc[0] for _, vc in kvs])
            return logits[0], kcs, vcs

        def chunk_write(kp, vp, table_row, p0, n_valid, kcs, vcs):
            c = kcs.shape[1]
            offs = jnp.arange(c, dtype=jnp.int32)
            positions = p0 + offs
            valid = offs < n_valid
            for i in range(n_layers):
                kp = kvc.scatter_chunk(kp, i, table_row, positions,
                                       kcs[i], valid, bs, trash)
                vp = kvc.scatter_chunk(vp, i, table_row, positions,
                                       vcs[i], valid, bs, trash)
            return kp, vp

        def prefill_wave(params, stack, kp, vp, table_rows, tokens, p0,
                         n_valid, aidx):
            """One pass of B piggybacked prefill chunks (tokens
            ``[B, C]``; everything per-row is DATA). Rows with
            ``n_valid == 0`` (request's chunks exhausted) write only to
            the trash block and query at the sentinel position."""
            b, c = tokens.shape
            offs = jnp.arange(c, dtype=jnp.int32)[None, :]
            positions = p0[:, None] + offs
            valid = offs < n_valid[:, None]
            q_pos = jnp.where(valid, positions, sentinel)
            views = [(kvc.gather_view(kp[i], table_rows),
                      kvc.gather_view(vp[i], table_rows))
                     for i in range(n_layers)]
            adapters = None
            if stack is not None:
                from ...llm.lora import lora_select
                adapters = lora_select(stack, aidx)   # per-row 3-D leaves
            logits, kvs = self.module.apply(
                {"params": params}, tokens, positions=q_pos,
                kv_view=views, adapters=adapters, lora_scale=scale)
            kcs = jnp.stack([kc for kc, _ in kvs])   # [L, B, C, H, D]
            vcs = jnp.stack([vc for _, vc in kvs])
            return logits, kcs, vcs

        def wave_write(kp, vp, table_rows, p0, n_valid, kcs, vcs):
            c = kcs.shape[2]
            offs = jnp.arange(c, dtype=jnp.int32)[None, :]
            positions = p0[:, None] + offs
            valid = offs < n_valid[:, None]
            for i in range(n_layers):
                kp = kvc.scatter_chunk_batch(kp, i, table_rows,
                                             positions, kcs[i], valid,
                                             bs, trash)
                vp = kvc.scatter_chunk_batch(vp, i, table_rows,
                                             positions, vcs[i], valid,
                                             bs, trash)
            return kp, vp

        def cow_read(kp, vp, src, dst, n_rows):
            # admission-time copy-on-write, read half: merge the
            # partially matched cached block's first n_rows over the
            # destination block's tail — [L, bs, H, D] per pool, tiny
            keep = (jnp.arange(bs) < n_rows)[None, :, None, None]
            return (jnp.where(keep, kp[:, src], kp[:, dst]),
                    jnp.where(keep, vp[:, src], vp[:, dst]))

        def cow_write(kp, vp, dst, mk, mv):
            # write half: one dynamic-update-slice per pool, in place
            # under donation — the slot owns dst, the source block is
            # never written
            return kp.at[:, dst].set(mk), vp.at[:, dst].set(mv)

        self._step_fn = jax.jit(decode_step)
        self._step_write_fn = jax.jit(decode_write, donate_argnums=(0, 1))
        self._prefill_fn = jax.jit(prefill_chunk)
        self._chunk_write_fn = jax.jit(chunk_write, donate_argnums=(0, 1))
        self._prefill_wave_fn = jax.jit(prefill_wave)
        self._wave_write_fn = jax.jit(wave_write, donate_argnums=(0, 1))
        self._cow_read_fn = jax.jit(cow_read)
        self._cow_write_fn = jax.jit(cow_write, donate_argnums=(0, 1))
        self._sample_fn = jax.jit(sample)

    def _stack(self):
        return self.bank.stack() if self.bank is not None else None

    # ---------------------------------------------------------- admission --
    def free_slots(self) -> List[int]:
        return [int(i) for i in np.flatnonzero(~self._active)
                if int(i) not in self._reserved]

    def can_admit(self, prompt_len: int, max_new_tokens: int) -> bool:
        if not self.free_slots():
            return False
        total = min(int(prompt_len) + int(max_new_tokens),
                    self.cfg.max_seq_len)
        need = self.cache_cfg.blocks_needed(total)
        budget = self.alloc.free_blocks
        if self._index is not None:
            # cold cached chains are reclaimable space: admission may
            # evict them (begin_admit does), so count them as headroom
            budget += self._index.reclaimable(self.alloc)
        return need <= budget

    def _match_prefix(self, ids: List[int]) -> Tuple[List[int], int]:
        """→ ``(chain, matched_tokens)``: the indexed block chain
        prefixing ``ids`` and the token count actually reused, capped at
        ``len(ids) - 1`` so the last prompt token is always prefilled
        (its logits sample the first generated token). Pure lookup —
        hit/reuse accounting happens in ``begin_admit`` once the
        admission actually commits to the aliasing (a dropped alias or a
        returned-None reservation must not count as reuse)."""
        if self._index is None:
            return [], 0
        chain = self._index.match(ids)
        matched = min(len(chain) * self.cache_cfg.block_size,
                      len(ids) - 1)
        return chain, matched

    def begin_admit(self, prompt_ids, *, adapter_idx: int = 0,
                    temperature: float = 0.0, seed: int = 0,
                    max_new_tokens: int = 64) -> Optional[_PendingAdmit]:
        """Reserve a slot + blocks for one request — prefix-match,
        evict cold cache under pressure, alias matched blocks, run the
        COW copy for a partially matched block — WITHOUT prefilling.
        Returns None when no slot or no reclaimable blocks remain (the
        caller waits); raises on requests that can never be admitted."""
        jnp = self._jnp
        ids = list(map(int, prompt_ids))
        if not ids:
            raise ValueError("empty prompt")
        if len(ids) >= self.cfg.max_seq_len:
            raise ValueError(
                f"prompt of {len(ids)} tokens >= max_seq_len "
                f"{self.cfg.max_seq_len}")
        free = self.free_slots()
        if not free:
            return None
        slot = free[0]
        total = min(len(ids) + int(max_new_tokens), self.cfg.max_seq_len)
        need_total = self.cache_cfg.blocks_needed(total)
        bs = self.cache_cfg.block_size
        chain, matched = self._match_prefix(ids)
        n_alias = matched // bs
        n_copy = matched - n_alias * bs          # COW rows, 0..bs-1
        need_fresh = need_total - n_alias
        if need_fresh > self.alloc.free_blocks and self._index is not None:
            ev0 = self._index.evictions
            self._index.evict(self.alloc, need_fresh, protect=chain)
            if need_fresh > self.alloc.free_blocks:
                # only the protected (matched) chain is still evictable:
                # give up aliasing so those cold blocks can go too
                chain, matched, n_alias, n_copy = [], 0, 0, 0
                need_fresh = need_total
                self._index.evict(self.alloc, need_fresh)
            obs_metrics.record_llm_prefix_evictions(
                self._index.evictions - ev0)
        if need_fresh > self.alloc.free_blocks:
            return None
        row = self.alloc.alloc(slot, total, shared=chain[:n_alias])
        if n_copy > 0:
            # copy-on-write: the reusable head of the partially matched
            # block moves into the slot's OWN block; the shared source
            # is read, never written
            dst_d = jnp.int32(int(row[n_alias]))
            mk, mv = self._cow_read_fn(
                self._kp, self._vp, jnp.int32(int(chain[n_alias])),
                dst_d, jnp.int32(n_copy))
            self._kp, self._vp = self._cow_write_fn(
                self._kp, self._vp, dst_d, mk, mv)
        self._reserved.add(slot)
        if self._index is not None:
            # account reuse only now — the admission COMMITTED to this
            # aliasing (not on a dropped alias or an abandoned lookup)
            if matched > 0:
                self._index.hits += 1
                self._index.tokens_reused += matched
            else:
                self._index.misses += 1
            obs_metrics.record_llm_prefix_cache(matched,
                                                len(ids) - matched)
            # suffix-cache accounting: fully-aliased blocks whose tokens
            # the engine GENERATED (indexed at a prior slot's release) —
            # a multi-turn follow-up aliasing its own earlier reply
            n_decode = self._index.count_suffix_reuse(chain[:n_alias])
            if n_decode > 0:
                self._index.suffix_hits += 1
                self._index.suffix_tokens_reused += n_decode * bs
                obs_metrics.record_llm_suffix_cache(n_decode * bs)
        else:
            n_decode = 0
        info = {"cached_tokens": matched,
                "novel_tokens": len(ids) - matched,
                "aliased_blocks": n_alias, "cow_rows": n_copy,
                "suffix_tokens": n_decode * bs}
        self.last_admit_info = info
        return _PendingAdmit(slot=slot, row=row, ids=ids,
                             novel_start=matched, aidx=int(adapter_idx),
                             temp=float(temperature),
                             seed=int(seed) & 0x7FFFFFFF, info=info)

    def abort_admit(self, pending: _PendingAdmit) -> None:
        """Unwind one wave member after a failed ``finish_admits``. A
        member the failure caught BEFORE activation just returns its
        reservation; one already activated (sampling for a LATER member
        raised) is released like any finished slot — its prompt blocks
        were fully written and index-inserted, so cached entries stay
        valid under the index's own pin."""
        if self._active[pending.slot]:
            self.release(pending.slot)
        else:
            self.alloc.free(pending.slot)
            self._reserved.discard(pending.slot)

    def finish_admits(self, pendings: Sequence[_PendingAdmit]
                      ) -> List[int]:
        """Prefill the reserved admissions' novel suffixes — piggybacked
        through the batched wave program when enabled and the wave has
        more than one member, else serially — then activate the slots.
        Returns each request's first generated token, in order."""
        pendings = list(pendings)
        if not pendings:
            return []
        if self.prefill_batch > 1 and len(pendings) > 1:
            lasts = self._prefill_piggybacked(pendings)
        else:
            lasts = [self._prefill_serial(p) for p in pendings]
        jnp = self._jnp
        firsts = []
        for p, logits_row in zip(pendings, lasts):
            first = int(self._sample_fn(
                logits_row, jnp.float32(p.temp), jnp.int32(p.seed),
                jnp.int32(len(p.ids))))
            self._activate(p, first)
            firsts.append(first)
        return firsts

    def _prefill_serial(self, p: _PendingAdmit):
        """Chunked prefill of one pending admission's novel suffix →
        the last prompt token's logits row (device array)."""
        jnp = self._jnp
        c = self.prefill_chunk
        row_dev = jnp.asarray(p.row)
        stack = self._stack()
        logits_last = None
        last_valid = 1
        for j in range(p.novel_start, len(p.ids), c):
            chunk = p.ids[j:j + c]
            n_valid = len(chunk)
            chunk = chunk + [0] * (c - n_valid)
            j_d, nv_d = jnp.int32(j), jnp.int32(n_valid)
            logits_last, kcs, vcs = self._dispatch(
                "llm_prefill_chunk", self._prefill_fn,
                self.params, stack, self._kp, self._vp, row_dev,
                jnp.asarray(chunk, jnp.int32), j_d, nv_d,
                jnp.int32(p.aidx))
            self._kp, self._vp = self._dispatch(
                "llm_prefill_write", self._chunk_write_fn,
                self._kp, self._vp, row_dev, j_d, nv_d, kcs, vcs)
            last_valid = n_valid
        return logits_last[last_valid - 1]

    def _prefill_piggybacked(self, pendings: List[_PendingAdmit]):
        """The admission wave's chunks through the ``[B, C]`` program:
        pass j carries every member's j-th novel chunk (exhausted rows
        ride along as zero-valid trash writes), so the wave costs
        ``ceil(longest_novel / C)`` passes instead of the members' sum.
        Returns each member's last-prompt-token logits row."""
        jnp = self._jnp
        c, b = self.prefill_chunk, self.prefill_batch
        stack = self._stack()
        lasts: List[Any] = [None] * len(pendings)
        for g0 in range(0, len(pendings), b):
            group = pendings[g0:g0 + b]
            rows = np.full((b, self.cache_cfg.max_blocks_per_slot),
                           self.cache_cfg.trash_block, np.int32)
            aidx = np.zeros(b, np.int32)
            counts = []
            for i, p in enumerate(group):
                rows[i] = p.row
                aidx[i] = p.aidx
                counts.append(-(-(len(p.ids) - p.novel_start) // c))
            rows_dev = jnp.asarray(rows)
            aidx_dev = jnp.asarray(aidx)
            for j in range(max(counts)):
                toks = np.zeros((b, c), np.int32)
                p0 = np.zeros(b, np.int32)
                n_valid = np.zeros(b, np.int32)
                for i, p in enumerate(group):
                    start = p.novel_start + j * c
                    chunk = p.ids[start:start + c]
                    if not chunk:
                        continue
                    toks[i, :len(chunk)] = chunk
                    p0[i] = start
                    n_valid[i] = len(chunk)
                p0_d, nv_d = jnp.asarray(p0), jnp.asarray(n_valid)
                logits, kcs, vcs = self._dispatch(
                    "llm_prefill_wave", self._prefill_wave_fn,
                    self.params, stack, self._kp, self._vp, rows_dev,
                    jnp.asarray(toks), p0_d, nv_d, aidx_dev)
                self._kp, self._vp = self._dispatch(
                    "llm_wave_write", self._wave_write_fn,
                    self._kp, self._vp, rows_dev, p0_d, nv_d, kcs, vcs)
                for i, p in enumerate(group):
                    if j == counts[i] - 1:
                        lasts[g0 + i] = logits[i, int(n_valid[i]) - 1]
        return lasts

    def _activate(self, p: _PendingAdmit, first: int) -> None:
        slot = p.slot
        self._active[slot] = True
        self._tables[slot] = p.row
        self._pos[slot] = len(p.ids)
        self._last[slot] = first
        self._temp[slot] = p.temp
        self._seed[slot] = p.seed
        self._aidx[slot] = p.aidx
        self._reserved.discard(slot)
        if self._index is not None:
            # now that the prompt's full blocks are completely written
            # (and never rewritten: decode lands past the prompt), they
            # become shareable
            self._index.insert(p.ids, p.row, len(p.ids), self.alloc)

    def admit(self, prompt_ids, *, adapter_idx: int = 0,
              temperature: float = 0.0, seed: int = 0,
              max_new_tokens: int = 64) -> Tuple[int, int]:
        """Prefill one request into the lowest free slot; returns
        ``(slot, first_generated_token)``. Deterministic: the same admit
        sequence always lands in the same slots with the same cache
        layout."""
        pending = self.begin_admit(
            prompt_ids, adapter_idx=adapter_idx, temperature=temperature,
            seed=seed, max_new_tokens=max_new_tokens)
        if pending is None:
            if not self.free_slots():
                raise RuntimeError("no free slot")
            raise RuntimeError(
                f"KV pool exhausted: "
                f"{self.alloc.free_blocks} blocks free")
        try:
            first = self.finish_admits([pending])[0]
        except Exception:
            # a failed prefill must not strand the reservation: the
            # slot and its worst-case block reserve go back to the pool
            self.abort_admit(pending)
            raise
        return pending.slot, first

    def release(self, slot: int, final_ids=None) -> None:
        """Return a slot's blocks to the pool. Under suffix caching the
        caller passes ``final_ids`` — the request's full token chain
        (prompt + generated) — and every fully WRITTEN decode block is
        indexed first, under the same pin discipline as prompt blocks,
        so a follow-up or requeued request aliases the whole
        conversation prefix. The insert must precede the free: ``retain``
        requires a live reference, which the slot still holds here.

        Only positions ``0.._pos[slot]-1`` have KV in the pool (the
        final sampled token was never scattered — the slot retired
        before its next step), so indexing caps at ``_pos[slot]``."""
        slot = int(slot)
        if (self.suffix_cache and self._index is not None
                and final_ids is not None and self._active[slot]):
            n = min(int(self._pos[slot]), len(final_ids))
            if n >= self.cache_cfg.block_size:
                added = self._index.insert(
                    [int(t) for t in final_ids[:n]], self._tables[slot],
                    n, self.alloc, origin="decode")
                if added:
                    obs_metrics.record_llm_suffix_insert(added)
        self.alloc.free(slot)
        self._active[slot] = False
        self._tables[slot] = self.cache_cfg.trash_block

    # --------------------------------------------------------------- step --
    def active_count(self) -> int:
        return int(self._active.sum())

    def step(self) -> Dict[int, int]:
        """One decode step for every active slot → ``{slot: next_token}``.
        Each slot's ``last_tok`` is written into the cache at its position
        and the following token is sampled; positions advance by one."""
        jnp = self._jnp
        if not self._active.any():
            return {}
        tables_d = jnp.asarray(self._tables)
        pos_d = jnp.asarray(self._pos)
        active_d = jnp.asarray(self._active)
        nxt, finite, kcs, vcs = self._dispatch(
            "llm_decode_step", self._step_fn,
            self.params, self._stack(), self._kp, self._vp,
            tables_d, pos_d, active_d, jnp.asarray(self._aidx),
            jnp.asarray(self._last), jnp.asarray(self._temp),
            jnp.asarray(self._seed))
        self._kp, self._vp = self._dispatch(
            "llm_decode_write", self._step_write_fn,
            self._kp, self._vp, tables_d, pos_d, active_d, kcs, vcs)
        toks = np.asarray(nxt)
        self.last_step_finite = bool(finite)
        self.steps_run += 1
        out: Dict[int, int] = {}
        for slot in np.flatnonzero(self._active):
            slot = int(slot)
            self._pos[slot] += 1
            self._last[slot] = toks[slot]
            out[slot] = int(toks[slot])
        return out

    def slot_position(self, slot: int) -> int:
        return int(self._pos[slot])

    # ------------------------------------------------------- observability --
    def kv_pool_stats(self) -> Dict[str, Any]:
        """Paged-pool state for the SLO gauges: used/free blocks, how
        many WORST-CASE (max_seq_len) requests the free list can still
        admit, and internal fragmentation — the reserved-but-unwritten
        fraction of allocated blocks (admission reserves prompt+max_new
        up front, so a short generation strands block tail capacity
        until release)."""
        ccfg = self.cache_cfg
        free = self.alloc.free_blocks
        used = ccfg.num_blocks - free
        per_req = ccfg.blocks_needed(ccfg.max_seq_len)
        written = int(self._pos[self._active].sum()) if used else 0
        reclaimable = 0
        if self._index is not None:
            reclaimable = self._index.reclaimable(self.alloc)
            # index-only cached blocks are FULL by construction (only
            # completely written prompt blocks are indexed) — without
            # this an idle pool holding a warm cache reads as 100%
            # fragmented
            written += reclaimable * ccfg.block_size
        capacity = used * ccfg.block_size
        # aliasing REDUCES fragmentation: two slots reading one physical
        # block count their positions against a single block's capacity
        # (clamped at 0 when sharing overshoots)
        frag = 1.0 - written / capacity if capacity else 0.0
        # headroom counts reclaimable cache blocks as free: admission
        # evicts refcount-0 cached blocks on demand, so a full-but-warm
        # pool can still admit. Counting only the free list makes a
        # replica look MORE loaded the warmer its cache gets, and a
        # cache-aware gateway would spill away from exactly the
        # replicas it tried to keep warm.
        return {"used_blocks": used, "free_blocks": free,
                "headroom_requests": (free + reclaimable) // per_req,
                "fragmentation": round(max(frag, 0.0), 4),
                "aliased_blocks": self.alloc.aliased_blocks(),
                "cached_blocks": (self._index.cached_blocks
                                  if self._index is not None else 0)}

    def debug_state(self) -> Dict[str, Any]:
        """The slot matrix + block-table summary, host-side mirrors only
        (no device sync) — the ``/debug/state`` payload."""
        slots = []
        for s in range(self.slots):
            row = {"slot": s, "active": bool(self._active[s])}
            if self._active[s]:
                table = self._tables[s]
                owned = table[table != self.cache_cfg.trash_block]
                row.update({
                    "position": int(self._pos[s]),
                    "adapter_idx": int(self._aidx[s]),
                    "temperature": float(self._temp[s]),
                    "blocks": int(owned.size),
                    "aliased_blocks": int(sum(
                        1 for b in owned
                        if self.alloc.refcount(int(b)) >= 2))})
            slots.append(row)
        out = {"slots": slots, "steps_run": int(self.steps_run),
               "resets": int(self.resets),
               "last_step_finite": bool(self.last_step_finite),
               "kv_pool": self.kv_pool_stats(),
               "geometry": {
                   "num_slots": self.slots,
                   "block_size": self.cache_cfg.block_size,
                   "num_blocks": self.cache_cfg.num_blocks,
                   "max_seq_len": self.cfg.max_seq_len,
                   "prefill_chunk": self.prefill_chunk,
                   "prefill_batch": self.prefill_batch,
                   "prefix_cache": self._index is not None,
                   "suffix_cache": self.suffix_cache}}
        if self._index is not None:
            # the live-diagnosis payload an aliasing bug needs: the
            # index's hit/eviction counters plus every allocated block's
            # reference count (>= 2 means shared right now)
            pc = self._index.debug_state()
            pc["block_refcounts"] = {
                str(b): int(c)
                for b, c in sorted(self.alloc.refcounts().items())}
            out["prefix_cache"] = pc
        return out
