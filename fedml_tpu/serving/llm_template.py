"""LLM serving template: a causal-LM predictor with a compiled generate
loop and an OpenAI-compatible chat route.

Parity target: the reference's HF chatbot serving template
(``serving/templates/hf_template/src/main_entry.py`` — a
``FedMLPredictor`` wrapping an HF pipeline behind
``FedMLInferenceRunner``, with the OpenAI-style request/response shape
its docs advertise). TPU-first redesign:

* generation runs through ONE jitted fixed-shape step — the token buffer
  is padded to ``max_seq_len`` and the step reads the logits at the
  current position, so every decode step reuses the same compiled
  program (no per-length recompiles; causal masking makes the padded
  tail inert);
* the model is the repo's own flax ``CausalLM`` (optionally with LoRA
  adapters merged via the bundle), loaded from a ``save_model`` artifact
  — msgpack, never pickle;
* the chat endpoint speaks ``POST /v1/chat/completions`` with the
  OpenAI request/response schema, so existing OpenAI clients can point
  at a served federated fine-tune unchanged.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import FedMLInferenceRunner, FedMLPredictor, load_model

logger = logging.getLogger(__name__)

PyTree = Any


class CausalLMPredictor(FedMLPredictor):
    """Serve a fedml_tpu causal LM.

    ``bundle`` is an :class:`~fedml_tpu.llm.federated.LLMBundle` (its
    ``apply`` merges LoRA adapters when present); ``params`` is the
    trainable tree that ``run_federated_llm`` / ``save_model`` produced.

    Two serving modes (``llm_serving_mode``):

    * ``"single"`` (default, the original behavior): one request at a
      time through one compiled full-forward step over the padded
      ``[1, max_seq_len]`` buffer;
    * ``"batch"``: requests flow through the continuous-batching engine
      (``serving/batch/``) — paged KV cache, one-token decode work per
      step, per-request LoRA adapter selection from a multi-adapter bank
      (``adapter_bank`` / ``llm_adapter_dir``), deadline eviction.
    """

    def __init__(self, bundle, params: PyTree, tokenizer=None,
                 max_seq_len: Optional[int] = None,
                 temperature: float = 0.0, mode: str = "single",
                 batch_opts: Optional[Dict[str, Any]] = None,
                 adapter_bank=None, stream: bool = False):
        import jax
        import jax.numpy as jnp

        from ..llm.data import ByteTokenizer

        self.bundle = bundle
        self.params = params
        self.tokenizer = tokenizer or ByteTokenizer()
        self.max_seq_len = int(max_seq_len or bundle.cfg.max_seq_len)
        self.temperature = float(temperature)
        self.mode = str(mode)
        # llm_stream knob: with it OFF a request carrying "stream": true
        # gets the ordinary JSON completion — the wire stays byte-
        # identical to the pre-streaming path
        self.stream_enabled = bool(stream)
        if self.mode not in ("single", "batch"):
            raise ValueError(f"llm_serving_mode {mode!r}: single|batch")

        def step(params, buf, pos, temp, key):
            # buf: [1, L] padded token buffer; logits at the last real
            # position decide the next token. Fixed shapes = one compile.
            logits = bundle.apply(params, buf)[0, pos - 1]
            greedy = jnp.argmax(logits).astype(jnp.int32)
            sampled = jax.random.categorical(key, logits / jnp.maximum(
                temp, 1e-6)).astype(jnp.int32)
            return jnp.where(temp > 0, sampled, greedy)

        self._step = jax.jit(step)
        self._jnp = jnp
        self._jax = jax
        self._engine = None
        self._bank = adapter_bank
        self._default_aidx = 0
        self._request_timeout_s = float(
            (batch_opts or {}).get("request_timeout_s", 120.0))
        # suffix caching changes how multi-turn chats ENCODE: the
        # follow-up must reproduce the prior request's exact token chain
        # (prompt ++ SEP ++ generated reply) for the generated blocks to
        # alias; knob off keeps the legacy "\n"-joined prompt byte-for-
        # byte
        self._suffix_chat = bool(
            (batch_opts or {}).get("suffix_cache", False))
        if self._suffix_chat:
            # the byte tokenizer's "replace" decode is lossy on invalid
            # UTF-8 (an untrained model emits it freely), which would
            # break encode(decode(ids)) == ids — the equality the whole
            # suffix-alias path rests on. Swap in the round-trip-exact
            # variant; for valid UTF-8 it is byte-identical.
            from ..llm.data import ByteTokenizer, RoundTripByteTokenizer
            if type(self.tokenizer) is ByteTokenizer:
                self.tokenizer = RoundTripByteTokenizer()
        if self.mode == "batch":
            self._build_engine(batch_opts or {})

    def _build_engine(self, opts: Dict[str, Any]) -> None:
        from .batch import AdapterBank, BatchingEngine, DecodeScheduler
        bundle = self.bundle
        if bundle.base_params is not None:
            # LoRA artifact: base model resident, the artifact's adapter
            # registered as "default" so adapter-less requests behave like
            # the single path (modulo factored-vs-merged float paths)
            base = bundle.base_params
            if self._bank is None:
                self._bank = AdapterBank(
                    self.params, alpha=bundle.lora_alpha,
                    capacity=int(opts.get("max_adapters", 64)))
            self._default_aidx = self._bank.add("default", self.params)
        else:
            # full fine-tune artifact: the params ARE the model; a bank
            # only makes sense if the caller supplied one
            base = self.params
            if self._bank is not None:
                self._default_aidx = 0
        scheduler = DecodeScheduler(
            bundle.module, bundle.cfg, base, self._bank,
            slots=int(opts.get("slots", 8)),
            block_size=int(opts.get("block_size", 16)),
            num_blocks=opts.get("num_blocks"),
            prefill_chunk=int(opts.get("prefill_chunk", 32)),
            prefix_cache=bool(opts.get("prefix_cache", False)),
            prefill_batch=int(opts.get("prefill_batch", 0) or 0),
            suffix_cache=bool(opts.get("suffix_cache", False)))
        self._engine = BatchingEngine(
            scheduler,
            default_deadline_s=float(opts.get("deadline_s", 0.0)),
            watchdog_s=float(opts.get("watchdog_s", 30.0)),
            flight_records=int(opts.get("flight_records", 256)),
            flight_dir=opts.get("flight_dir"),
            max_resets=int(opts.get("max_resets", 3)),
            reset_window_s=float(opts.get("reset_window_s", 300.0)),
            max_requeues=int(opts.get("max_requeues", 2)),
            preempt_after_s=float(opts.get("preempt_after_s", 0.0)),
            shed_queue_depth=int(opts.get("shed_queue_depth", 0)),
            chaos=opts.get("chaos"))

    @property
    def adapter_bank(self):
        return self._bank

    @property
    def engine(self):
        return self._engine

    def health(self) -> Dict[str, Any]:
        """``/healthz`` payload: the engine's watchdog view in batch
        mode; the single path is stateless, so up == ok."""
        if self._engine is not None:
            return self._engine.health()
        return {"status": "ok", "mode": "single"}

    def debug_state(self) -> Dict[str, Any]:
        if self._engine is not None:
            return self._engine.debug_state()
        return {"mode": "single", "max_seq_len": self.max_seq_len}

    def close(self) -> None:
        if self._bank is not None and hasattr(self._bank, "stop_watch"):
            self._bank.stop_watch()
        if self._engine is not None:
            self._engine.stop()
            self._engine = None

    @classmethod
    def from_artifact(cls, args, params_path: str, **kw):
        """Load a served artifact the way the CLI/launcher does: rebuild
        the bundle from config (model only — no dataset construction),
        params from the msgpack artifact. ``llm_serving_mode: batch``
        turns on continuous batching; ``llm_adapter_dir`` loads a named
        adapter bank exported by ``llm/federated.py``."""
        from ..llm.federated import build_llm_bundle
        bundle, tokenizer = build_llm_bundle(args)
        kw.setdefault("mode", str(getattr(args, "llm_serving_mode",
                                          "single")))
        if kw["mode"] == "batch":
            kw.setdefault("batch_opts", {
                "slots": int(getattr(args, "serving_slots", 8)),
                "block_size": int(getattr(args, "serving_kv_block_size",
                                          16)),
                "prefill_chunk": int(getattr(args, "serving_prefill_chunk",
                                             32)),
                "max_adapters": int(getattr(args, "serving_max_adapters",
                                            64)),
                "deadline_s": float(getattr(args, "serving_deadline_s",
                                            0.0)),
                "request_timeout_s": float(
                    getattr(args, "serving_request_timeout_s", 120.0)),
                "watchdog_s": float(getattr(args, "serving_watchdog_s",
                                            30.0)),
                "flight_records": int(getattr(args,
                                              "serving_flight_records",
                                              256)),
                "flight_dir": (getattr(args, "serving_flight_dir", None)
                               or getattr(args, "log_file_dir", None)),
                "max_resets": int(getattr(args, "serving_max_resets", 3)),
                "reset_window_s": float(
                    getattr(args, "serving_reset_window_s", 300.0)),
                "max_requeues": int(
                    getattr(args, "serving_max_requeues", 2)),
                "preempt_after_s": float(
                    getattr(args, "serving_preempt_after_s", 0.0)),
                "shed_queue_depth": int(
                    getattr(args, "serving_shed_queue_depth", 0)),
                "prefix_cache": bool(
                    getattr(args, "llm_prefix_cache", False)),
                "prefill_batch": int(
                    getattr(args, "llm_prefill_batch", 0) or 0),
                "suffix_cache": bool(
                    getattr(args, "llm_suffix_cache", False)),
            })
            # seeded serving chaos (engine-side stall/NaN injection);
            # None unless a chaos_serving_* knob is live, so the default
            # decode loop never consults a plan
            if kw["batch_opts"].get("chaos") is None:
                from ..core.chaos import ServingChaosInjector
                kw["batch_opts"]["chaos"] = \
                    ServingChaosInjector.from_args(args)
            adapter_dir = getattr(args, "llm_adapter_dir", None)
            if adapter_dir and kw.get("adapter_bank") is None:
                from .batch import AdapterBank
                kw["adapter_bank"] = AdapterBank.from_artifacts(
                    adapter_dir,
                    alpha=float(getattr(args, "lora_alpha", 16.0)),
                    capacity=int(getattr(args, "serving_max_adapters",
                                         64)))
                # adapter hot-swap: watch the export dir so a fresh
                # federated round's adapters go live with zero restart
                watch_s = float(getattr(args, "llm_adapter_watch_s",
                                        0.0) or 0.0)
                if watch_s > 0:
                    kw["adapter_bank"].watch_dir(adapter_dir,
                                                 poll_s=watch_s)
        kw.setdefault("stream", bool(getattr(args, "llm_stream", False)))
        return cls(bundle, load_model(params_path), tokenizer=tokenizer,
                   **kw)

    # --- generation ---------------------------------------------------------
    def _encode_prompt(self, prompt: str, max_new_tokens: int) -> List[int]:
        """Tokenize and fit the prompt: keep the TAIL of an over-long
        prompt (the most recent turns — for chat, dropping the head is
        right and dropping the tail is exactly wrong) and reserve room
        for ``max_new_tokens`` of completion."""
        from ..llm.data import BOS, SEP
        ids = [BOS] + self.tokenizer.encode(prompt) + [SEP]
        reserve = max(1, min(int(max_new_tokens), self.max_seq_len - 1))
        budget = max(1, self.max_seq_len - reserve)
        if len(ids) > budget:
            ids = ids[-budget:]
        return ids

    def _encode_chat(self, messages, max_new_tokens: int) -> List[int]:
        """Suffix-cache chat encoding: assistant turns ride behind a
        ``SEP`` (instruction ++ SEP ++ response — the shape the engine's
        own decode wrote into the KV pool), so a follow-up's token chain
        is EXACTLY the prior request's chain ++ the new user turn, and
        the generated-token blocks alias instead of re-prefilling. The
        byte tokenizer encodes per character, so concatenating per-turn
        encodes equals encoding the concatenation — turn-1 requests
        produce the same ids as :meth:`_encode_prompt`."""
        from ..llm.data import BOS, SEP
        ids: List[int] = [BOS]
        first = True
        for m in messages:
            content = m.get("content") if isinstance(m, dict) else None
            if not content:
                continue
            if isinstance(m, dict) and m.get("role") == "assistant":
                ids += [SEP] + self.tokenizer.encode(str(content))
            else:
                ids += self.tokenizer.encode(
                    str(content) if first else "\n" + str(content))
            first = False
        ids.append(SEP)
        reserve = max(1, min(int(max_new_tokens), self.max_seq_len - 1))
        budget = max(1, self.max_seq_len - reserve)
        if len(ids) > budget:
            ids = ids[-budget:]
        return ids

    def generate(self, prompt: str, max_new_tokens: int = 64,
                 temperature: Optional[float] = None,
                 seed: Optional[int] = None,
                 adapter: Optional[str] = None) -> Dict[str, Any]:
        """``seed=None`` (the default) derives a fresh per-request seed,
        so concurrent no-seed users at ``temperature > 0`` get distinct
        samples; an explicit seed reproduces exactly."""
        import os as _os
        temp = self.temperature if temperature is None else float(temperature)
        if seed is None:
            seed = int.from_bytes(_os.urandom(4), "little") & 0x7FFFFFFF
        ids = self._encode_prompt(prompt, max_new_tokens)
        if self._engine is not None:
            return self._generate_batched(ids, max_new_tokens, temp,
                                          int(seed), adapter)
        if adapter is not None:
            raise ValueError(
                "per-request adapter selection needs llm_serving_mode: "
                "batch (the single path serves one merged artifact)")
        return self._generate_single(ids, max_new_tokens, temp, int(seed))

    def _generate_single(self, ids: List[int], max_new_tokens: int,
                         temp: float, seed: int) -> Dict[str, Any]:
        from ..llm.data import EOS
        jnp = self._jnp
        n_prompt = len(ids)
        buf = np.zeros((1, self.max_seq_len), np.int32)
        buf[0, :n_prompt] = ids
        buf = jnp.asarray(buf)
        key = self._jax.random.PRNGKey(seed)
        pos = n_prompt
        out_ids: List[int] = []
        finish = "length"
        for _ in range(int(max_new_tokens)):
            if pos >= self.max_seq_len:
                break
            key, sub = self._jax.random.split(key)
            nxt = int(self._step(self.params, buf, jnp.int32(pos),
                                 jnp.float32(temp), sub))
            if nxt == EOS:
                finish = "stop"
                break
            out_ids.append(nxt)
            buf = buf.at[0, pos].set(nxt)
            pos += 1
        return {"text": self.tokenizer.decode(out_ids),
                "finish_reason": finish,
                "prompt_tokens": n_prompt,
                "completion_tokens": len(out_ids)}

    def _resolve_aidx(self, adapter: Optional[str]) -> Tuple[int, bool]:
        """Adapter name → ``(bank row index, pinned)`` — the ONE
        resolution path for batched and streamed requests. Resolution
        and pinning happen atomically (:meth:`AdapterBank.acquire`), so
        a concurrent hot-swap can never retire-and-reuse the row between
        lookup and submit; the pin transfers to the engine request
        (released at resolution) via ``adapter_pre_pinned``."""
        if adapter is not None and self._bank is None:
            raise ValueError(
                f"adapter {adapter!r} requested but no adapter bank is "
                "loaded (full fine-tune artifact without llm_adapter_dir)")
        pinned = False
        if adapter is not None:
            aidx = self._bank.acquire(adapter)
            pinned = aidx > 0
        else:
            aidx = self._default_aidx
            if self._bank is not None and aidx > 0:
                self._bank.retain_row(aidx)   # fixed idx: no name race
                pinned = True
        from ..core.obs import metrics as obs_metrics
        obs_metrics.record_llm_adapter(
            adapter if adapter is not None
            else ("default" if self._default_aidx else "base"))
        return aidx, pinned

    def _submit_pinned(self, ids: List[int], *, max_new_tokens: int,
                       temp: float, seed: int, adapter: Optional[str],
                       stream_q=None):
        """Resolve+pin the adapter and submit; a submit that raises
        before the engine owns the request releases the pin here."""
        aidx, pinned = self._resolve_aidx(adapter)
        try:
            return self._engine.submit(
                ids, max_new_tokens=int(max_new_tokens),
                temperature=temp, seed=seed, adapter_idx=aidx,
                adapter_pre_pinned=pinned, stream_q=stream_q)
        except Exception:
            if pinned:
                self._bank.release_row(aidx)
            raise

    def _generate_batched(self, ids: List[int], max_new_tokens: int,
                          temp: float, seed: int,
                          adapter: Optional[str]) -> Dict[str, Any]:
        fut = self._submit_pinned(ids, max_new_tokens=max_new_tokens,
                                  temp=temp, seed=seed, adapter=adapter)
        out = fut.result(timeout=self._request_timeout_s)
        return {"text": self.tokenizer.decode(out["ids"]),
                "finish_reason": out["finish_reason"],
                "prompt_tokens": out["prompt_tokens"],
                "completion_tokens": out["completion_tokens"]}

    # --- request surfaces ---------------------------------------------------
    def predict(self, request: Any) -> Any:
        """Plain surface: ``{"prompt": str, "max_new_tokens"?,
        "temperature"?, "seed"?, "adapter"?}`` → ``{"text": ...}``.
        No ``seed`` in the request → a fresh per-request seed (each
        sampled request gets its own stream); an explicit seed is
        reproducible."""
        seed = request.get("seed")
        out = self.generate(
            str(request.get("prompt", "")),
            max_new_tokens=int(request.get("max_new_tokens", 64)),
            temperature=request.get("temperature"),
            seed=None if seed is None else int(seed),
            adapter=request.get("adapter"))
        return out

    def _resolve_adapter(self, request: Any) -> Optional[str]:
        """Explicit ``adapter`` wins; otherwise an OpenAI ``model`` field
        naming a bank entry selects it — existing OpenAI clients pick
        their federated per-silo personalization by model name."""
        adapter = request.get("adapter")
        if adapter is not None:
            return str(adapter)
        model = request.get("model")
        if (model is not None and self._bank is not None
                and self._bank.has(str(model))):
            return str(model)
        return None

    def chat(self, request: Any) -> Any:
        """OpenAI ``/v1/chat/completions`` schema. The prompt is the
        concatenated user/system turns (the instruction-tuning format the
        federated fine-tune trained on: instruction ++ SEP ++ response).
        With the ``llm_stream`` knob on, a request carrying ``"stream":
        true`` returns ``text/event-stream`` chunk deltas instead (knob
        off ⇒ the stream flag is ignored and the wire is byte-identical
        to the pre-streaming path)."""
        messages = request.get("messages") or []
        # keep EVERY turn (assistant replies included) — dropping the
        # model's own prior turns would make multi-turn continuations
        # incoherent
        prompt = "\n".join(str(m.get("content", "")) for m in messages
                           if m.get("content"))
        seed = request.get("seed")
        max_new = int(request.get("max_tokens", 64))
        # suffix-cache encoding (knob-gated): token-level chat layout so
        # follow-ups alias their own generated turns; knob off keeps the
        # legacy string prompt byte-identical
        use_suffix = self._suffix_chat and self._engine is not None
        ids = self._encode_chat(messages, max_new) if use_suffix else None
        if (self.stream_enabled and request.get("stream")
                and self._engine is not None):
            return self._chat_stream(request, prompt, seed, ids=ids)
        if use_suffix:
            import os as _os
            temp = (self.temperature
                    if request.get("temperature") is None
                    else float(request.get("temperature")))
            rseed = (int.from_bytes(_os.urandom(4), "little") & 0x7FFFFFFF
                     if seed is None else int(seed))
            out = self._generate_batched(ids, max_new, temp, rseed,
                                         self._resolve_adapter(request))
        else:
            out = self.generate(
                prompt,
                max_new_tokens=max_new,
                temperature=request.get("temperature"),
                seed=None if seed is None else int(seed),
                adapter=self._resolve_adapter(request))
        # OpenAI's finish_reason enum has no server-side eviction values:
        # "stop" stays "stop", every server-cut reason ("length",
        # "deadline", "preempted") maps to "length" for client compat,
        # with the native reason preserved in finish_reason_detail so a
        # caller can tell "budget spent" from "truncated by the server"
        native = out["finish_reason"]
        return {
            "id": f"chatcmpl-{uuid.uuid4().hex[:24]}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": str(request.get("model", self.bundle.name)),
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": out["text"]},
                "finish_reason": "stop" if native == "stop" else "length",
                "finish_reason_detail": native,
            }],
            "usage": {
                "prompt_tokens": out["prompt_tokens"],
                "completion_tokens": out["completion_tokens"],
                "total_tokens": out["prompt_tokens"]
                + out["completion_tokens"],
            },
        }

    def _chat_stream(self, request: Any, prompt: str, seed,
                     ids: Optional[List[int]] = None) -> Any:
        """SSE token streaming: submit with a stream queue and emit one
        OpenAI ``chat.completion.chunk`` per decoded text delta, closed
        by a finish frame carrying ``finish_reason`` +
        ``finish_reason_detail`` and the usage totals. An engine
        preempt/requeue (PR 11 recovery) replays transparently
        mid-stream — the kept prefix is never re-emitted, the stream
        just pauses over the recompute gap."""
        import os as _os
        import queue as _queue

        from . import SSEStream
        from ..core.obs import metrics as obs_metrics

        temp = (self.temperature if request.get("temperature") is None
                else float(request.get("temperature")))
        if seed is None:
            seed = int.from_bytes(_os.urandom(4), "little") & 0x7FFFFFFF
        max_new = int(request.get("max_tokens", 64))
        obs_metrics.record_llm_stream_request()
        if ids is None:
            ids = self._encode_prompt(prompt, max_new)
        q: "_queue.SimpleQueue" = _queue.SimpleQueue()
        # submit BEFORE returning the stream: an Overloaded/validation
        # verdict still surfaces as the ordinary HTTP error, not a
        # broken half-stream
        fut = self._submit_pinned(ids, max_new_tokens=max_new,
                                  temp=temp, seed=int(seed),
                                  adapter=self._resolve_adapter(request),
                                  stream_q=q)
        rid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        created = int(time.time())
        model = str(request.get("model", self.bundle.name))
        deadline = time.time() + self._request_timeout_s

        def chunk(delta: Dict[str, Any], finish=None, **extra):
            out = {"id": rid, "object": "chat.completion.chunk",
                   "created": created, "model": model,
                   "choices": [{"index": 0, "delta": delta,
                                "finish_reason": finish}]}
            out["choices"][0].update(extra)
            return out

        def events():
            yield chunk({"role": "assistant", "content": ""})
            toks: List[int] = []
            emitted = ""
            while True:
                try:
                    kind, val = q.get(
                        timeout=max(deadline - time.time(), 0.001))
                except _queue.Empty:
                    raise TimeoutError(
                        f"stream stalled past request_timeout_s "
                        f"{self._request_timeout_s}")
                if kind == "token":
                    toks.append(int(val))
                    if self._suffix_chat:
                        # per-token deltas: the full-redecode slicing
                        # below silently drops bytes whenever a multi-
                        # byte sequence resolves retroactively (text
                        # changes without growing), so the client's
                        # concatenated reply would not re-encode to the
                        # generated ids. One token -> one lossless delta
                        # keeps the follow-up's re-encode exact.
                        delta = self.tokenizer.decode([int(val)])
                    else:
                        text = self.tokenizer.decode(toks)
                        delta = text[len(emitted):]
                        if delta:
                            emitted = text
                    if delta:
                        yield chunk({"content": delta})
                elif kind == "finish":
                    native = str(val)
                    out = fut.result(timeout=5.0)
                    yield chunk(
                        {}, finish="stop" if native == "stop"
                        else "length",
                        finish_reason_detail=native,
                        usage={
                            "prompt_tokens": out["prompt_tokens"],
                            "completion_tokens":
                                out["completion_tokens"],
                            "total_tokens": out["prompt_tokens"]
                            + out["completion_tokens"]})
                    return
                else:   # ("error", msg)
                    raise RuntimeError(f"stream failed: {val}")

        return SSEStream(events())


class ChatCompletionRunner(FedMLInferenceRunner):
    """Inference runner with the OpenAI chat route mounted:
    ``POST /v1/chat/completions`` (and ``/predict`` + ``/ready`` from the
    base runner)."""

    def __init__(self, predictor: CausalLMPredictor, host: str = "127.0.0.1",
                 port: int = 0, chaos=None):
        super().__init__(predictor, host=host, port=port,
                         extra_routes={
                             "/v1/chat/completions": predictor.chat},
                         chaos=chaos)


def serve_chat(args, params_path: str, host: str = "127.0.0.1",
               port: int = 0, block: bool = False) -> ChatCompletionRunner:
    """Two-line path from a federated LoRA artifact to a chat endpoint."""
    predictor = CausalLMPredictor.from_artifact(args, params_path)
    runner = ChatCompletionRunner(predictor, host=host, port=port)
    if block:
        runner.run()
    else:
        runner.start()
    return runner
