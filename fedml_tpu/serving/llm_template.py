"""LLM serving template: a causal-LM predictor with a compiled generate
loop and an OpenAI-compatible chat route.

Parity target: the reference's HF chatbot serving template
(``serving/templates/hf_template/src/main_entry.py`` — a
``FedMLPredictor`` wrapping an HF pipeline behind
``FedMLInferenceRunner``, with the OpenAI-style request/response shape
its docs advertise). TPU-first redesign:

* generation runs through ONE jitted fixed-shape step — the token buffer
  is padded to ``max_seq_len`` and the step reads the logits at the
  current position, so every decode step reuses the same compiled
  program (no per-length recompiles; causal masking makes the padded
  tail inert);
* the model is the repo's own flax ``CausalLM`` (optionally with LoRA
  adapters merged via the bundle), loaded from a ``save_model`` artifact
  — msgpack, never pickle;
* the chat endpoint speaks ``POST /v1/chat/completions`` with the
  OpenAI request/response schema, so existing OpenAI clients can point
  at a served federated fine-tune unchanged.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

from . import FedMLInferenceRunner, FedMLPredictor, load_model

logger = logging.getLogger(__name__)

PyTree = Any


class CausalLMPredictor(FedMLPredictor):
    """Serve a fedml_tpu causal LM: greedy/temperature decoding with a
    single compiled step.

    ``bundle`` is an :class:`~fedml_tpu.llm.federated.LLMBundle` (its
    ``apply`` merges LoRA adapters when present); ``params`` is the
    trainable tree that ``run_federated_llm`` / ``save_model`` produced.
    """

    def __init__(self, bundle, params: PyTree, tokenizer=None,
                 max_seq_len: Optional[int] = None,
                 temperature: float = 0.0):
        import jax
        import jax.numpy as jnp

        from ..llm.data import ByteTokenizer

        self.bundle = bundle
        self.params = params
        self.tokenizer = tokenizer or ByteTokenizer()
        self.max_seq_len = int(max_seq_len or bundle.cfg.max_seq_len)
        self.temperature = float(temperature)

        def step(params, buf, pos, temp, key):
            # buf: [1, L] padded token buffer; logits at the last real
            # position decide the next token. Fixed shapes = one compile.
            logits = bundle.apply(params, buf)[0, pos - 1]
            greedy = jnp.argmax(logits).astype(jnp.int32)
            sampled = jax.random.categorical(key, logits / jnp.maximum(
                temp, 1e-6)).astype(jnp.int32)
            return jnp.where(temp > 0, sampled, greedy)

        self._step = jax.jit(step)
        self._jnp = jnp
        self._jax = jax

    @classmethod
    def from_artifact(cls, args, params_path: str, **kw):
        """Load a served artifact the way the CLI/launcher does: rebuild
        the bundle from config (model only — no dataset construction),
        params from the msgpack artifact."""
        from ..llm.federated import build_llm_bundle
        bundle, tokenizer = build_llm_bundle(args)
        return cls(bundle, load_model(params_path), tokenizer=tokenizer,
                   **kw)

    # --- generation ---------------------------------------------------------
    def generate(self, prompt: str, max_new_tokens: int = 64,
                 temperature: Optional[float] = None,
                 seed: int = 0) -> Dict[str, Any]:
        from ..llm.data import BOS, EOS, SEP
        jnp = self._jnp
        temp = self.temperature if temperature is None else float(temperature)
        ids = [BOS] + self.tokenizer.encode(prompt) + [SEP]
        ids = ids[: self.max_seq_len - 1]
        n_prompt = len(ids)
        buf = np.zeros((1, self.max_seq_len), np.int32)
        buf[0, :n_prompt] = ids
        buf = jnp.asarray(buf)
        key = self._jax.random.PRNGKey(seed)
        pos = n_prompt
        out_ids: List[int] = []
        finish = "length"
        for _ in range(int(max_new_tokens)):
            if pos >= self.max_seq_len:
                break
            key, sub = self._jax.random.split(key)
            nxt = int(self._step(self.params, buf, jnp.int32(pos),
                                 jnp.float32(temp), sub))
            if nxt == EOS:
                finish = "stop"
                break
            out_ids.append(nxt)
            buf = buf.at[0, pos].set(nxt)
            pos += 1
        return {"text": self.tokenizer.decode(out_ids),
                "finish_reason": finish,
                "prompt_tokens": n_prompt,
                "completion_tokens": len(out_ids)}

    # --- request surfaces ---------------------------------------------------
    def predict(self, request: Any) -> Any:
        """Plain surface: ``{"prompt": str, "max_new_tokens"?,
        "temperature"?}`` → ``{"text": ...}``."""
        out = self.generate(
            str(request.get("prompt", "")),
            max_new_tokens=int(request.get("max_new_tokens", 64)),
            temperature=request.get("temperature"),
            seed=int(request.get("seed", 0)))
        return out

    def chat(self, request: Any) -> Any:
        """OpenAI ``/v1/chat/completions`` schema. The prompt is the
        concatenated user/system turns (the instruction-tuning format the
        federated fine-tune trained on: instruction ++ SEP ++ response)."""
        messages = request.get("messages") or []
        # keep EVERY turn (assistant replies included) — dropping the
        # model's own prior turns would make multi-turn continuations
        # incoherent
        prompt = "\n".join(str(m.get("content", "")) for m in messages
                           if m.get("content"))
        out = self.generate(
            prompt,
            max_new_tokens=int(request.get("max_tokens", 64)),
            temperature=request.get("temperature"),
            seed=int(request.get("seed", 0)))
        return {
            "id": f"chatcmpl-{uuid.uuid4().hex[:24]}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": str(request.get("model", self.bundle.name)),
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": out["text"]},
                "finish_reason": out["finish_reason"],
            }],
            "usage": {
                "prompt_tokens": out["prompt_tokens"],
                "completion_tokens": out["completion_tokens"],
                "total_tokens": out["prompt_tokens"]
                + out["completion_tokens"],
            },
        }


class ChatCompletionRunner(FedMLInferenceRunner):
    """Inference runner with the OpenAI chat route mounted:
    ``POST /v1/chat/completions`` (and ``/predict`` + ``/ready`` from the
    base runner)."""

    def __init__(self, predictor: CausalLMPredictor, host: str = "127.0.0.1",
                 port: int = 0):
        super().__init__(predictor, host=host, port=port,
                         extra_routes={
                             "/v1/chat/completions": predictor.chat})


def serve_chat(args, params_path: str, host: str = "127.0.0.1",
               port: int = 0, block: bool = False) -> ChatCompletionRunner:
    """Two-line path from a federated LoRA artifact to a chat endpoint."""
    predictor = CausalLMPredictor.from_artifact(args, params_path)
    runner = ChatCompletionRunner(predictor, host=host, port=port)
    if block:
        runner.run()
    else:
        runner.start()
    return runner
