"""Simulated device client (Beehive device analogue).

Parity target: the reference's on-device stack — Android service + MobileNN
C++ trainers (``android/fedmlsdk/MobileNN``, ``FedMLClientManager``) driven
over MQTT+S3 file exchange. Here a *device* is a process/thread speaking the
same registration → train-on-file → upload-file protocol over any
transport; its training engine is selectable:

* ``jax``   — the shared jitted local-SGD loop (works for every model);
* ``native`` — the C++ core (:mod:`fedml_tpu.native`, the MobileNN
  analogue) for linear models, exercising a real native train path with
  ctypes in place of JNI.
"""

from __future__ import annotations

import logging
import os
import platform
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.algframe.local_training import run_local_sgd
from ..core.algframe.types import TrainHyper
from ..core.collectives import tree_flatten_to_vector
from ..core.distributed.communication.message import Message
from ..core.distributed.fedml_comm_manager import FedMLCommManager
from ..core.wire import encode_update
from ..serving import load_model, save_model
from ..utils.compression import CommCompressionSpec
from ..utils.paths import confine_path
from .message_define import DeviceMessage

logger = logging.getLogger(__name__)


class DeviceClientManager(FedMLCommManager):
    """One simulated device; ``rank`` doubles as its device id (>= 1)."""

    def __init__(self, args, fed, bundle, spec, optimizer, device_id: int,
                 comm=None, backend: str = "INPROC",
                 engine: Optional[str] = None,
                 eligibility: Optional[dict] = None):
        size = int(getattr(args, "client_num_per_round", 1)) + 1
        super().__init__(args, comm, device_id, size, backend)
        self.fed = fed
        self.bundle = bundle
        self.spec = spec
        self.opt = optimizer
        self.device_id = int(device_id)
        self.engine = (engine or str(getattr(args, "device_engine", "jax"))
                       ).lower()
        # eligibility analogues the registration handshake carries
        # (charging/idle/unmetered); a real device SDK would read the
        # platform battery/network managers — the simulated device reads
        # per-device overrides, then args knobs, defaulting to eligible
        elig = dict(eligibility or {})
        self.eligibility = {
            k: bool(elig.get(k, getattr(args, f"device_{k}", True)))
            for k in ("charging", "idle", "unmetered")}
        self.cache_dir = os.path.expanduser(
            getattr(args, "model_file_cache_dir", None)
            or "~/.cache/fedml_tpu/device_models")
        os.makedirs(self.cache_dir, exist_ok=True)
        self.rng = jax.random.fold_in(
            jax.random.PRNGKey(int(getattr(args, "random_seed", 0)) + 31),
            self.device_id)
        # uplink wire compression (device_wire_compression; off = dense
        # artifacts, byte-identical). The device always has the round's
        # base in hand — the global it just trained from — so only the
        # error-feedback residual persists across rounds.
        method = getattr(args, "device_wire_compression", None)
        self._wire_spec = None
        self._wire_residual: Optional[np.ndarray] = None
        if method:
            self._wire_spec = CommCompressionSpec(
                method=str(method),
                ratio=float(getattr(args, "comm_compression_ratio", 0.1)),
                levels=int(getattr(args, "comm_quantize_levels", 127)))
            self._wire_rng = jax.random.fold_in(
                jax.random.PRNGKey(
                    int(getattr(args, "random_seed", 0)) + 977),
                self.device_id)
        self._train_jit = None
        self._native = None
        if self.engine == "native":
            from .. import native
            if not native.available():
                logger.warning("device %d: native core unavailable, "
                               "falling back to jax engine", self.device_id)
                self.engine = "jax"
            else:
                # trainer chosen by the MODEL's param tree: the CNN engine
                # for DeviceCNN-shaped trees, the linear engine otherwise
                # (reference MobileNN dispatches MNN vs torch engines)
                model = str(getattr(args, "model", "lr")).lower()
                if model in ("device_cnn", "mobile_cnn"):
                    self._native = native.NativeCNNTrainer()
                else:
                    self._native = native.NativeLinearTrainer()

    # --- FSM ---------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            DeviceMessage.MSG_TYPE_S2D_INIT, self.handle_round)
        self.register_message_receive_handler(
            DeviceMessage.MSG_TYPE_S2D_SYNC, self.handle_round)
        self.register_message_receive_handler(
            DeviceMessage.MSG_TYPE_S2D_FINISH, self.handle_finish)

    def run(self) -> None:
        self.register_message_receive_handlers()
        self.send_register()
        self.com_manager.handle_receive_message()

    def send_register(self) -> None:
        msg = Message(DeviceMessage.MSG_TYPE_D2S_REGISTER, self.device_id, 0)
        msg.add_params(DeviceMessage.ARG_DEVICE_ID, self.device_id)
        msg.add_params(DeviceMessage.ARG_DEVICE_OS, platform.system())
        msg.add_params(DeviceMessage.ARG_DEVICE_ENGINE, self.engine)
        msg.add_params(DeviceMessage.ARG_DEVICE_CHARGING,
                       self.eligibility["charging"])
        msg.add_params(DeviceMessage.ARG_DEVICE_IDLE,
                       self.eligibility["idle"])
        msg.add_params(DeviceMessage.ARG_DEVICE_UNMETERED,
                       self.eligibility["unmetered"])
        self.send_message(msg)

    def handle_round(self, msg: Message) -> None:
        # server-supplied fields: confine the path to the shared cache dir
        # (msgpack artifact + confinement = no unpickle / no arbitrary-file
        # read). Drop bad messages instead of raising — an exception here
        # would kill the device's receive loop. TypeError covers missing
        # fields (confine_path(None) / int(None)).
        try:
            round_idx = int(msg.get(DeviceMessage.ARG_ROUND_IDX))
            silo_idx = int(msg.get(DeviceMessage.ARG_DATA_SILO_IDX,
                                   self.device_id - 1))
            params = load_model(confine_path(
                msg.get(DeviceMessage.ARG_MODEL_FILE), self.cache_dir))
        except (TypeError, ValueError, OSError) as e:
            logger.warning("device %d: dropping round message: %s",
                           self.device_id, e)
            return
        cdata = jax.tree_util.tree_map(
            lambda a: a[silo_idx % self.fed.num_clients], self.fed.train)
        eval_acc = None
        if self.engine == "native":
            # on-device eval of the received global model BEFORE training
            # (the MobileNN on-device test path) — reported to the server
            eval_acc = self._eval_native(params, cdata)
            new_params, n, loss = self._train_native(params, cdata,
                                                     round_idx)
        else:
            new_params, n, loss = self._train_jax(params, cdata, round_idx)
        out_path = os.path.join(
            self.cache_dir,
            f"device_{self.device_id}_round_{round_idx}.npk")
        artifact = new_params
        if self._wire_spec is not None:
            enc = encode_update(
                np.asarray(tree_flatten_to_vector(new_params), np.float32),
                base=np.asarray(tree_flatten_to_vector(params), np.float32),
                spec=self._wire_spec, residual=self._wire_residual,
                rng=jax.random.fold_in(self._wire_rng, round_idx),
                msg_type=DeviceMessage.MSG_TYPE_D2S_MODEL)
            self._wire_residual = enc.residual
            artifact = enc.payload
        save_model(artifact, out_path)
        reply = Message(DeviceMessage.MSG_TYPE_D2S_MODEL, self.device_id, 0)
        reply.add_params(DeviceMessage.ARG_DEVICE_ID, self.device_id)
        reply.add_params(DeviceMessage.ARG_MODEL_FILE, out_path)
        reply.add_params(DeviceMessage.ARG_ROUND_IDX, round_idx)
        reply.add_params(DeviceMessage.ARG_NUM_SAMPLES, n)
        reply.add_params(DeviceMessage.ARG_TRAIN_LOSS, loss)
        if eval_acc is not None:
            reply.add_params(DeviceMessage.ARG_DEVICE_EVAL_ACC, eval_acc)
        self.send_message(reply)

    def handle_finish(self, msg: Message) -> None:
        logger.info("device %d finished", self.device_id)
        self.finish()

    # --- engines -----------------------------------------------------------
    def _train_jax(self, params, cdata, round_idx: int):
        if self._train_jit is None:
            def impl(params, cdata, rng, hyper):
                inner = self.opt.make_inner_opt(hyper)
                new_params, _, metrics = run_local_sgd(
                    self.spec, inner, params, cdata, rng, hyper,
                    grad_transform=self.opt.grad_transform,
                    ctx={"global_params": params, "server_state": {},
                         "client_state": {}, "hyper": hyper})
                return new_params, metrics

            self._train_jit = jax.jit(impl)
        hyper = TrainHyper(
            learning_rate=jnp.float32(self.args.learning_rate),
            epochs=int(self.args.epochs), round_idx=jnp.int32(round_idx))
        key = jax.random.fold_in(self.rng, round_idx)
        params = jax.tree_util.tree_map(jnp.asarray, params)
        new_params, metrics = self._train_jit(params, cdata, key, hyper)
        n = float(cdata.num_samples)
        cnt = max(float(metrics["count"]), 1.0)
        return (jax.device_get(new_params), n,
                float(metrics["loss_sum"]) / cnt)

    @staticmethod
    def _flatten_real(cdata):
        x = np.asarray(cdata.x)
        y = np.asarray(cdata.y)
        mask = np.asarray(cdata.mask).reshape(-1) > 0
        return (x.reshape((-1,) + x.shape[2:])[mask],
                y.reshape(-1)[mask].astype(np.int32))

    def _eval_native(self, params, cdata) -> float:
        x, y = self._flatten_real(cdata)
        return float(self._native.evaluate(params, x, y))

    def _train_native(self, params, cdata, round_idx: int):
        # flatten padded batches back to the real sample list
        x, y = self._flatten_real(cdata)
        new_params, loss = self._native.train(
            params, x, y, epochs=int(self.args.epochs),
            batch_size=int(self.args.batch_size),
            lr=float(self.args.learning_rate),
            seed=round_idx * 7919 + self.device_id)
        return new_params, float(len(x)), loss
