"""Cross-device FL server (Beehive analogue).

Parity target: reference ``cross_device/server_mnn/fedml_server_manager.py:14``
(device ONLINE handshake, start-train broadcast, model-file collection,
FINISH) and ``fedml_aggregator.py:17,63`` (reads each device's uploaded
model file, weighted-averages, evaluates the global model server-side).

TPU-native redesign: the *server* is a JAX host — aggregation is a jitted
weighted tree-average and evaluation a jitted batched forward, while the
device side stays file-based (devices upload params artifacts; the wire
message carries the artifact path + sample count). Transport is any
``FedMLCommManager`` backend (in-proc for tests, TCP/gRPC across a LAN/WAN).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Optional

import jax
import numpy as np

from ..core import mlops
from ..core.collectives import tree_flatten_to_vector, vector_to_tree_like
from ..core.distributed.communication.message import Message
from ..core.distributed.fedml_comm_manager import FedMLCommManager
from ..core.wire import decode_update
from ..utils.compression import is_compressed_payload
from ..simulation.sampling import FAST_SAMPLE_MIN_N, sample_ids_streaming
from ..serving import check_model_magic, load_model, save_model
from ..utils.paths import confine_path
from .message_define import DeviceMessage

logger = logging.getLogger(__name__)


class DeviceAggregator:
    """Server state: device model files -> weighted average -> eval
    (reference ``fedml_aggregator.py:63`` reads MNN files and averages)."""

    def __init__(self, args, global_params, eval_fn=None):
        self.args = args
        self.global_params = global_params
        self.eval_fn = eval_fn
        self.client_num = int(getattr(args, "client_num_per_round", 1))
        self._expected = self.client_num
        self.model_files: Dict[int, str] = {}
        self.sample_nums: Dict[int, float] = {}

    def set_round_expected(self, n: int) -> None:
        """Per-round barrier width (cohort assembly over-samples the
        dispatch but closes on the WANTED cohort — Bonawitz pace
        steering: first k reports win, the rest are straggler slack)."""
        self._expected = max(int(n), 1)

    def add_device_result(self, device_id: int, model_file: str,
                          num_samples: float) -> None:
        self.model_files[device_id] = model_file
        self.sample_nums[device_id] = float(num_samples)

    def all_received(self) -> bool:
        return len(self.model_files) >= self._expected

    def aggregate(self):
        # compressed uplinks (device_wire_compression): artifacts are
        # delta blobs vs the round's dispatched global — still this
        # round's ``global_params``, which aggregate() only replaces at
        # the end. Flatten that base once, lazily.
        base_vec = None
        loaded = []
        for did, path in sorted(self.model_files.items()):
            try:
                # artifacts were magic-validated at receive time; a file
                # that still fails here (deleted/truncated in between) is
                # skipped, never fatal to the round-closing thread
                params = load_model(path)
                if is_compressed_payload(params):
                    if base_vec is None:
                        base_vec = np.asarray(
                            tree_flatten_to_vector(self.global_params),
                            np.float32)
                    params = vector_to_tree_like(
                        decode_update(params, base=base_vec),
                        self.global_params)
                loaded.append((self.sample_nums[did], params))
            except (ValueError, OSError) as e:
                logger.warning("aggregate: skipping device %d: %s", did, e)
        self.model_files.clear()
        self.sample_nums.clear()
        if not loaded:  # dead round: keep the previous global
            return self.global_params
        total = sum(n for n, _ in loaded) or 1.0
        acc = None
        for n, params in loaded:
            w = n / total
            scaled = jax.tree_util.tree_map(
                lambda a: np.asarray(a, np.float32) * w, params)
            acc = scaled if acc is None else jax.tree_util.tree_map(
                np.add, acc, scaled)
        self.global_params = acc
        return self.global_params

    def test_on_server(self) -> Optional[Dict[str, float]]:
        if self.eval_fn is None:
            return None
        return self.eval_fn(self.global_params)


class DeviceServerManager(FedMLCommManager):
    """Rank 0; devices register with their own ids (ranks 1..N)."""

    def __init__(self, args, aggregator: DeviceAggregator, comm=None,
                 rank: int = 0, size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.aggregator = aggregator
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        self.expected_devices = int(getattr(args, "client_num_per_round", 1))
        self.devices_online: Dict[int, Dict] = {}
        self.is_initialized = False
        self.cache_dir = os.path.expanduser(
            getattr(args, "model_file_cache_dir", None)
            or "~/.cache/fedml_tpu/device_models")
        os.makedirs(self.cache_dir, exist_ok=True)
        self.history = []
        self.result: Optional[dict] = None
        self._lock = threading.Lock()
        # elastic rounds (mirrors the cross-silo server): a dead device
        # must not stall the all-received barrier forever
        self.round_timeout_s = float(getattr(args, "round_timeout_s", 0)
                                     or 0)
        self._timer: Optional[threading.Timer] = None
        self._timer_gen = 0
        # guards the timer-vs-last-arrival race: set under the lock when a
        # round's collection closes, so a timer thread that was already
        # blocked on the lock bails instead of double-advancing
        self._round_closed = False
        # did -> on-device accuracy of the round's global model (native
        # devices report it; cleared per round)
        self._device_accs: dict = {}
        # --- streaming cohort assembly (cohort_assembly knob; off =
        # every online device trains every round, the legacy behavior).
        # Population-plane pieces: sparse-capable stats store over device
        # ids, handshake eligibility predicates, chunked top-k assembler,
        # and Oort's deadline pacer driving the straggler timer + the
        # over-sampled dispatch width.
        self.cohort_enabled = bool(getattr(args, "cohort_assembly", False))
        self.stats = None
        self.assembler = None
        self.pacer = None
        self._cohort: list = []
        self._barrier = self.expected_devices
        self._dispatch_ts = 0.0
        if self.cohort_enabled:
            from ..core.selection import (DeadlinePacer,
                                          StreamingCohortAssembler,
                                          make_stats_store,
                                          required_eligibility)
            # +1: device ids are 1-based ranks
            population = max(int(getattr(args, "client_num_in_total",
                                         self.expected_devices)),
                             self.expected_devices) + 1
            self.stats = make_stats_store(args, population)
            self.assembler = StreamingCohortAssembler(args, self.stats,
                                                      population)
            self.pacer = DeadlinePacer.from_args(args)
            self.required_elig = required_eligibility(args)
            self.cohort_k = int(getattr(args, "cohort_size", 0) or 0) \
                or self.expected_devices
        self._round_k = getattr(self, "cohort_k", self.expected_devices)
        self._round_utility = 0.0
        # per-round (round_idx, cohort) trail — restart-and-resume tests
        # assert a restarted server replays these identically
        self.cohort_log: list = []
        # --- durable fleet plane (fleet_registry knob; off = the
        # in-memory single-tenant path above, bit-identical). The sqlite
        # registry remembers every device across restarts, the fairness
        # tables arbitrate concurrent tasks sharing the file, and the
        # checkpointed stats/pacer posture makes a restarted server
        # resume the learned fleet posture instead of re-learning it.
        self.fleet = None
        self.fleet_task = str(getattr(args, "fleet_task_id", "") or "train")
        reg_path = getattr(args, "fleet_registry", None)
        if reg_path:
            from ..core.fleet import DeviceRegistry
            self.fleet = DeviceRegistry(str(reg_path))
            self.fleet_cap = int(getattr(args,
                                         "fleet_max_rounds_per_window", 0)
                                 or 0)
            self.fleet_window_s = float(getattr(
                args, "fleet_fairness_window_s", 3600.0) or 3600.0)
            if self.cohort_enabled:
                self._fleet_restore()

    # --- durable fleet plane -----------------------------------------------
    def _fleet_restore(self) -> None:
        """Resume the persisted control-plane posture: the fleet-wide
        stats snapshot, this task's pacer, and its round cursor. A fresh
        registry has none of them — start cold, exactly like fleet-off."""
        st = self.fleet.load_state("fleet:stats")
        if st is not None:
            try:
                self.stats.load_state_dict(st)
            except ValueError as e:
                logger.warning("fleet: persisted stats incompatible with "
                               "this population (%s) — resuming cold", e)
        pst = self.fleet.load_state(f"fleet:pacer:{self.fleet_task}")
        if pst is not None:
            self.pacer.load_state_dict(pst)
        sst = self.fleet.load_state(f"fleet:server:{self.fleet_task}")
        if sst is not None:
            self.round_idx = int(sst["round_idx"])
            if "model" in sst:
                from ..core.distributed.communication.message import \
                    loads_tree
                self.aggregator.global_params = loads_tree(
                    sst["model"].tobytes())
            logger.info(
                "fleet: task %r resumes at round %d (%d devices "
                "remembered)", self.fleet_task, self.round_idx,
                self.fleet.device_count())

    def _fleet_save(self) -> None:
        """Checkpoint the control plane after every closed round — a
        crash between rounds restarts into the NEXT round with the
        learned posture AND the aggregated global model intact (the
        model rides along as a wire-codec blob, never pickle)."""
        from ..core.distributed.communication.message import dumps_tree
        self.fleet.save_state("fleet:stats", self.stats.state_dict())
        self.fleet.save_state(f"fleet:pacer:{self.fleet_task}",
                              self.pacer.state_dict())
        blob = np.frombuffer(dumps_tree(self.aggregator.global_params),
                             dtype=np.uint8)
        self.fleet.save_state(f"fleet:server:{self.fleet_task}",
                              {"round_idx": np.int64(self.round_idx),
                               "model": blob})

    # --- FSM ---------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            DeviceMessage.MSG_TYPE_D2S_REGISTER, self.handle_register)
        self.register_message_receive_handler(
            DeviceMessage.MSG_TYPE_D2S_MODEL, self.handle_device_model)

    def handle_register(self, msg: Message) -> None:
        # idempotent: a device re-registering under the same id (network
        # flap, app restart) refreshes its eligibility in place — the
        # online-table slot is keyed by id, the registry write is an
        # UPSERT preserving first_seen/participation, and the
        # is_initialized guard below keeps a re-register from dispatching
        # a second session. Its stats history is keyed by id in the
        # stats store and is never touched here.
        did = int(msg.get(DeviceMessage.ARG_DEVICE_ID))
        self.devices_online[did] = {
            "os": msg.get(DeviceMessage.ARG_DEVICE_OS, "?"),
            "engine": msg.get(DeviceMessage.ARG_DEVICE_ENGINE, "?"),
            # eligibility analogues (absent = True: a device that
            # predates the handshake fields stays schedulable)
            "charging": bool(msg.get(DeviceMessage.ARG_DEVICE_CHARGING,
                                     True)),
            "idle": bool(msg.get(DeviceMessage.ARG_DEVICE_IDLE, True)),
            "unmetered": bool(msg.get(DeviceMessage.ARG_DEVICE_UNMETERED,
                                      True)),
        }
        if self.fleet is not None:
            self.fleet.register(did, self.devices_online[did])
        logger.info("server: device %d online (%s/%s), %d/%d", did,
                    self.devices_online[did]["os"],
                    self.devices_online[did]["engine"],
                    len(self.devices_online), self.expected_devices)
        if (len(self.devices_online) >= self.expected_devices
                and not self.is_initialized):
            self.is_initialized = True
            if self.round_idx >= self.round_num:
                # fleet-resumed past the final round: the session this
                # registry remembers already completed
                logger.info("fleet: session already complete at round %d",
                            self.round_idx)
                self.finish_session()
                return
            mlops.log_aggregation_status("RUNNING")
            self._dispatch_round(DeviceMessage.MSG_TYPE_S2D_INIT)

    def _global_model_file(self) -> str:
        path = os.path.join(self.cache_dir,
                            f"global_round_{self.round_idx}.npk")
        save_model(self.aggregator.global_params, path)
        return path

    def _round_cohort(self) -> list:
        """The devices this round trains: every online device (legacy),
        or the streaming-assembled cohort — eligibility predicates over
        the handshake metadata, utility scoring from observed history,
        pacer-over-sampled dispatch width."""
        online = sorted(self.devices_online)
        if not self.cohort_enabled:
            return online
        from ..core import mlops
        from ..core.obs import metrics as obs_metrics
        from ..core.selection.cohort import eligible_mask
        k = self.pacer.paced_cohort(self.cohort_k)
        self._round_k = k
        target = self.pacer.target_cohort(k, ceiling=len(online))
        ids = np.asarray(online, np.int64)
        metas = [self.devices_online[d] for d in online]
        mask = eligible_mask(metas, self.required_elig)

        def elig(chunk: np.ndarray) -> np.ndarray:
            # the online table is one in-memory chunk here; the fleet
            # path below pages the persistent registry instead
            pos = np.searchsorted(ids, chunk)
            return mask[pos]

        candidates = [ids]
        eligible_fn = elig
        if self.fleet is not None:
            # registry-backed candidates: page every device the fleet
            # has EVER heard from (chunked — the population is never
            # materialized), sieved by liveness (must be online right
            # now to receive a dispatch), the handshake predicate, and
            # the trailing-window fairness cap
            candidates = self.fleet.iter_id_chunks(self.assembler.chunk)

            def fleet_elig(chunk: np.ndarray) -> np.ndarray:
                pos = np.searchsorted(ids, chunk)
                pos = np.minimum(pos, max(len(ids) - 1, 0))
                m = (len(ids) > 0) & (ids[pos] == chunk) & mask[pos]
                if self.fleet_cap and m.any():
                    counts = self.fleet.participation_counts(
                        chunk[m], self.fleet_window_s)
                    keep = counts < self.fleet_cap
                    m[np.flatnonzero(m)] = keep
                return m

            eligible_fn = fleet_elig
        res = self.assembler.assemble(
            self.round_idx, target, candidates, eligible_fn=eligible_fn,
            deadline_s=self.pacer.deadline_s,
            over_sample=self.pacer.over_sample)
        cohort = sorted(res.cohort)
        self._round_utility = (float(np.sum(res.scores))
                               if res.scores is not None
                               and len(res.scores) else 0.0)
        if self.fleet is not None and cohort:
            # atomic multi-tenant arbitration: a concurrent task sharing
            # the registry cannot co-schedule a device this round
            granted, busy, capped = self.fleet.claim(
                self.fleet_task, cohort, self.round_idx,
                cap=self.fleet_cap, window_s=self.fleet_window_s)
            obs_metrics.record_fleet_round(self.fleet_task, len(granted),
                                           busy, capped)
            if busy or capped:
                logger.info(
                    "fleet round %d: %d denied busy, %d denied by the "
                    "participation cap", self.round_idx, busy, capped)
            cohort = sorted(granted)
        if not cohort:
            if self.fleet is not None:
                # fairness denials are binding — never bulldoze the cap
                # by falling back to the whole online table; the dead-
                # round leash closes this round and the next one retries
                logger.warning(
                    "fleet round %d: no claimable device — empty round",
                    self.round_idx)
                self.cohort_log.append((self.round_idx, []))
                return []
            logger.warning(
                "cohort assembly round %d: no eligible device of %d "
                "online — dispatching to every online device",
                self.round_idx, len(online))
            cohort = online
        self.stats.record_selected(self.round_idx, cohort)
        mlops.log_selection(
            round_idx=self.round_idx, strategy="cohort",
            sampled=cohort, excluded=[],
            target_n=target,
            dropout_posterior=round(
                self.stats.population_dropout_mean(), 5))
        logger.info(
            "cohort round %d: %d/%d online eligible, dispatching %d "
            "(deadline %.1fs, over-sample %.2f, assembly %.2fms)",
            self.round_idx, res.eligible, len(online), len(cohort),
            self.pacer.deadline_s, self.pacer.over_sample, res.wall_ms)
        self.cohort_log.append((self.round_idx, list(cohort)))
        return cohort

    def _round_deadline_s(self) -> float:
        """Straggler budget for the CURRENT round: the pacer's live
        deadline under cohort assembly, else the static knob."""
        if self.cohort_enabled:
            return float(self.pacer.deadline_s)
        return self.round_timeout_s

    def _dispatch_round(self, msg_type: str) -> None:
        """Write the global artifact once, point every cohort device at
        it (reference start_train JSON with the global model S3 path)."""
        path = self._global_model_file()
        cohort = self._round_cohort()
        with self._lock:
            self._round_closed = False
            self._cohort = list(cohort)
            # cohort mode: the barrier closes on the WANTED k (the
            # pacer-scaled live k, when cohort adaptation is on), not
            # the over-sampled dispatch width — first k reports win
            self._barrier = (min(self._round_k, len(cohort))
                             if self.cohort_enabled
                             else self.aggregator.client_num)
            self.aggregator.set_round_expected(self._barrier)
        self._dispatch_ts = time.time()
        # dead-round leash: if NO device ever reports this round (all
        # crashed post-registration), the tight first-arrival timer in
        # handle_device_model never arms and the round would hang forever.
        # Arm a generous 3x leash now; the first arrival swaps it for the
        # tight straggler timer (mirrors SecAggServerManager._start_round).
        deadline = self._round_deadline_s()
        if deadline > 0:
            self._arm_timer(3.0 * deadline)
        n_total = int(getattr(self.args, "client_num_in_total",
                              self.expected_devices))
        if n_total <= len(cohort):
            silos = np.arange(len(cohort))
        elif n_total >= FAST_SAMPLE_MIN_N:
            # population-scale silo draw: O(cohort) via the streaming
            # sampler instead of RandomState.choice's [n_total]
            # permutation (still a pure function of the round index)
            silos = sample_ids_streaming(
                np.random.default_rng((1000, self.round_idx)),
                n_total, len(cohort))
        else:
            rs = np.random.RandomState(1000 + self.round_idx)
            silos = rs.choice(n_total, len(cohort), replace=False)
        for i, did in enumerate(cohort):
            msg = Message(msg_type, self.rank, did)
            msg.add_params(DeviceMessage.ARG_MODEL_FILE, path)
            msg.add_params(DeviceMessage.ARG_ROUND_IDX, self.round_idx)
            msg.add_params(DeviceMessage.ARG_DATA_SILO_IDX, int(silos[i]))
            self.send_message(msg)

    def _arm_timer(self, seconds: float) -> None:
        """(Re-)arm the round timer. ``Timer.cancel()`` is a no-op once the
        callback has started, so a leash timer that already fired and is
        blocked on the lock cannot be cancelled — the generation counter
        lets such a stale callback recognize it was superseded (e.g. by the
        tight straggler timer) and bail instead of closing the round."""
        if self._timer is not None:
            self._timer.cancel()
        self._timer_gen += 1
        this_round, this_gen = self.round_idx, self._timer_gen
        self._timer = threading.Timer(
            seconds, lambda: self._on_round_timeout(this_round, this_gen))
        self._timer.daemon = True
        self._timer.start()

    def handle_device_model(self, msg: Message) -> None:
        did = int(msg.get(DeviceMessage.ARG_DEVICE_ID))
        # peer-supplied fields: a bad message is dropped, not raised — a
        # handler exception would kill the receive loop (one malicious peer
        # must not take the server down). TypeError covers a missing path
        # (confine_path(None)); ValueError covers escape attempts, a bad
        # magic, and non-numeric round indices.
        try:
            path = confine_path(msg.get(DeviceMessage.ARG_MODEL_FILE),
                                self.cache_dir)
            # validate the artifact NOW (existence + magic header only —
            # aggregate() does the full parse once), not at aggregate()
            # time where a failure would crash the round-closing thread
            check_model_magic(path)
            msg_round = int(msg.get(DeviceMessage.ARG_ROUND_IDX,
                                    self.round_idx))
        except (TypeError, ValueError, OSError) as e:
            logger.warning("server: dropping model from device %d: %s",
                           did, e)
            return
        with self._lock:
            # a straggler's model for an already-closed round must not
            # fold into the current round (same stale-round rule as the
            # FA server). _round_closed covers the window where the timer
            # closed the round but round_idx has not advanced yet.
            if self._round_closed or msg_round != self.round_idx:
                logger.warning(
                    "server: dropping stale round model from device %d",
                    did)
                return
            self.aggregator.add_device_result(
                did, path,
                float(msg.get(DeviceMessage.ARG_NUM_SAMPLES, 1.0)))
            if self.cohort_enabled and self._dispatch_ts > 0:
                # dispatch→upload wall clock: the utility scorer's
                # system-latency signal and the pacer's raw material
                self.stats.record_latency(did,
                                          time.time() - self._dispatch_ts)
            acc = msg.get(DeviceMessage.ARG_DEVICE_EVAL_ACC)
            if acc is not None:  # on-device eval of the global model
                self._device_accs[did] = float(acc)
            if not self.aggregator.all_received():
                deadline = self._round_deadline_s()
                if (deadline > 0
                        and len(self.aggregator.model_files) == 1):
                    # first arrival: swap the dead-round leash for the
                    # tight straggler timeout
                    self._arm_timer(deadline)
                return
            self._finish_collect_locked()
        self._advance_round()

    def _on_round_timeout(self, armed_round: int, armed_gen: int) -> None:
        with self._lock:
            if (self.round_idx != armed_round or self._round_closed
                    or self._timer_gen != armed_gen):
                return  # round completed or timer re-armed in the meantime
            n = len(self.aggregator.model_files)
            logger.warning(
                "device server round %d: timeout with %d/%d device models "
                "— %s", self.round_idx, n, self.aggregator.client_num,
                "aggregating the devices that reported" if n
                else "no device reported; keeping the previous global model")
            self._finish_collect_locked()
        self._advance_round()

    def _finish_collect_locked(self) -> None:
        self._round_closed = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self.cohort_enabled and self._cohort:
            # the round's outcome feeds the control plane BEFORE
            # aggregate() clears the report table: availability evidence
            # per dispatched device (reported vs not — the Beta dropout
            # posterior), then the pacer's deadline/over-sample step
            reported = set(self.aggregator.model_files)
            for did in self._cohort:
                self.stats.record_availability(did,
                                               participated=did in reported)
            # the pacer measures delivery against the round's BARRIER
            # (the wanted k), not the over-sampled dispatch width — a
            # round that closed on k early reports is a SUCCESS even
            # though the straggler slack never reported (Bonawitz pace
            # steering: the slack exists to be discarded)
            self.pacer.observe_round(
                completed=len(reported),
                expected=self._barrier,
                wall_s=max(time.time() - self._dispatch_ts, 0.0))
            # cohort-size adaptation (pacer_adapt_cohort; no-op off):
            # the assembled cohort's aggregate utility is the
            # saturation signal that grows/shrinks the live k
            self.pacer.observe_utility(self._round_utility)
            if self.fleet is not None:
                # close the fleet round: claims released, participation
                # recorded for the devices that actually served,
                # last_heard refreshed
                served = sorted(reported)
                self.fleet.release(self.fleet_task, self.round_idx,
                                   served)
                if served:
                    self.fleet.touch(served)
        self.aggregator.aggregate()

    def _advance_round(self) -> None:
        stats = self.aggregator.test_on_server()
        rec = {"round": self.round_idx}
        if stats:
            rec.update(stats)
            logger.info("server round %d: %s", self.round_idx, stats)
        if self._device_accs:  # on-device evals of this round's global
            rec["device_eval_acc"] = (sum(self._device_accs.values())
                                      / len(self._device_accs))
            rec["device_eval_count"] = len(self._device_accs)
            self._device_accs = {}
        self.history.append(rec)
        mlops.log_round_info(self.round_num, self.round_idx)
        self.round_idx += 1
        if self.fleet is not None and self.cohort_enabled:
            self._fleet_save()
        if self.round_idx >= self.round_num:
            self.finish_session()
            return
        self._dispatch_round(DeviceMessage.MSG_TYPE_S2D_SYNC)

    def finish_session(self) -> None:
        for did in sorted(self.devices_online):
            self.send_message(Message(DeviceMessage.MSG_TYPE_S2D_FINISH,
                                      self.rank, did))
        last_eval = next((r for r in reversed(self.history)
                          if "test_acc" in r), {})
        self.result = {"params": self.aggregator.global_params,
                       "history": self.history,
                       "final_test_acc": last_eval.get("test_acc"),
                       "rounds": self.round_num}
        mlops.log_aggregation_status("FINISHED")
        self.finish()
