"""Cross-device FL server (Beehive analogue).

Parity target: reference ``cross_device/server_mnn/fedml_server_manager.py:14``
(device ONLINE handshake, start-train broadcast, model-file collection,
FINISH) and ``fedml_aggregator.py:17,63`` (reads each device's uploaded
model file, weighted-averages, evaluates the global model server-side).

TPU-native redesign: the *server* is a JAX host — aggregation is a jitted
weighted tree-average and evaluation a jitted batched forward, while the
device side stays file-based (devices upload params artifacts; the wire
message carries the artifact path + sample count). Transport is any
``FedMLCommManager`` backend (in-proc for tests, TCP/gRPC across a LAN/WAN).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional

import jax
import numpy as np

from ..core import mlops
from ..core.distributed.communication.message import Message
from ..core.distributed.fedml_comm_manager import FedMLCommManager
from ..serving import load_model, save_model
from .message_define import DeviceMessage

logger = logging.getLogger(__name__)


class DeviceAggregator:
    """Server state: device model files -> weighted average -> eval
    (reference ``fedml_aggregator.py:63`` reads MNN files and averages)."""

    def __init__(self, args, global_params, eval_fn=None):
        self.args = args
        self.global_params = global_params
        self.eval_fn = eval_fn
        self.client_num = int(getattr(args, "client_num_per_round", 1))
        self.model_files: Dict[int, str] = {}
        self.sample_nums: Dict[int, float] = {}

    def add_device_result(self, device_id: int, model_file: str,
                          num_samples: float) -> None:
        self.model_files[device_id] = model_file
        self.sample_nums[device_id] = float(num_samples)

    def all_received(self) -> bool:
        return len(self.model_files) >= self.client_num

    def aggregate(self):
        total = sum(self.sample_nums.values()) or 1.0
        acc = None
        for did, path in sorted(self.model_files.items()):
            params = load_model(path)
            w = self.sample_nums[did] / total
            scaled = jax.tree_util.tree_map(
                lambda a: np.asarray(a, np.float32) * w, params)
            acc = scaled if acc is None else jax.tree_util.tree_map(
                np.add, acc, scaled)
        self.global_params = jax.tree_util.tree_map(
            lambda a: np.asarray(a, np.float32), acc)
        self.model_files.clear()
        self.sample_nums.clear()
        return self.global_params

    def test_on_server(self) -> Optional[Dict[str, float]]:
        if self.eval_fn is None:
            return None
        return self.eval_fn(self.global_params)


class DeviceServerManager(FedMLCommManager):
    """Rank 0; devices register with their own ids (ranks 1..N)."""

    def __init__(self, args, aggregator: DeviceAggregator, comm=None,
                 rank: int = 0, size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.aggregator = aggregator
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        self.expected_devices = int(getattr(args, "client_num_per_round", 1))
        self.devices_online: Dict[int, Dict] = {}
        self.is_initialized = False
        self.cache_dir = os.path.expanduser(
            getattr(args, "model_file_cache_dir", None)
            or "~/.cache/fedml_tpu/device_models")
        os.makedirs(self.cache_dir, exist_ok=True)
        self.history = []
        self.result: Optional[dict] = None
        self._lock = threading.Lock()
        # elastic rounds (mirrors the cross-silo server): a dead device
        # must not stall the all-received barrier forever
        self.round_timeout_s = float(getattr(args, "round_timeout_s", 0)
                                     or 0)
        self._timer: Optional[threading.Timer] = None

    # --- FSM ---------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            DeviceMessage.MSG_TYPE_D2S_REGISTER, self.handle_register)
        self.register_message_receive_handler(
            DeviceMessage.MSG_TYPE_D2S_MODEL, self.handle_device_model)

    def handle_register(self, msg: Message) -> None:
        did = int(msg.get(DeviceMessage.ARG_DEVICE_ID))
        self.devices_online[did] = {
            "os": msg.get(DeviceMessage.ARG_DEVICE_OS, "?"),
            "engine": msg.get(DeviceMessage.ARG_DEVICE_ENGINE, "?"),
        }
        logger.info("server: device %d online (%s/%s), %d/%d", did,
                    self.devices_online[did]["os"],
                    self.devices_online[did]["engine"],
                    len(self.devices_online), self.expected_devices)
        if (len(self.devices_online) >= self.expected_devices
                and not self.is_initialized):
            self.is_initialized = True
            mlops.log_aggregation_status("RUNNING")
            self._dispatch_round(DeviceMessage.MSG_TYPE_S2D_INIT)

    def _global_model_file(self) -> str:
        path = os.path.join(self.cache_dir,
                            f"global_round_{self.round_idx}.pkl")
        save_model(self.aggregator.global_params, path)
        return path

    def _dispatch_round(self, msg_type: str) -> None:
        """Write the global artifact once, point every device at it
        (reference start_train JSON with the global model S3 path)."""
        path = self._global_model_file()
        n_total = int(getattr(self.args, "client_num_in_total",
                              self.expected_devices))
        rs = np.random.RandomState(1000 + self.round_idx)
        silos = (np.arange(len(self.devices_online))
                 if n_total <= len(self.devices_online)
                 else rs.choice(n_total, len(self.devices_online),
                                replace=False))
        for i, did in enumerate(sorted(self.devices_online)):
            msg = Message(msg_type, self.rank, did)
            msg.add_params(DeviceMessage.ARG_MODEL_FILE, path)
            msg.add_params(DeviceMessage.ARG_ROUND_IDX, self.round_idx)
            msg.add_params(DeviceMessage.ARG_DATA_SILO_IDX, int(silos[i]))
            self.send_message(msg)

    def handle_device_model(self, msg: Message) -> None:
        did = int(msg.get(DeviceMessage.ARG_DEVICE_ID))
        with self._lock:
            self.aggregator.add_device_result(
                did, msg.get(DeviceMessage.ARG_MODEL_FILE),
                float(msg.get(DeviceMessage.ARG_NUM_SAMPLES, 1.0)))
            if not self.aggregator.all_received():
                if (self.round_timeout_s > 0
                        and len(self.aggregator.model_files) == 1):
                    this_round = self.round_idx
                    self._timer = threading.Timer(
                        self.round_timeout_s,
                        lambda: self._on_round_timeout(this_round))
                    self._timer.daemon = True
                    self._timer.start()
                return
            self._finish_collect_locked()
        self._advance_round()

    def _on_round_timeout(self, armed_round: int) -> None:
        with self._lock:
            if (self.round_idx != armed_round
                    or not self.aggregator.model_files):
                return  # round completed normally in the meantime
            logger.warning(
                "device server round %d: timeout with %d/%d device models "
                "— aggregating the devices that reported", self.round_idx,
                len(self.aggregator.model_files),
                self.aggregator.client_num)
            self._finish_collect_locked()
        self._advance_round()

    def _finish_collect_locked(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self.aggregator.aggregate()

    def _advance_round(self) -> None:
        stats = self.aggregator.test_on_server()
        rec = {"round": self.round_idx}
        if stats:
            rec.update(stats)
            logger.info("server round %d: %s", self.round_idx, stats)
        self.history.append(rec)
        mlops.log_round_info(self.round_num, self.round_idx)
        self.round_idx += 1
        if self.round_idx >= self.round_num:
            self.finish_session()
            return
        self._dispatch_round(DeviceMessage.MSG_TYPE_S2D_SYNC)

    def finish_session(self) -> None:
        for did in sorted(self.devices_online):
            self.send_message(Message(DeviceMessage.MSG_TYPE_S2D_FINISH,
                                      self.rank, did))
        last_eval = next((r for r in reversed(self.history)
                          if "test_acc" in r), {})
        self.result = {"params": self.aggregator.global_params,
                       "history": self.history,
                       "final_test_acc": last_eval.get("test_acc"),
                       "rounds": self.round_num}
        mlops.log_aggregation_status("FINISHED")
        self.finish()
