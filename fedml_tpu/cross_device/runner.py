"""Cross-device runner: builds the ServerMNN-analogue side or a simulated
device per ``args.role``, plus the in-proc session helper used by tests
(reference ``launch_cross_device.py`` ``run_mnn_server``)."""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax

from ..core.algframe.client_trainer import make_trainer_spec
from ..cross_silo.horizontal.runner import _make_eval_fn
from ..optimizers.registry import create_optimizer
from .client import DeviceClientManager
from .server import DeviceAggregator, DeviceServerManager


def build_device_server(args, fed, bundle, backend: Optional[str] = None):
    spec = make_trainer_spec(fed, bundle)
    rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
    init_rng, _ = jax.random.split(rng)
    global_params = jax.device_get(bundle.init(init_rng, fed.train.x[0, 0]))
    aggregator = DeviceAggregator(args, global_params,
                                  eval_fn=_make_eval_fn(spec, fed))
    size = int(getattr(args, "client_num_per_round", 1)) + 1
    return DeviceServerManager(args, aggregator, rank=0, size=size,
                               backend=backend or _backend(args))


def build_device_client(args, fed, bundle, device_id: int,
                        backend: Optional[str] = None,
                        engine: Optional[str] = None,
                        eligibility: Optional[dict] = None):
    spec = make_trainer_spec(fed, bundle)
    optimizer = create_optimizer(args, spec)
    return DeviceClientManager(args, fed, bundle, spec, optimizer,
                               device_id, backend=backend or _backend(args),
                               engine=engine, eligibility=eligibility)


def _backend(args) -> str:
    b = str(getattr(args, "backend", "") or "").upper()
    return b if b in ("INPROC", "TCP", "GRPC") else "TCP"


class CrossDeviceRunner:
    """``args.role`` == 'server' runs the MNN-server analogue; anything else
    runs one simulated device (``args.rank`` = device id)."""

    def __init__(self, args, dataset, model):
        role = str(getattr(args, "role", "server")).lower()
        if role == "server":
            self.manager = build_device_server(args, dataset, model)
        else:
            self.manager = build_device_client(
                args, dataset, model, max(int(getattr(args, "rank", 1)), 1))

    def run(self, comm_round=None) -> Any:
        self.manager.run()
        return getattr(self.manager, "result", None)


def build_cross_device_runner(args, dataset, model):
    return CrossDeviceRunner(args, dataset, model)


def run_cross_device_inproc(args, fed, bundle,
                            engines: Optional[list] = None,
                            eligibility: Optional[list] = None
                            ) -> Dict[str, Any]:
    """Server + N simulated devices as threads over the in-proc broker —
    the cross-device 'multi-node without a cluster' test mode.
    ``eligibility`` (optional, per device): charging/idle/unmetered
    handshake overrides the cohort-assembly predicates read."""
    from ..cross_silo import run_inproc_session
    n = int(getattr(args, "client_num_per_round", 2))
    engs = engines or [None] * n
    eligs = eligibility or [None] * n
    return run_inproc_session(args, lambda: [
        build_device_server(args, fed, bundle, backend="INPROC"),
        *[build_device_client(args, fed, bundle, device_id=i + 1,
                              backend="INPROC", engine=engs[i],
                              eligibility=eligs[i])
          for i in range(n)]], join_timeout_s=30.0)
