"""Cross-device protocol message vocabulary.

Parity target: reference ``cross_device/server_mnn/message_define.py`` (the
MNN server speaks start-train JSON + model-file messages to phones). Keys
are file-payload centric: model parameters travel as *artifact files* on a
shared medium (the cross-device analogue of the reference's S3+MNN file
exchange), messages carry paths + metadata."""


class DeviceMessage:
    # device -> server
    MSG_TYPE_D2S_REGISTER = "d2s_register"
    MSG_TYPE_D2S_MODEL = "d2s_model"
    # server -> device
    MSG_TYPE_S2D_INIT = "s2d_init"
    MSG_TYPE_S2D_SYNC = "s2d_sync"
    MSG_TYPE_S2D_FINISH = "s2d_finish"

    ARG_DEVICE_ID = "device_id"
    ARG_DEVICE_OS = "device_os"
    ARG_DEVICE_ENGINE = "device_engine"
    # eligibility analogues on the registration handshake (Bonawitz
    # MLSys'19 §2: phones check in when charging + idle + on an unmetered
    # network; the server's cohort assembly filters on them). Absent
    # fields read as True — a device that predates the fields stays
    # schedulable.
    ARG_DEVICE_CHARGING = "device_charging"
    ARG_DEVICE_IDLE = "device_idle"
    ARG_DEVICE_UNMETERED = "device_unmetered"
    ARG_MODEL_FILE = "model_file"
    ARG_ROUND_IDX = "round_idx"
    ARG_DATA_SILO_IDX = "data_silo_idx"
    ARG_NUM_SAMPLES = "num_samples"
    ARG_TRAIN_LOSS = "train_loss"
    # on-device eval of the received GLOBAL model on the device's shard
    # (reference: MobileNN's on-device test path; reported natively)
    ARG_DEVICE_EVAL_ACC = "device_eval_acc"

    STATUS_ONLINE = "ONLINE"
    STATUS_FINISHED = "FINISHED"
