"""Cross-device FL (Beehive analogue): a Python server speaking a
file-payload device protocol + simulated device clients whose training
engine is JAX or the native C++ core (:mod:`fedml_tpu.native`).

Reference surface covered: ``cross_device/server_mnn/`` (server manager +
aggregator reading uploaded device model files), the device protocol
(registration/ONLINE handshake, per-round model-file exchange, FINISH), and
the native on-device trainer story (``android/fedmlsdk/MobileNN``) via the
ctypes-bridged C++ core.
"""

from .client import DeviceClientManager  # noqa: F401
from .message_define import DeviceMessage  # noqa: F401
from .runner import (build_cross_device_runner,  # noqa: F401
                     build_device_client, build_device_server,
                     run_cross_device_inproc)
from .server import DeviceAggregator, DeviceServerManager  # noqa: F401
