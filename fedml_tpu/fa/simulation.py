"""FA simulation runner (reference ``fa/simulation/sp/simulator.py`` +
``fa/fa_runner.py``): rounds of client sampling -> local_analyze ->
aggregate, over per-client raw-data lists."""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Sequence

import numpy as np

from ..simulation.sampling import (client_sampling,
                                   sampling_stream_from_args)

logger = logging.getLogger(__name__)


class FASimulator:
    def __init__(self, args, client_datas: Sequence[Sequence],
                 client_analyzer, server_aggregator):
        self.args = args
        self.client_datas = list(client_datas)
        self.analyzer = client_analyzer
        self.aggregator = server_aggregator
        self.history: List[Any] = []

    def run(self, comm_round=None) -> Dict[str, Any]:
        rounds = int(comm_round if comm_round is not None
                     else getattr(self.args, "comm_round", 1))
        per_round = int(getattr(self.args, "client_num_per_round",
                                len(self.client_datas)))
        for round_idx in range(rounds):
            sampled = client_sampling(
                round_idx, len(self.client_datas), per_round,
                random_seed=int(getattr(self.args, "random_seed", 0) or 0),
                stream=sampling_stream_from_args(self.args))
            init_msg = self.aggregator.get_init_msg()
            submissions = []
            for cid in sampled:
                self.analyzer.set_init_msg(init_msg)
                submissions.append(
                    self.analyzer.local_analyze(self.client_datas[cid],
                                                self.args))
            result = self.aggregator.aggregate(submissions)
            self.history.append(result)
            logger.info("fa round %d: %s", round_idx, _brief(result))
        return {"result": self.aggregator.get_server_data(),
                "history": self.history, "rounds": rounds}


def _brief(x, n=80):
    s = repr(x)
    return s if len(s) <= n else s[:n] + "..."
