"""Local analyzers + server aggregators for each FA task.

Parity target: reference ``fa/local_analyzer/*`` + ``fa/aggregator/*`` —
average, frequency estimation, set intersection, union, k-percentile, and
heavy-hitter discovery (TrieHH lives in :mod:`.triehh`).
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from .base_frame import FAClientAnalyzer, FAServerAggregator


# --- average ---------------------------------------------------------------

class AvgClientAnalyzer(FAClientAnalyzer):
    def local_analyze(self, train_data, args=None) -> Tuple[float, int]:
        arr = np.asarray(train_data, dtype=np.float64)
        return float(arr.sum()), int(arr.size)


class AvgAggregator(FAServerAggregator):
    def aggregate(self, submissions: List[Tuple[float, int]]) -> float:
        total = sum(s for s, _ in submissions)
        n = sum(n for _, n in submissions)
        self.server_data = total / max(n, 1)
        return self.server_data


# --- frequency estimation ---------------------------------------------------

class FrequencyClientAnalyzer(FAClientAnalyzer):
    def local_analyze(self, train_data, args=None) -> Dict[Any, int]:
        return dict(Counter(list(np.asarray(train_data).ravel().tolist())))


class FrequencyAggregator(FAServerAggregator):
    def __init__(self, args=None):
        super().__init__(args)
        self.server_data = Counter()

    def aggregate(self, submissions: List[Dict[Any, int]]) -> Dict[Any, int]:
        for sub in submissions:
            self.server_data.update(sub)
        return dict(self.server_data)


# --- intersection / union ---------------------------------------------------

class IntersectionClientAnalyzer(FAClientAnalyzer):
    def local_analyze(self, train_data, args=None) -> set:
        return set(np.asarray(train_data).ravel().tolist())


class IntersectionAggregator(FAServerAggregator):
    def aggregate(self, submissions: List[set]) -> set:
        out = submissions[0]
        for s in submissions[1:]:
            out = out & s
        self.server_data = out
        return out


class UnionAggregator(FAServerAggregator):
    def aggregate(self, submissions: List[set]) -> set:
        out = set()
        for s in submissions:
            out |= s
        self.server_data = out
        return out


# --- k-percentile -----------------------------------------------------------

class KPercentileClientAnalyzer(FAClientAnalyzer):
    """Client reports its count below/above the server's current pivot —
    the interactive binary-search protocol of the reference (clients never
    reveal raw values)."""

    def local_analyze(self, train_data, args=None):
        pivot = self.init_msg
        arr = np.asarray(train_data, dtype=np.float64).ravel()
        return int((arr <= pivot).sum()), int(arr.size)


class KPercentileAggregator(FAServerAggregator):
    """Server drives a bisection on the pivot until the global rank of the
    pivot matches k%."""

    def __init__(self, args=None, k: float = 50.0, lo: float = -1e9,
                 hi: float = 1e9):
        super().__init__(args)
        self.k = k
        self.lo, self.hi = lo, hi
        self.pivot = 0.5 * (lo + hi)

    def get_init_msg(self):
        return self.pivot

    def aggregate(self, submissions: List[Tuple[int, int]]) -> float:
        below = sum(b for b, _ in submissions)
        total = sum(n for _, n in submissions)
        if total and (below / total) * 100.0 < self.k:
            self.lo = self.pivot
        else:
            self.hi = self.pivot
        self.pivot = 0.5 * (self.lo + self.hi)
        self.server_data = self.pivot
        return self.pivot
