"""Federated-analytics algorithm frame.

Parity target: reference ``fa/base_frame/`` — ``FAClientAnalyzer`` /
``FAServerAggregator`` mirror the FL ClientTrainer/ServerAggregator minus
models: a client turns its local raw data into a *submission*, the server
folds submissions into the global analytic result.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, List, Sequence


class FAClientAnalyzer(ABC):
    def __init__(self, args=None):
        self.args = args
        self.init_msg: Any = None

    def set_init_msg(self, init_msg: Any) -> None:
        self.init_msg = init_msg

    def get_init_msg(self) -> Any:
        return self.init_msg

    @abstractmethod
    def local_analyze(self, train_data: Sequence, args=None) -> Any:
        """Raw local data -> client submission."""


class FAServerAggregator(ABC):
    def __init__(self, args=None):
        self.args = args
        self.server_data: Any = None

    def get_server_data(self) -> Any:
        return self.server_data

    def get_init_msg(self) -> Any:
        return None

    @abstractmethod
    def aggregate(self, submissions: List[Any]) -> Any:
        """Fold client submissions into the global result."""
