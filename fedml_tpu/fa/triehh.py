"""TrieHH — interactive federated heavy-hitter discovery.

Parity target: reference ``fa/local_analyzer/heavy_hitter_triehh.py`` +
``fa/aggregator/heavy_hitter_triehh_aggregator.py`` + ``fa/utils/trie.py``
(Zhu et al., "Federated Heavy Hitters Discovery with Differential Privacy"):
the server grows a prefix trie one character per round; sampled clients vote
for the (round+1)-length prefix of one of their words IF its round-length
prefix is already in the trie; prefixes with >= theta votes are added.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional, Sequence

import numpy as np

from .base_frame import FAClientAnalyzer, FAServerAggregator


class Trie:
    """Prefix store (reference ``fa/utils/trie.py``)."""

    def __init__(self):
        self._prefixes = {""}

    def contains_prefix(self, p: str) -> bool:
        return p in self._prefixes

    def add(self, p: str) -> None:
        self._prefixes.add(p)

    def all_prefixes(self):
        return set(self._prefixes)

    def terminal_words(self, end: str = "$") -> List[str]:
        return sorted(p[:-1] for p in self._prefixes if p.endswith(end))


class TrieHHClientAnalyzer(FAClientAnalyzer):
    """Votes with one uniformly-sampled local word per round."""

    def __init__(self, args=None, seed: int = 0):
        super().__init__(args)
        self.rng = np.random.RandomState(seed)

    def local_analyze(self, train_data: Sequence[str], args=None
                      ) -> Optional[str]:
        trie_prefixes, round_len = self.init_msg
        words = list(train_data)
        if not words:
            return None
        word = words[self.rng.randint(len(words))] + "$"
        if len(word) < round_len:
            return None
        prefix = word[:round_len]
        if round_len == 1 or word[:round_len - 1] in trie_prefixes:
            return prefix
        return None


class TrieHHAggregator(FAServerAggregator):
    def __init__(self, args=None, theta: int = 2, max_rounds: int = 10):
        super().__init__(args)
        self.trie = Trie()
        self.theta = int(theta)
        self.round_len = 1
        self.server_data: List[str] = []

    def get_init_msg(self):
        return (self.trie.all_prefixes(), self.round_len)

    def aggregate(self, submissions: List[Optional[str]]) -> List[str]:
        votes = Counter(s for s in submissions if s)
        for prefix, count in votes.items():
            if count >= self.theta:
                self.trie.add(prefix)
        self.round_len += 1
        self.server_data = self.trie.terminal_words()
        return self.server_data
