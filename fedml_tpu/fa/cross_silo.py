"""FA over the WAN FSM — cross-silo federated analytics.

Parity target: reference ``fa/cross_silo/`` (the FL cross-silo skeleton
minus models: server broadcasts the round's init message, clients run
``local_analyze`` on their raw local data and ship a *submission*, the
server folds submissions with the ``FAServerAggregator``). Transport is
any ``FedMLCommManager`` backend; the in-proc session helper mirrors the
FL one so an analytics session is testable without a cluster.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Sequence

from ..core.distributed.communication.message import Message
from ..core.distributed.fedml_comm_manager import FedMLCommManager

logger = logging.getLogger(__name__)


class FAMessage:
    C2S_ONLINE = "fa_online"
    S2C_INIT = "fa_init"          # round start: init_msg + round idx
    C2S_SUBMISSION = "fa_submission"
    S2C_FINISH = "fa_finish"

    KEY_INIT = "init_msg"
    KEY_ROUND = "round"
    KEY_SUBMISSION = "submission"


class FAClientManager(FedMLCommManager):
    """One analytics party: raw local data + a client analyzer."""

    def __init__(self, args, analyzer, local_data: Sequence, comm=None,
                 rank: int = 1, size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.analyzer = analyzer
        self.local_data = local_data

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(FAMessage.S2C_INIT,
                                              self.on_init)
        self.register_message_receive_handler(FAMessage.S2C_FINISH,
                                              self.on_finish)

    def run(self) -> None:
        self.register_message_receive_handlers()
        self.send_message(Message(FAMessage.C2S_ONLINE, self.rank, 0))
        self.com_manager.handle_receive_message()

    def on_init(self, msg: Message) -> None:
        self.analyzer.set_init_msg(msg.get(FAMessage.KEY_INIT))
        submission = self.analyzer.local_analyze(self.local_data, self.args)
        out = Message(FAMessage.C2S_SUBMISSION, self.rank, 0)
        out.add_params(FAMessage.KEY_SUBMISSION, submission)
        out.add_params(FAMessage.KEY_ROUND, msg.get(FAMessage.KEY_ROUND))
        self.send_message(out)

    def on_finish(self, msg: Message) -> None:
        self.finish()


class FAServerManager(FedMLCommManager):
    def __init__(self, args, aggregator, comm=None, rank: int = 0,
                 size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.aggregator = aggregator
        self.n_clients = size - 1
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        self.online: Dict[int, bool] = {}
        # keyed by sender id: a client retry must not count twice, and a
        # late previous-round submission must not fold into this round
        # (mirrors the SecAgg/LSA masked-input bookkeeping)
        self.submissions: Dict[int, Any] = {}
        self.history: List[Any] = []
        self.result: Optional[dict] = None
        self._lock = threading.Lock()
        self._started = False

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(FAMessage.C2S_ONLINE,
                                              self.on_online)
        self.register_message_receive_handler(FAMessage.C2S_SUBMISSION,
                                              self.on_submission)

    def on_online(self, msg: Message) -> None:
        self.online[msg.get_sender_id()] = True
        if len(self.online) >= self.n_clients and not self._started:
            self._started = True
            self._start_round()

    def _start_round(self) -> None:
        init_msg = self.aggregator.get_init_msg()
        for rank in sorted(self.online):
            out = Message(FAMessage.S2C_INIT, 0, rank)
            out.add_params(FAMessage.KEY_INIT, init_msg)
            out.add_params(FAMessage.KEY_ROUND, self.round_idx)
            self.send_message(out)

    def on_submission(self, msg: Message) -> None:
        # the whole round close (aggregate + round_idx advance) stays under
        # the lock: a retransmit arriving mid-aggregation must see the NEW
        # round index, or it would be folded into the next round
        with self._lock:
            if int(msg.get(FAMessage.KEY_ROUND, -1)) != self.round_idx:
                return  # stale round (WAN reorder) / retry — drop
            self.submissions[msg.get_sender_id()] = msg.get(
                FAMessage.KEY_SUBMISSION)
            if len(self.submissions) < self.n_clients:
                return
            subs = [self.submissions[k] for k in sorted(self.submissions)]
            self.submissions = {}
            result = self.aggregator.aggregate(subs)
            self.history.append(result)
            logger.info("fa server round %d done", self.round_idx)
            self.round_idx += 1
            done = self.round_idx >= self.round_num
        if done:
            for rank in sorted(self.online):
                self.send_message(Message(FAMessage.S2C_FINISH, 0, rank))
            self.result = {"result": self.aggregator.get_server_data(),
                           "history": self.history,
                           "rounds": self.round_num}
            self.finish()
            return
        self._start_round()


def run_fa_cross_silo_inproc(args, client_datas: Sequence[Sequence],
                             analyzer_factory, aggregator) -> Dict[str, Any]:
    """Server + one FA client per data shard as threads over the in-proc
    broker (the FL session helper's analytics twin)."""
    from ..core.distributed.communication.inproc import InProcBroker

    broker = InProcBroker()
    args.inproc_broker = broker
    n = len(client_datas)
    server = FAServerManager(args, aggregator, rank=0, size=n + 1,
                             backend="INPROC")
    clients = [FAClientManager(args, analyzer_factory(), client_datas[i],
                               rank=i + 1, size=n + 1, backend="INPROC")
               for i in range(n)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30.0)
    return server.result
