"""FA over the WAN FSM — cross-silo federated analytics.

Parity target: reference ``fa/cross_silo/`` (the FL cross-silo skeleton
minus models: server broadcasts the round's init message, clients run
``local_analyze`` on their raw local data and ship a *submission*, the
server folds submissions with the ``FAServerAggregator``). Transport is
any ``FedMLCommManager`` backend; the in-proc session helper mirrors the
FL one so an analytics session is testable without a cluster.

Cohort assembly (``cohort_assembly`` knob; off = every online client
analyzes every round, the legacy behavior) rides the SAME machinery as
the training plane: clients report the charging/idle/unmetered
handshake analogues on their ONLINE message, the server sieves
eligibility and streams a utility-scored cohort per round, and Oort's
deadline pacer steers the over-sample. Same handshake, different
payloads — an analytics task is just another tenant of the fleet, and
with ``fleet_registry`` set it registers and claims devices through the
shared :class:`~fedml_tpu.core.fleet.DeviceRegistry` so a concurrent
training task never co-schedules a device.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.distributed.communication.message import Message
from ..core.distributed.fedml_comm_manager import FedMLCommManager

logger = logging.getLogger(__name__)


class FAMessage:
    C2S_ONLINE = "fa_online"
    S2C_INIT = "fa_init"          # round start: init_msg + round idx
    C2S_SUBMISSION = "fa_submission"
    S2C_FINISH = "fa_finish"

    KEY_INIT = "init_msg"
    KEY_ROUND = "round"
    KEY_SUBMISSION = "submission"
    KEY_ELIGIBILITY = "eligibility"  # handshake dict on C2S_ONLINE


class FAClientManager(FedMLCommManager):
    """One analytics party: raw local data + a client analyzer."""

    def __init__(self, args, analyzer, local_data: Sequence, comm=None,
                 rank: int = 1, size: int = 0, backend: str = "INPROC",
                 eligibility: Optional[dict] = None):
        super().__init__(args, comm, rank, size, backend)
        self.analyzer = analyzer
        self.local_data = local_data
        # charging/idle/unmetered analogues, reported on the handshake
        # (absent keys default True server-side — same convention as the
        # training plane's DeviceMessage handshake)
        self.eligibility = dict(eligibility or {})

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(FAMessage.S2C_INIT,
                                              self.on_init)
        self.register_message_receive_handler(FAMessage.S2C_FINISH,
                                              self.on_finish)

    def run(self) -> None:
        self.register_message_receive_handlers()
        online = Message(FAMessage.C2S_ONLINE, self.rank, 0)
        online.add_params(FAMessage.KEY_ELIGIBILITY, self.eligibility)
        self.send_message(online)
        self.com_manager.handle_receive_message()

    def on_init(self, msg: Message) -> None:
        self.analyzer.set_init_msg(msg.get(FAMessage.KEY_INIT))
        submission = self.analyzer.local_analyze(self.local_data, self.args)
        out = Message(FAMessage.C2S_SUBMISSION, self.rank, 0)
        out.add_params(FAMessage.KEY_SUBMISSION, submission)
        out.add_params(FAMessage.KEY_ROUND, msg.get(FAMessage.KEY_ROUND))
        self.send_message(out)

    def on_finish(self, msg: Message) -> None:
        self.finish()


class FAServerManager(FedMLCommManager):
    def __init__(self, args, aggregator, comm=None, rank: int = 0,
                 size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.aggregator = aggregator
        self.n_clients = size - 1
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        # rank -> handshake eligibility meta (empty dict = all-True)
        self.online: Dict[int, Dict] = {}
        # keyed by sender id: a client retry must not count twice, and a
        # late previous-round submission must not fold into this round
        # (mirrors the SecAgg/LSA masked-input bookkeeping)
        self.submissions: Dict[int, Any] = {}
        self.history: List[Any] = []
        self.result: Optional[dict] = None
        self._lock = threading.Lock()
        self._started = False
        # --- cohort assembly (same knob + machinery as the training
        # plane; off = broadcast to every online client, bit-identical)
        self.cohort_enabled = bool(getattr(args, "cohort_assembly", False))
        self.stats = None
        self.assembler = None
        self.pacer = None
        self._cohort: List[int] = []
        self._barrier = self.n_clients
        self._round_k = self.n_clients
        self._round_utility = 0.0
        self._dispatch_ts = 0.0
        self.cohort_log: list = []
        if self.cohort_enabled:
            from ..core.selection import (DeadlinePacer,
                                          StreamingCohortAssembler,
                                          make_stats_store,
                                          required_eligibility)
            population = max(self.n_clients, 1) + 1  # 1-based ranks
            self.stats = make_stats_store(args, population)
            self.assembler = StreamingCohortAssembler(args, self.stats,
                                                      population)
            self.pacer = DeadlinePacer.from_args(args)
            self.required_elig = required_eligibility(args)
            self.cohort_k = int(getattr(args, "cohort_size", 0) or 0) \
                or self.n_clients
        # --- fleet tenancy (fleet_registry knob): the FA task registers
        # its parties and claims its cohorts through the shared registry
        self.fleet = None
        self.fleet_task = str(getattr(args, "fleet_task_id", "") or "fa")
        reg_path = getattr(args, "fleet_registry", None)
        if reg_path:
            from ..core.fleet import DeviceRegistry
            self.fleet = DeviceRegistry(str(reg_path))
            self.fleet_cap = int(getattr(args,
                                         "fleet_max_rounds_per_window", 0)
                                 or 0)
            self.fleet_window_s = float(getattr(
                args, "fleet_fairness_window_s", 3600.0) or 3600.0)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(FAMessage.C2S_ONLINE,
                                              self.on_online)
        self.register_message_receive_handler(FAMessage.C2S_SUBMISSION,
                                              self.on_submission)

    def on_online(self, msg: Message) -> None:
        rank = msg.get_sender_id()
        meta = msg.get(FAMessage.KEY_ELIGIBILITY) or {}
        self.online[rank] = dict(meta) if isinstance(meta, dict) else {}
        if self.fleet is not None:
            self.fleet.register(int(rank), self.online[rank])
        if len(self.online) >= self.n_clients and not self._started:
            self._started = True
            self._start_round()

    def _round_cohort(self) -> List[int]:
        """The parties this round analyzes: every online client
        (legacy), or the streaming-assembled cohort — the training
        plane's eligibility sieve with analytics payloads."""
        online = sorted(self.online)
        if not self.cohort_enabled:
            return online
        from ..core.selection.cohort import eligible_mask
        k = self.pacer.paced_cohort(self.cohort_k)
        self._round_k = k
        target = self.pacer.target_cohort(k, ceiling=len(online))
        ids = np.asarray(online, np.int64)
        metas = [self.online[r] for r in online]
        mask = eligible_mask(metas, self.required_elig)

        def elig(chunk: np.ndarray) -> np.ndarray:
            pos = np.searchsorted(ids, chunk)
            return mask[pos]

        res = self.assembler.assemble(
            self.round_idx, target, [ids], eligible_fn=elig,
            deadline_s=self.pacer.deadline_s,
            over_sample=self.pacer.over_sample)
        cohort = sorted(res.cohort)
        self._round_utility = (float(np.sum(res.scores))
                               if res.scores is not None
                               and len(res.scores) else 0.0)
        if self.fleet is not None and cohort:
            from ..core.obs import metrics as obs_metrics
            granted, busy, capped = self.fleet.claim(
                self.fleet_task, cohort, self.round_idx,
                cap=self.fleet_cap, window_s=self.fleet_window_s)
            obs_metrics.record_fleet_round(self.fleet_task, len(granted),
                                           busy, capped)
            cohort = sorted(granted)
        if not cohort and self.fleet is None:
            logger.warning(
                "fa cohort round %d: no eligible client of %d online — "
                "broadcasting to every online client",
                self.round_idx, len(online))
            cohort = online
        self.stats.record_selected(self.round_idx, cohort)
        self.cohort_log.append((self.round_idx, list(cohort)))
        logger.info("fa cohort round %d: dispatching %d/%d online",
                    self.round_idx, len(cohort), len(online))
        return cohort

    def _start_round(self) -> None:
        init_msg = self.aggregator.get_init_msg()
        cohort = self._round_cohort()
        self._cohort = list(cohort)
        self._barrier = (max(min(self._round_k, len(cohort)), 1)
                         if self.cohort_enabled else self.n_clients)
        self._dispatch_ts = time.time()
        for rank in cohort:
            out = Message(FAMessage.S2C_INIT, 0, rank)
            out.add_params(FAMessage.KEY_INIT, init_msg)
            out.add_params(FAMessage.KEY_ROUND, self.round_idx)
            self.send_message(out)

    def _close_round_locked(self) -> None:
        """Cohort-mode round close under the lock: control-plane
        evidence (availability per dispatched party, dispatch→submit
        latency already recorded, pacer step) + the fleet release."""
        if not self.cohort_enabled or not self._cohort:
            return
        reported = set(self.submissions)
        for rank in self._cohort:
            self.stats.record_availability(rank,
                                           participated=rank in reported)
        self.pacer.observe_round(
            completed=len(reported), expected=self._barrier,
            wall_s=max(time.time() - self._dispatch_ts, 0.0))
        self.pacer.observe_utility(self._round_utility)
        if self.fleet is not None:
            self.fleet.release(self.fleet_task, self.round_idx,
                               sorted(reported))

    def on_submission(self, msg: Message) -> None:
        # the whole round close (aggregate + round_idx advance) stays under
        # the lock: a retransmit arriving mid-aggregation must see the NEW
        # round index, or it would be folded into the next round
        with self._lock:
            if int(msg.get(FAMessage.KEY_ROUND, -1)) != self.round_idx:
                return  # stale round (WAN reorder) / retry — drop
            rank = msg.get_sender_id()
            self.submissions[rank] = msg.get(FAMessage.KEY_SUBMISSION)
            if self.cohort_enabled and self._dispatch_ts > 0:
                self.stats.record_latency(rank,
                                          time.time() - self._dispatch_ts)
            if len(self.submissions) < self._barrier:
                return
            self._close_round_locked()
            subs = [self.submissions[k] for k in sorted(self.submissions)]
            self.submissions = {}
            result = self.aggregator.aggregate(subs)
            self.history.append(result)
            logger.info("fa server round %d done", self.round_idx)
            self.round_idx += 1
            done = self.round_idx >= self.round_num
        if done:
            for rank in sorted(self.online):
                self.send_message(Message(FAMessage.S2C_FINISH, 0, rank))
            self.result = {"result": self.aggregator.get_server_data(),
                           "history": self.history,
                           "rounds": self.round_num}
            self.finish()
            return
        self._start_round()


def run_fa_cross_silo_inproc(args, client_datas: Sequence[Sequence],
                             analyzer_factory, aggregator,
                             eligibility: Optional[Dict[int, dict]] = None
                             ) -> Dict[str, Any]:
    """Server + one FA client per data shard as threads over the in-proc
    broker (the FL session helper's analytics twin). ``eligibility``
    maps rank -> handshake overrides for cohort-assembly sessions."""
    from ..core.distributed.communication.inproc import InProcBroker

    broker = InProcBroker()
    args.inproc_broker = broker
    n = len(client_datas)
    server = FAServerManager(args, aggregator, rank=0, size=n + 1,
                             backend="INPROC")
    clients = [FAClientManager(args, analyzer_factory(), client_datas[i],
                               rank=i + 1, size=n + 1, backend="INPROC",
                               eligibility=(eligibility or {}).get(i + 1))
               for i in range(n)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30.0)
    return server.result
