"""Federated analytics (reference ``fa/``, 2.6k LoC): the FL skeleton minus
models — client analyzers + server aggregators for average, frequency,
intersection, union, k-percentile, and TrieHH heavy hitters, with an SP
simulator (cross-silo FA runs over the same WAN FSM as FL).

Usage parity with ``fa.init`` / ``FARunner``:

    from fedml_tpu import fa
    result = fa.run_fa("avg", client_datas, args)
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

from .analyzers import (AvgAggregator, AvgClientAnalyzer,
                        FrequencyAggregator, FrequencyClientAnalyzer,
                        IntersectionAggregator, IntersectionClientAnalyzer,
                        KPercentileAggregator, KPercentileClientAnalyzer,
                        UnionAggregator)
from .base_frame import FAClientAnalyzer, FAServerAggregator
from .simulation import FASimulator
from .triehh import Trie, TrieHHAggregator, TrieHHClientAnalyzer

FA_TASK_AVG = "avg"
FA_TASK_FREQ = "frequency_estimation"
FA_TASK_INTERSECTION = "intersection"
FA_TASK_UNION = "union"
FA_TASK_K_PERCENTILE = "k_percentile"
FA_TASK_HEAVY_HITTER_TRIEHH = "heavy_hitter_triehh"


def create_fa_pair(task: str, args=None):
    """(analyzer, aggregator) per FA task (reference ``fa/fa_runner`` +
    per-task creators)."""
    t = str(task).lower()
    if t == FA_TASK_AVG:
        return AvgClientAnalyzer(args), AvgAggregator(args)
    if t in (FA_TASK_FREQ, "freq"):
        return FrequencyClientAnalyzer(args), FrequencyAggregator(args)
    if t == FA_TASK_INTERSECTION:
        return IntersectionClientAnalyzer(args), IntersectionAggregator(args)
    if t == FA_TASK_UNION:
        return IntersectionClientAnalyzer(args), UnionAggregator(args)
    if t == FA_TASK_K_PERCENTILE:
        k = float(getattr(args, "k_percentile", 50) or 50) if args else 50.0
        return (KPercentileClientAnalyzer(args),
                KPercentileAggregator(args, k=k))
    if t in (FA_TASK_HEAVY_HITTER_TRIEHH, "heavy_hitter"):
        theta = int(getattr(args, "triehh_theta", 2) or 2) if args else 2
        return (TrieHHClientAnalyzer(args),
                TrieHHAggregator(args, theta=theta))
    raise ValueError(f"unknown FA task {task!r}")


def run_fa(task: str, client_datas: Sequence[Sequence], args=None,
           comm_round: Optional[int] = None) -> Dict[str, Any]:
    analyzer, aggregator = create_fa_pair(task, args)
    sim = FASimulator(args or _DefaultArgs(len(client_datas)), client_datas,
                      analyzer, aggregator)
    return sim.run(comm_round)


class _DefaultArgs:
    def __init__(self, n_clients: int):
        self.comm_round = 1
        self.client_num_per_round = n_clients


__all__ = ["FAClientAnalyzer", "FAServerAggregator", "FASimulator",
           "create_fa_pair", "run_fa", "Trie", "TrieHHAggregator",
           "TrieHHClientAnalyzer"]
