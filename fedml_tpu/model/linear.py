"""Linear / MLP models (reference ``model/linear/lr.py``, ``model/mlp.py``)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class LogisticRegression(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes)(x)


class MLP(nn.Module):
    num_classes: int
    hidden: Sequence[int] = (128, 64)
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
            if self.dropout:
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)
