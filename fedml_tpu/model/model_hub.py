"""Model dispatch: ``fedml_tpu.model.create(args, output_dim)``.

Parity target: ``model/model_hub.py:19-88`` of the reference (dispatch on
``(model, dataset)``). Returns a :class:`ModelBundle` wrapping a flax module
with init/apply closures the algorithm frame consumes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any


@dataclasses.dataclass
class ModelBundle:
    module: nn.Module
    name: str
    _has_dropout: bool = False
    compute_dtype: Any = jnp.float32

    def init(self, rng: jax.Array, sample_input: jnp.ndarray) -> PyTree:
        # jit the init: eager tracing pays one device round-trip per op,
        # which on the tunneled TPU platform turns a deep model's init
        # (MobileNetV3: hundreds of ops) into MINUTES; compiled it is one
        # dispatch. eval_shape-free — shapes come from the sample input.
        variables = jax.jit(
            lambda r, x: self.module.init(r, x, train=False)
        )(rng, sample_input)
        return variables["params"]

    def apply(self, params: PyTree, x: jnp.ndarray, rng: Optional[jax.Array] = None,
              train: bool = False) -> jnp.ndarray:
        rngs = {"dropout": rng} if (rng is not None and self._has_dropout) else None
        if self.compute_dtype != jnp.float32:
            # Mixed precision, TPU-standard recipe: master params stay f32
            # (the optimizer and the FedAvg psum aggregate in f32); the
            # forward/backward compute path — where the MXU matmuls are —
            # runs in bf16 via a cast at the boundary. Gradients flow back
            # through the cast and land in f32 on the master leaves.
            dt = self.compute_dtype
            params = jax.tree_util.tree_map(
                lambda a: a.astype(dt) if a.dtype == jnp.float32 else a, params)
            if jnp.issubdtype(x.dtype, jnp.floating):
                x = x.astype(dt)
        out = self.module.apply({"params": params}, x, train=train, rngs=rngs)
        return out.astype(jnp.float32)


def _fused_conv_mode(args) -> str:
    """``fused_conv_block`` knob -> BasicBlock ``fused`` mode. Off (the
    default) keeps the original flax path, bit-compatible with every run
    before the knob existed; true/pallas dispatches the VMEM-resident
    Pallas kernel (interpret mode off-TPU); reference/xla runs the same
    fused math through plain XLA (the kernel's numerical golden)."""
    v = getattr(args, "fused_conv_block", None)
    if v is None or v is False:
        return ""
    s = str(v).lower()
    if s in ("", "false", "0", "no", "none", "off"):
        return ""
    if s in ("true", "1", "yes", "on", "pallas"):
        return "pallas"
    if s in ("reference", "xla"):
        return "reference"
    raise ValueError(
        f"unknown fused_conv_block mode {v!r} (false|true|pallas|reference)")


def _compute_dtype(args):
    p = str(getattr(args, "precision", "float32") or "float32").lower()
    if p in ("bf16", "bfloat16", "mixed", "mixed_bfloat16"):
        return jnp.bfloat16
    if p in ("fp16", "float16", "half"):
        return jnp.float16
    return jnp.float32


def create(args, output_dim: int):
    """Returns a ModelBundle, or a (generator, discriminator) bundle pair
    for model='gan' (consumed by custom FedGAN trainers). ``args.precision``
    (bfloat16/float32) selects the compute dtype of the bundle's apply path."""
    out = _create(args, output_dim)
    dt = _compute_dtype(args)
    if dt != jnp.float32:
        if isinstance(out, tuple):
            out = tuple(dataclasses.replace(b, compute_dtype=dt) for b in out)
        else:
            out = dataclasses.replace(out, compute_dtype=dt)
    return out


def _create(args, output_dim: int):
    name = str(getattr(args, "model", "lr")).lower()
    from .linear import LogisticRegression, MLP
    from .cv.cnn import CNNFemnist, SimpleCNN

    if name in ("lr", "logistic_regression"):
        return ModelBundle(LogisticRegression(output_dim), name)
    if name == "mlp":
        return ModelBundle(MLP(output_dim), name, _has_dropout=True)
    if name in ("cnn", "cnn_dropout", "femnist_cnn"):
        return ModelBundle(CNNFemnist(output_dim), name, _has_dropout=True)
    if name in ("device_cnn", "mobile_cnn"):
        from .cv.cnn import DeviceCNN
        return ModelBundle(DeviceCNN(num_classes=output_dim), name)
    if name in ("simple_cnn", "cifar_cnn"):
        return ModelBundle(SimpleCNN(output_dim), name)
    if name in ("lenet", "lenet5", "mnn_lenet"):
        from .cv.lenet import LeNet5
        return ModelBundle(LeNet5(output_dim), name)
    if name in ("vfl_feature_extractor", "local_model"):
        from .finance import VFLFeatureExtractor
        return ModelBundle(VFLFeatureExtractor(out_dim=output_dim), name)
    if name in ("vfl_classifier", "dense_model"):
        from .finance import VFLClassifier
        return ModelBundle(VFLClassifier(output_dim), name)
    if name in ("lending_club_mlp", "finance_mlp"):
        from .finance import LendingClubMLP
        return ModelBundle(LendingClubMLP(output_dim), name)
    if name.startswith("resnet"):
        from .cv.resnet import create_resnet
        return ModelBundle(
            create_resnet(name, output_dim, fused=_fused_conv_mode(args)),
            name)
    if name in ("rnn", "lstm", "rnn_shakespeare", "stacked_lstm"):
        dataset = str(getattr(args, "dataset", "")).lower()
        if "stackoverflow" in dataset:
            from .nlp.rnn import RNNStackOverflow
            return ModelBundle(RNNStackOverflow(vocab_size=output_dim), name)
        from .nlp.rnn import RNNShakespeare
        return ModelBundle(RNNShakespeare(vocab_size=output_dim), name)
    if name.startswith("mobilenet"):
        from .cv.mobilenet import MobileNetV3Small
        return ModelBundle(MobileNetV3Small(output_dim), name)
    if name.startswith("efficientnet"):
        from .cv.efficientnet import create_efficientnet
        return ModelBundle(create_efficientnet(name, output_dim), name,
                           _has_dropout=True)
    if name.startswith("vgg"):
        from .cv.vgg import create_vgg
        return ModelBundle(create_vgg(name, output_dim), name,
                           _has_dropout=True)
    if name in ("gan", "mnist_gan"):
        from .cv.gan import Discriminator, Generator
        # FedGAN trains (generator, discriminator) pairs; return both
        return (ModelBundle(Generator(), "generator"),
                ModelBundle(Discriminator(), "discriminator"))
    raise ValueError(f"unknown model {name!r}")
