from .model_hub import create, ModelBundle

__all__ = ["create", "ModelBundle"]
