"""CIFAR ResNets (ResNet-20/56, 6n+2 layout) and ResNet-18.

Parity targets: ``model/cv/resnet.py`` (resnet56 for the north-star CIFAR-10
benchmark) and ``model/cv/resnet_gn.py`` of the reference. GroupNorm is the
default normalization — the reference's own federated configs use GN because
BatchNorm statistics break under non-IID client data, and GN keeps the model
a pure function of (params, x), which is what lets a whole FL round jit.

``fused`` routes the narrow (<= 64 channel) BasicBlocks through the Pallas
fused conv->GN->residual->ReLU kernel (``core/kernels/conv_block``, ISSUE
16): ``"pallas"`` dispatches the VMEM-resident kernel (interpret mode off-
TPU), ``"reference"`` the XLA reference math, ``""`` (default) the original
flax path — bit-identical to before the knob existed. All three declare
byte-identical parameter trees (same scope paths, names, initializers), so
checkpoints and the engine's flat-vector defenses are mode-agnostic.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ...core.kernels.conv_block import (MAX_FUSED_CHANNELS, fused_block,
                                        reference_block)


class _ConvKernel(nn.Module):
    """Parameter-only stand-in for ``nn.Conv(use_bias=False)``: declares
    the same ``kernel`` param (name, shape, lecun_normal init) under the
    same scope path, so the fused block's init tree is bit-identical to
    the unfused module's."""
    features: int
    ksize: Tuple[int, int] = (3, 3)

    @nn.compact
    def __call__(self, in_features: int):
        return self.param("kernel", nn.initializers.lecun_normal(),
                          self.ksize + (int(in_features), self.features))


class _GroupNormParams(nn.Module):
    """Parameter-only stand-in for ``nn.GroupNorm``: scale (ones) then
    bias (zeros), flax declaration order."""
    features: int

    @nn.compact
    def __call__(self):
        scale = self.param("scale", nn.initializers.ones, (self.features,))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        return scale, bias


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    groups: int = 8
    fused: str = ""  # "" (flax path) | "pallas" | "reference"

    @nn.compact
    def __call__(self, x):
        # narrow stages only: wide ImageNet blocks already saturate the
        # MXU through XLA, and their activations dwarf the VMEM budget
        if self.fused and self.filters <= MAX_FUSED_CHANNELS:
            return self._fused_call(x)
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                    use_bias=False)(x)
        y = nn.GroupNorm(num_groups=min(self.groups, self.filters))(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), use_bias=False)(y)
        y = nn.GroupNorm(num_groups=min(self.groups, self.filters))(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False)(x)
            residual = nn.GroupNorm(
                num_groups=min(self.groups, self.filters))(residual)
        return nn.relu(residual + y)

    def _fused_call(self, x):
        """One fused kernel per block. The explicit ``name=`` arguments pin
        the child scope paths to exactly what flax auto-naming gives the
        unfused path (Conv_0/GroupNorm_0/.../GroupNorm_2), which is what
        makes the two parameter trees — values included — bit-identical."""
        cin = int(x.shape[-1])
        f = self.filters
        p = {"w1": _ConvKernel(f, name="Conv_0")(cin)}
        p["g1_scale"], p["g1_bias"] = _GroupNormParams(
            f, name="GroupNorm_0")()
        p["w2"] = _ConvKernel(f, name="Conv_1")(f)
        p["g2_scale"], p["g2_bias"] = _GroupNormParams(
            f, name="GroupNorm_1")()
        if self.strides != 1 or cin != f:
            p["wp"] = _ConvKernel(f, ksize=(1, 1), name="Conv_2")(cin)
            p["gp_scale"], p["gp_bias"] = _GroupNormParams(
                f, name="GroupNorm_2")()
        impl = fused_block if self.fused == "pallas" else reference_block
        return impl(x, p, strides=self.strides,
                    groups=min(self.groups, f))


class CifarResNet(nn.Module):
    """6n+2 ResNet: stages of n blocks at widths 16/32/64."""
    num_classes: int
    blocks_per_stage: int  # n: 3 -> resnet20, 9 -> resnet56
    fused: str = ""

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), use_bias=False)(x)
        x = nn.GroupNorm(num_groups=8)(x)
        x = nn.relu(x)
        for stage, filters in enumerate((16, 32, 64)):
            for block in range(self.blocks_per_stage):
                strides = 2 if (stage > 0 and block == 0) else 1
                x = BasicBlock(filters, strides, fused=self.fused)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


class ResNet18(nn.Module):
    """ImageNet-style ResNet-18 (reference ``model/cv/resnet.py`` resnet18)."""
    num_classes: int
    fused: str = ""

    @nn.compact
    def __call__(self, x, train: bool = False):
        small = x.shape[1] <= 64  # CIFAR-style stem for small images
        if small:
            x = nn.Conv(64, (3, 3), use_bias=False)(x)
        else:
            x = nn.Conv(64, (7, 7), strides=(2, 2), use_bias=False)(x)
        x = nn.GroupNorm(num_groups=8)(x)
        x = nn.relu(x)
        if not small:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, filters in enumerate((64, 128, 256, 512)):
            for block in range(2):
                strides = 2 if (stage > 0 and block == 0) else 1
                x = BasicBlock(filters, strides, fused=self.fused)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def create_resnet(name: str, num_classes: int, fused: str = "") -> nn.Module:
    name = name.lower()
    if name in ("resnet20", "resnet20_gn"):
        return CifarResNet(num_classes, blocks_per_stage=3, fused=fused)
    if name in ("resnet56", "resnet56_gn", "resnet"):
        return CifarResNet(num_classes, blocks_per_stage=9, fused=fused)
    if name in ("resnet18", "resnet18_gn"):
        return ResNet18(num_classes, fused=fused)
    raise ValueError(f"unknown resnet variant {name!r}")
