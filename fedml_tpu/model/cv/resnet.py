"""CIFAR ResNets (ResNet-20/56, 6n+2 layout) and ResNet-18.

Parity targets: ``model/cv/resnet.py`` (resnet56 for the north-star CIFAR-10
benchmark) and ``model/cv/resnet_gn.py`` of the reference. GroupNorm is the
default normalization — the reference's own federated configs use GN because
BatchNorm statistics break under non-IID client data, and GN keeps the model
a pure function of (params, x), which is what lets a whole FL round jit.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    groups: int = 8

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                    use_bias=False)(x)
        y = nn.GroupNorm(num_groups=min(self.groups, self.filters))(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), use_bias=False)(y)
        y = nn.GroupNorm(num_groups=min(self.groups, self.filters))(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False)(x)
            residual = nn.GroupNorm(
                num_groups=min(self.groups, self.filters))(residual)
        return nn.relu(residual + y)


class CifarResNet(nn.Module):
    """6n+2 ResNet: stages of n blocks at widths 16/32/64."""
    num_classes: int
    blocks_per_stage: int  # n: 3 -> resnet20, 9 -> resnet56

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), use_bias=False)(x)
        x = nn.GroupNorm(num_groups=8)(x)
        x = nn.relu(x)
        for stage, filters in enumerate((16, 32, 64)):
            for block in range(self.blocks_per_stage):
                strides = 2 if (stage > 0 and block == 0) else 1
                x = BasicBlock(filters, strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


class ResNet18(nn.Module):
    """ImageNet-style ResNet-18 (reference ``model/cv/resnet.py`` resnet18)."""
    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        small = x.shape[1] <= 64  # CIFAR-style stem for small images
        if small:
            x = nn.Conv(64, (3, 3), use_bias=False)(x)
        else:
            x = nn.Conv(64, (7, 7), strides=(2, 2), use_bias=False)(x)
        x = nn.GroupNorm(num_groups=8)(x)
        x = nn.relu(x)
        if not small:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, filters in enumerate((64, 128, 256, 512)):
            for block in range(2):
                strides = 2 if (stage > 0 and block == 0) else 1
                x = BasicBlock(filters, strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def create_resnet(name: str, num_classes: int) -> nn.Module:
    name = name.lower()
    if name in ("resnet20", "resnet20_gn"):
        return CifarResNet(num_classes, blocks_per_stage=3)
    if name in ("resnet56", "resnet56_gn", "resnet"):
        return CifarResNet(num_classes, blocks_per_stage=9)
    if name in ("resnet18", "resnet18_gn"):
        return ResNet18(num_classes)
    raise ValueError(f"unknown resnet variant {name!r}")
