"""CNNs for federated vision tasks.

Parity targets: ``model/cv/cnn.py`` (FedAvg-paper CNN for MNIST/FEMNIST) and
``model/cv/simple_cnn.py`` (CIFAR CNN) of the reference. GroupNorm instead of
BatchNorm keeps the model purely functional (no mutable batch stats crossing
jit boundaries) — the reference itself ships GN variants for federated CIFAR
(``model/cv/resnet_gn.py``) because BN statistics break under non-IID FL.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class CNNFemnist(nn.Module):
    """The 2-conv CNN from the FedAvg paper (reference ``model/cv/cnn.py``
    ``CNN_DropOut``)."""
    num_classes: int = 62
    dropout: float = 0.25

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 2:  # flat input -> image
            side = int(round((x.shape[-1]) ** 0.5))
            x = x.reshape((x.shape[0], side, side, 1))
        x = nn.relu(nn.Conv(32, (3, 3))(x))
        x = nn.relu(nn.Conv(64, (3, 3))(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


class SimpleCNN(nn.Module):
    """CIFAR-10 CNN (reference ``model/cv/simple_cnn.py`` — conv-pool x2 +
    3 dense), GN-normalized."""
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(32, (5, 5))(x)
        x = nn.GroupNorm(num_groups=8)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5))(x)
        x = nn.GroupNorm(num_groups=8)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(384)(x))
        x = nn.relu(nn.Dense(192)(x))
        return nn.Dense(self.num_classes)(x)


class DeviceCNN(nn.Module):
    """LeNet-class CNN sized for on-device training, paired 1:1 with the
    native C++ trainer (``native/mobilenn.cpp`` train_cnn_sgd): conv3x3 SAME
    + relu + maxpool2, twice, then dense. The param tree (Conv_0/Conv_1/
    Dense_0) and flatten order match the native layout exactly, so native
    and JAX devices train the same model and aggregate interchangeably."""
    num_classes: int = 10
    features: tuple = (8, 16)

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 2:  # flat input -> square single-channel image
            side = int(round((x.shape[-1]) ** 0.5))
            x = x.reshape((x.shape[0], side, side, 1))
        x = nn.relu(nn.Conv(self.features[0], (3, 3))(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(self.features[1], (3, 3))(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes)(x)
