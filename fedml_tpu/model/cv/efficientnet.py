"""EfficientNet-lite — MBConv backbone (reference ``model/cv/efficientnet/``).

GroupNorm replaces BatchNorm (functional purity for jitted FL rounds; the
squeeze-excite block is kept). Width/depth multipliers follow the B0/B1
scaling; the "lite" simplification (no SE in stem/head, ReLU6 instead of
SiLU) mirrors the variants used on edge devices — the role this model plays
in the reference's zoo.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


class SqueezeExcite(nn.Module):
    filters: int
    se_ratio: float = 0.25

    @nn.compact
    def __call__(self, x):
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        s = nn.relu(nn.Conv(max(1, int(self.filters * self.se_ratio)),
                            (1, 1))(s))
        s = nn.sigmoid(nn.Conv(self.filters, (1, 1))(s))
        return x * s


class MBConv(nn.Module):
    filters_out: int
    expand: int
    kernel: int = 3
    strides: int = 1
    use_se: bool = True

    @nn.compact
    def __call__(self, x):
        filters_in = x.shape[-1]
        h = x
        if self.expand != 1:
            h = nn.Conv(filters_in * self.expand, (1, 1), use_bias=False)(h)
            h = nn.GroupNorm(num_groups=8)(h)
            h = nn.relu6(h)
        h = nn.Conv(h.shape[-1], (self.kernel, self.kernel),
                    strides=(self.strides, self.strides),
                    feature_group_count=h.shape[-1], use_bias=False)(h)
        h = nn.GroupNorm(num_groups=8)(h)
        h = nn.relu6(h)
        if self.use_se:
            h = SqueezeExcite(h.shape[-1])(h)
        h = nn.Conv(self.filters_out, (1, 1), use_bias=False)(h)
        h = nn.GroupNorm(num_groups=min(8, self.filters_out))(h)
        if self.strides == 1 and filters_in == self.filters_out:
            h = h + x
        return h


# (expand, filters, blocks, strides, kernel) per stage — B0 layout
_B0_STAGES: Sequence[Tuple[int, int, int, int, int]] = (
    (1, 16, 1, 1, 3),
    (6, 24, 2, 2, 3),
    (6, 40, 2, 2, 5),
    (6, 80, 3, 2, 3),
    (6, 112, 3, 1, 5),
    (6, 192, 4, 2, 5),
    (6, 320, 1, 1, 3),
)


class EfficientNetLite(nn.Module):
    num_classes: int
    width_mult: float = 1.0
    depth_mult: float = 1.0
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        def w(f):
            return max(8, int(f * self.width_mult + 4) // 8 * 8)

        def d(n):
            return max(1, round(n * self.depth_mult))

        x = nn.Conv(w(32), (3, 3), strides=(2, 2), use_bias=False)(x)
        x = nn.GroupNorm(num_groups=8)(x)
        x = nn.relu6(x)
        for expand, filters, blocks, strides, kernel in _B0_STAGES:
            for b in range(d(blocks)):
                x = MBConv(w(filters), expand, kernel,
                           strides if b == 0 else 1)(x)
        x = nn.Conv(w(1280), (1, 1), use_bias=False)(x)
        x = nn.GroupNorm(num_groups=8)(x)
        x = nn.relu6(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


def create_efficientnet(name: str, num_classes: int) -> EfficientNetLite:
    name = name.lower()
    scale = {"efficientnet": (1.0, 1.0), "efficientnet-b0": (1.0, 1.0),
             "efficientnet-b1": (1.0, 1.1), "efficientnet-b2": (1.1, 1.2)}
    wm, dm = scale.get(name, (1.0, 1.0))
    return EfficientNetLite(num_classes, width_mult=wm, depth_mult=dm)
