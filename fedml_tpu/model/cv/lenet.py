"""LeNet — the mobile/cross-device reference model.

Parity target: the reference's MNN LeNet shipped to phones
(``model/mobile/``, MobileNN dataset+trainer pairs) and the classic
LeNet-5 shape. Cross-device sessions default to it for MNIST-class tasks.
"""

from __future__ import annotations

import flax.linen as nn


class LeNet5(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(nn.Conv(6, (5, 5), padding="SAME")(x))
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(16, (5, 5), padding="VALID")(x))
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120)(x))
        x = nn.relu(nn.Dense(84)(x))
        return nn.Dense(self.num_classes)(x)
