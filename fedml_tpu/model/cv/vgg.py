"""VGG-11/16 with GroupNorm (reference ``model/cv/vgg.py``)."""

from __future__ import annotations

from typing import Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

_CFG = {
    "vgg11": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "vgg16": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M"),
}


class VGG(nn.Module):
    num_classes: int
    cfg: Sequence[Union[int, str]]
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = False):
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(v), (3, 3), use_bias=False)(x)
                x = nn.GroupNorm(num_groups=8)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


def create_vgg(name: str, num_classes: int) -> VGG:
    return VGG(num_classes, _CFG[name.lower()])
