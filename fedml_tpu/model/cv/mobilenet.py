"""MobileNetV3-Small (GN variant) — the cross-device/Beehive model family
(parity: reference ``model/cv/mobilenet_v3.py``, used by the FEMNIST
hierarchical benchmark). Depthwise convs map to XLA's feature-group convs."""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


def hard_swish(x):
    return x * nn.relu6(x + 3.0) / 6.0


def hard_sigmoid(x):
    return nn.relu6(x + 3.0) / 6.0


class SqueezeExcite(nn.Module):
    reduce: int = 4

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(max(c // self.reduce, 8))(s))
        s = hard_sigmoid(nn.Dense(c)(s))
        return x * s[:, None, None, :]


class InvertedResidual(nn.Module):
    exp: int
    out: int
    kernel: int
    stride: int
    use_se: bool
    use_hs: bool

    @nn.compact
    def __call__(self, x):
        act = hard_swish if self.use_hs else nn.relu
        inp = x.shape[-1]
        y = x
        if self.exp != inp:
            y = nn.Conv(self.exp, (1, 1), use_bias=False)(y)
            y = nn.GroupNorm(num_groups=min(8, self.exp))(y)
            y = act(y)
        y = nn.Conv(self.exp, (self.kernel, self.kernel),
                    strides=(self.stride, self.stride),
                    feature_group_count=self.exp, use_bias=False)(y)
        y = nn.GroupNorm(num_groups=min(8, self.exp))(y)
        y = act(y)
        if self.use_se:
            y = SqueezeExcite()(y)
        y = nn.Conv(self.out, (1, 1), use_bias=False)(y)
        y = nn.GroupNorm(num_groups=min(8, self.out))(y)
        if self.stride == 1 and inp == self.out:
            y = y + x
        return y


# (kernel, exp, out, SE, HS, stride) — MobileNetV3-Small spec
_V3_SMALL: Sequence[Tuple[int, int, int, bool, bool, int]] = (
    (3, 16, 16, True, False, 2),
    (3, 72, 24, False, False, 2),
    (3, 88, 24, False, False, 1),
    (5, 96, 40, True, True, 2),
    (5, 240, 40, True, True, 1),
    (5, 240, 40, True, True, 1),
    (5, 120, 48, True, True, 1),
    (5, 144, 48, True, True, 1),
    (5, 288, 96, True, True, 2),
    (5, 576, 96, True, True, 1),
    (5, 576, 96, True, True, 1),
)


class MobileNetV3Small(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 2:  # flat grayscale input
            side = int(round(x.shape[-1] ** 0.5))
            x = x.reshape((x.shape[0], side, side, 1))
        x = nn.Conv(16, (3, 3), strides=(2, 2), use_bias=False)(x)
        x = nn.GroupNorm(num_groups=8)(x)
        x = hard_swish(x)
        for k, e, o, se, hs, s in _V3_SMALL:
            x = InvertedResidual(e, o, k, s, se, hs)(x)
        x = nn.Conv(576, (1, 1), use_bias=False)(x)
        x = nn.GroupNorm(num_groups=8)(x)
        x = hard_swish(x)
        x = jnp.mean(x, axis=(1, 2))
        x = hard_swish(nn.Dense(1024)(x))
        return nn.Dense(self.num_classes)(x)
