"""MNIST GAN generator/discriminator (reference ``model/cv/mnist_gan.py``,
used by the FedGAN optimizer)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class Generator(nn.Module):
    latent_dim: int = 100
    img_dim: int = 784

    @nn.compact
    def __call__(self, z, train: bool = False):
        h = nn.relu(nn.Dense(256)(z))
        h = nn.relu(nn.Dense(512)(h))
        h = nn.relu(nn.Dense(1024)(h))
        return nn.tanh(nn.Dense(self.img_dim)(h))


class Discriminator(nn.Module):
    img_dim: int = 784

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        h = nn.leaky_relu(nn.Dense(512)(x), 0.2)
        h = nn.leaky_relu(nn.Dense(256)(h), 0.2)
        return nn.Dense(1)(h)
