"""Finance / vertical-FL party models.

Parity target: reference ``model/finance/`` (``vfl_classifier.py``,
``vfl_feature_extractor.py``, ``vfl_models_standalone.py`` — per-party
dense feature extractors + an interactive classifier for the lending-club /
NUS-WIDE vertical tasks). TPU-native: plain flax modules; the VFL
simulator (:mod:`fedml_tpu.simulation.sp.vertical_fl`) composes guest/host
extractors with the interactive head, and gradients cross party boundaries
as tensors out of one jitted backward.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class VFLFeatureExtractor(nn.Module):
    """One party's local tower over its vertical feature slice (reference
    ``vfl_feature_extractor.py`` LocalModel)."""
    out_dim: int = 32
    hidden: Sequence[int] = (64,)

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x.reshape((x.shape[0], -1))
        for w in self.hidden:
            h = nn.relu(nn.Dense(w)(h))
        return nn.Dense(self.out_dim)(h)


class VFLClassifier(nn.Module):
    """Interactive head over concatenated party representations (reference
    ``vfl_classifier.py`` DenseModel: a linear layer on the fused reps)."""
    num_classes: int = 2

    @nn.compact
    def __call__(self, fused, train: bool = False):
        return nn.Dense(self.num_classes)(fused)


class LendingClubMLP(nn.Module):
    """Tabular credit-risk MLP (the lending-club standalone baseline)."""
    num_classes: int = 2
    hidden: Sequence[int] = (128, 64)

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x.reshape((x.shape[0], -1))
        for w in self.hidden:
            h = nn.relu(nn.Dense(w)(h))
        return nn.Dense(self.num_classes)(h)
