"""Federated NLP RNNs.

Parity targets: ``model/nlp/rnn.py`` of the reference —
``RNN_OriginalFedAvg`` (the FedAvg-paper Shakespeare model: 8-dim embedding,
2×LSTM(256), per-token vocab logits) and ``RNN_StackOverFlow`` (NWP:
embedding 96, LSTM 670). Implemented with ``nn.RNN``/``OptimizedLSTMCell`` —
XLA unrolls the recurrence into one fused scan on TPU.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class RNNShakespeare(nn.Module):
    vocab_size: int = 90
    embedding_dim: int = 8
    hidden_size: int = 256
    num_layers: int = 2

    @nn.compact
    def __call__(self, x, train: bool = False):
        # x: [batch, seq_len] int tokens -> [batch, seq_len, vocab] logits
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x.astype(jnp.int32))
        for _ in range(self.num_layers):
            h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        return nn.Dense(self.vocab_size)(h)


class RNNStackOverflow(nn.Module):
    """Next-word prediction (reference ``RNN_StackOverFlow``)."""
    vocab_size: int = 10004
    embedding_dim: int = 96
    hidden_size: int = 670

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x.astype(jnp.int32))
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        h = nn.Dense(self.embedding_dim)(h)
        return nn.Dense(self.vocab_size)(h)
