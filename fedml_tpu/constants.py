"""Framework-wide constants.

Capability parity with the reference's ``python/fedml/constants.py:1-82``
(platform names, backend names, federated-optimizer names), re-targeted at a
TPU-native stack: the simulation backends are SP (golden python loop) and TPU
(mesh/`shard_map` collective round) instead of MPI/NCCL process groups.
"""

FEDML_TRAINING_PLATFORM_SIMULATION = "simulation"
FEDML_TRAINING_PLATFORM_CROSS_SILO = "cross_silo"
FEDML_TRAINING_PLATFORM_CROSS_DEVICE = "cross_device"
FEDML_TRAINING_PLATFORM_CROSS_CLOUD = "cross_cloud"
FEDML_TRAINING_PLATFORM_SERVING = "fedml_serving"

# Simulation backends (reference: SP / MPI / NCCL — here the collective
# backend is the TPU mesh; SP is kept as the golden semantics reference).
FEDML_SIMULATION_TYPE_SP = "sp"
FEDML_SIMULATION_TYPE_TPU = "tpu"
# Accepted aliases for reference-config compatibility: configs written for the
# reference's NCCL/MPI simulators run on the mesh backend unchanged.
FEDML_SIMULATION_BACKEND_ALIASES = {
    "sp": FEDML_SIMULATION_TYPE_SP,
    "single_process": FEDML_SIMULATION_TYPE_SP,
    "tpu": FEDML_SIMULATION_TYPE_TPU,
    "mesh": FEDML_SIMULATION_TYPE_TPU,
    "nccl": FEDML_SIMULATION_TYPE_TPU,
    "mpi": FEDML_SIMULATION_TYPE_TPU,
}

# Cross-silo scenarios (reference: cross_silo/__init__.py)
FEDML_CROSS_SILO_SCENARIO_HORIZONTAL = "horizontal"
FEDML_CROSS_SILO_SCENARIO_HIERARCHICAL = "hierarchical"

# Communication backends for the WAN boundary (reference §2.2).
COMM_BACKEND_LOCAL = "LOCAL"     # in-process queues (testing / single host)
COMM_BACKEND_GRPC = "GRPC"
COMM_BACKEND_TCP = "TCP"         # native framed-socket transport
COMM_BACKEND_MQTT = "MQTT"       # control/data-plane split, optional broker

GRPC_BASE_PORT = 8890
TCP_BASE_PORT = 9590

# Federated optimizers (reference constants.py:38-60 lists 22; the ones with
# per-round protocol semantics implemented as (client, server) transform pairs).
FEDML_FEDERATED_OPTIMIZER_FEDAVG = "FedAvg"
FEDML_FEDERATED_OPTIMIZER_FEDAVG_SEQ = "FedAvg_seq"
FEDML_FEDERATED_OPTIMIZER_FEDOPT = "FedOpt"
FEDML_FEDERATED_OPTIMIZER_FEDOPT_SEQ = "FedOpt_seq"
FEDML_FEDERATED_OPTIMIZER_FEDPROX = "FedProx"
FEDML_FEDERATED_OPTIMIZER_FEDNOVA = "FedNova"
FEDML_FEDERATED_OPTIMIZER_FEDDYN = "FedDyn"
FEDML_FEDERATED_OPTIMIZER_SCAFFOLD = "SCAFFOLD"
FEDML_FEDERATED_OPTIMIZER_MIME = "Mime"
FEDML_FEDERATED_OPTIMIZER_FEDSGD = "FedSGD"
FEDML_FEDERATED_OPTIMIZER_ASYNC_FEDAVG = "Async_FedAvg"
FEDML_FEDERATED_OPTIMIZER_HIERACHICAL_FL = "HierarchicalFL"
FEDML_FEDERATED_OPTIMIZER_TURBO_AGGREGATE = "TurboAggregate"
FEDML_FEDERATED_OPTIMIZER_VERTICAL_FL = "vertical_fl"
FEDML_FEDERATED_OPTIMIZER_SPLIT_NN = "split_nn"
FEDML_FEDERATED_OPTIMIZER_DECENTRALIZED_FL = "decentralized_fl"
FEDML_FEDERATED_OPTIMIZER_FEDGAN = "FedGAN"
FEDML_FEDERATED_OPTIMIZER_FEDGKT = "FedGKT"
FEDML_FEDERATED_OPTIMIZER_FEDNAS = "FedNAS"
FEDML_FEDERATED_OPTIMIZER_FEDSEG = "FedSeg"
FEDML_FEDERATED_OPTIMIZER_LSA = "LSA"
FEDML_FEDERATED_OPTIMIZER_SA = "SA"

# Cross-silo secure-aggregation optimizer names (reference fedml_client.py:1-64)
FEDML_CROSS_SILO_OPTIMIZER_SA = FEDML_FEDERATED_OPTIMIZER_SA
FEDML_CROSS_SILO_OPTIMIZER_LSA = FEDML_FEDERATED_OPTIMIZER_LSA

# Message-type constants shared by the round FSM
# (reference: simulation/mpi/fedavg/message_define.py:1-31).
MSG_TYPE_S2C_INIT_CONFIG = 1
MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2
MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 3
MSG_TYPE_C2S_CLIENT_STATUS = 4
MSG_TYPE_S2C_FINISH = 5
MSG_TYPE_CONNECTION_IS_READY = 0

MSG_ARG_KEY_TYPE = "msg_type"
MSG_ARG_KEY_SENDER = "sender"
MSG_ARG_KEY_RECEIVER = "receiver"
MSG_ARG_KEY_MODEL_PARAMS = "model_params"
MSG_ARG_KEY_MODEL_PARAMS_URL = "model_params_url"
MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
MSG_ARG_KEY_CLIENT_STATUS = "client_status"
MSG_ARG_KEY_ROUND_INDEX = "round_idx"

CLIENT_STATUS_ONLINE = "ONLINE"
CLIENT_STATUS_FINISHED = "FINISHED"

# Wire-efficiency for cross-silo updates (``comm_compression`` knobs):
# sparsification/quantization of the client->server update with per-client
# error feedback, plus the server->client sync dtype. Off by default —
# payloads stay byte-identical to the dense float32 path.
COMM_COMPRESSION_TOPK = "topk"
COMM_COMPRESSION_RANDK = "randk"
COMM_COMPRESSION_QSGD = "qsgd"
COMM_COMPRESSION_TOPK_QSGD = "topk_qsgd"
COMM_COMPRESSION_RANDK_QSGD = "randk_qsgd"
COMM_COMPRESSION_METHODS = (
    COMM_COMPRESSION_TOPK, COMM_COMPRESSION_RANDK, COMM_COMPRESSION_QSGD,
    COMM_COMPRESSION_TOPK_QSGD, COMM_COMPRESSION_RANDK_QSGD,
)
COMM_BROADCAST_FULL = "full"          # dense float32 server->client sync
COMM_BROADCAST_BF16 = "bf16"          # dense sync at half the bytes
COMM_BROADCAST_COMPRESS = "compress"  # sync ships the compressed global delta

# Robust-round fusion (``robust_fused`` knob): with a sharded-capable
# defense the whole defended round — training, model-attack injection,
# feature-sharded defense, central-DP noise, server transform — runs as
# ONE jitted SPMD program (and scans over rounds in fused blocks), so the
# update stack never leaves device. ``host`` keeps the 3-dispatch
# host-orchestrated pipeline (required by contribution assessment / user
# ServerAggregators / host-only defenses, which AUTO falls back to).
ROBUST_FUSED_AUTO = "auto"
ROBUST_FUSED_FUSED = "fused"
ROBUST_FUSED_HOST = "host"

# Mesh axis names — the vocabulary of the whole framework.
AXIS_CLIENT = "client"   # FL round-level data parallelism (one+ clients/chip)
AXIS_DATA = "data"       # intra-silo data parallelism (DDP analogue)
AXIS_FSDP = "fsdp"       # parameter sharding (ZeRO-3 analogue)
AXIS_TENSOR = "tensor"   # tensor parallelism
AXIS_SEQ = "sp"          # sequence/context parallelism (ring attention)
AXIS_EXPERT = "expert"   # expert parallelism
AXIS_PIPE = "pipe"       # pipeline parallelism
