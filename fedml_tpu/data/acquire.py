"""Real dataset acquisition: download, verify, cache as ``.npz``.

Parity target: the reference downloads raw archives per dataset at load time
(``data/data_loader.py:262-448``; MNIST zip URL in ``constants.py:36``). Here
acquisition is one module with per-dataset recipes that

* download from the canonical public mirrors (with sha256 verification),
* parse the raw formats (IDX for MNIST-family, python pickles for
  CIFAR) into ``x_train/y_train/x_test/y_test`` numpy arrays,
* cache the result as ``<cache_dir>/<name>.npz`` so every later ``load()``
  is a single mmap-friendly read.

Networkless environments: ``acquire()`` returns None on any download
failure; the caller decides whether a synthetic stand-in is acceptable
(loudly — see ``data_loader.load``). Some *real* datasets need no network at
all: scikit-learn ships the UCI digits/wine/breast-cancer sets in-package,
and those are first-class datasets here (``digits`` is the zero-egress way
to demonstrate honest real-data accuracy).
"""

from __future__ import annotations

import gzip
import hashlib
import io
import logging
import os
import pickle
import struct
import tarfile
import urllib.request
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

Arrays = Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]

# Canonical public mirrors. MNIST's original host throttles; the ossci
# mirror is the one torchvision uses.
_MNIST_URLS = {
    "train_x": ("https://ossci-datasets.s3.amazonaws.com/mnist/"
                "train-images-idx3-ubyte.gz",
                "440fcabf73cc546fa21475e81ea370265605f56be210a4024d2ca8f203523609"),
    "train_y": ("https://ossci-datasets.s3.amazonaws.com/mnist/"
                "train-labels-idx1-ubyte.gz",
                "3552534a0a558bbed6aed32b30c495cca23d567ec52cac8be1a0730e8010255c"),
    "test_x": ("https://ossci-datasets.s3.amazonaws.com/mnist/"
               "t10k-images-idx3-ubyte.gz",
               "8d422c7b0a1c1c79245a5bcf07fe86e33eeafee792b84584aec276f5a2dbc4e6"),
    "test_y": ("https://ossci-datasets.s3.amazonaws.com/mnist/"
               "t10k-labels-idx1-ubyte.gz",
               "f7ae60f92e00ec6debd23a6088c31dbd2371eca3ffa0defaefb259924204aec6"),
}
# TLS-integrity via the github mirror (the official S3 website endpoint is
# http-only and we refuse to cache unauthenticated bytes as real data)
_FASHION_URLS = {
    "train_x": ("https://github.com/zalandoresearch/fashion-mnist/raw/master/"
                "data/fashion/train-images-idx3-ubyte.gz", None),
    "train_y": ("https://github.com/zalandoresearch/fashion-mnist/raw/master/"
                "data/fashion/train-labels-idx1-ubyte.gz", None),
    "test_x": ("https://github.com/zalandoresearch/fashion-mnist/raw/master/"
               "data/fashion/t10k-images-idx3-ubyte.gz", None),
    "test_y": ("https://github.com/zalandoresearch/fashion-mnist/raw/master/"
               "data/fashion/t10k-labels-idx1-ubyte.gz", None),
}
_CIFAR10_URL = ("https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz",
                "6d958be074577803d12ecdefd02955f39262c83c16fe9348329d7fe0b5c001ce")
_CIFAR100_URL = ("https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz",
                 "85cd44d02ba6437773c5bbd22e183051d648de2e7d6b014e1ef29b855ba677a7")

_TIMEOUT_S = float(os.environ.get("FEDML_TPU_DOWNLOAD_TIMEOUT", "30"))


def _fetch(url: str, sha256: Optional[str]) -> bytes:
    logger.info("downloading %s", url)
    with urllib.request.urlopen(url, timeout=_TIMEOUT_S) as r:
        blob = r.read()
    if sha256:
        got = hashlib.sha256(blob).hexdigest()
        if got != sha256:
            raise IOError(f"checksum mismatch for {url}: {got}")
    return blob


def _parse_idx(blob: bytes) -> np.ndarray:
    """Parse an IDX file (the MNIST raw format)."""
    data = gzip.decompress(blob) if blob[:2] == b"\x1f\x8b" else blob
    magic, = struct.unpack(">I", data[:4])
    ndim = magic & 0xFF
    dims = struct.unpack(f">{ndim}I", data[4:4 + 4 * ndim])
    arr = np.frombuffer(data, np.uint8, offset=4 + 4 * ndim)
    return arr.reshape(dims)


def _mnist_like(urls: Dict[str, Tuple[str, Optional[str]]]) -> Arrays:
    parts = {k: _parse_idx(_fetch(u, s)) for k, (u, s) in urls.items()}
    return ((parts["train_x"], parts["train_y"].astype(np.int64)),
            (parts["test_x"], parts["test_y"].astype(np.int64)))


def _cifar(url: Tuple[str, Optional[str]]) -> Arrays:
    blob = _fetch(*url)
    label_key = b"fine_labels" if "100" in url[0] else b"labels"
    xs_tr: List[np.ndarray] = []
    ys_tr: List[np.ndarray] = []
    x_te = y_te = None
    with tarfile.open(fileobj=io.BytesIO(blob), mode="r:gz") as tf:
        for m in tf.getmembers():
            base = os.path.basename(m.name)
            is_train = base.startswith("data_batch") or base == "train"
            is_test = base.startswith("test_batch") or base == "test"
            if not (is_train or is_test):
                continue
            d = pickle.load(tf.extractfile(m), encoding="bytes")
            x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            y = np.asarray(d[label_key], np.int64)
            if is_train:
                xs_tr.append(x)
                ys_tr.append(y)
            else:
                x_te, y_te = x, y
    return ((np.concatenate(xs_tr), np.concatenate(ys_tr)), (x_te, y_te))


def _sklearn_bundled(name: str) -> Arrays:
    """Real UCI datasets shipped inside scikit-learn — no network needed."""
    from sklearn import datasets as skd
    loaders = {"digits": skd.load_digits, "wine": skd.load_wine,
               "breast_cancer": skd.load_breast_cancer}
    ds = loaders[name]()
    x = np.asarray(ds.data, np.float32)
    y = np.asarray(ds.target, np.int64)
    if name == "digits":
        x = x.reshape(-1, 8, 8) * (255.0 / 16.0)  # to image convention
    else:  # z-score tabular features
        x = (x - x.mean(0)) / (x.std(0) + 1e-8)
    # deterministic 80/20 split
    rs = np.random.RandomState(0)
    order = rs.permutation(len(x))
    n_te = max(1, len(x) // 5)
    te, tr = order[:n_te], order[n_te:]
    return ((x[tr], y[tr]), (x[te], y[te]))


# name -> (recipe fn, needs_network)
_RECIPES = {
    "mnist": (lambda: _mnist_like(_MNIST_URLS), True),
    "fashionmnist": (lambda: _mnist_like(_FASHION_URLS), True),
    "cifar10": (lambda: _cifar(_CIFAR10_URL), True),
    "cifar100": (lambda: _cifar(_CIFAR100_URL), True),
    "fed_cifar100": (lambda: _cifar(_CIFAR100_URL), True),
    "digits": (lambda: _sklearn_bundled("digits"), False),
    "wine": (lambda: _sklearn_bundled("wine"), False),
    "breast_cancer": (lambda: _sklearn_bundled("breast_cancer"), False),
}

BUNDLED_REAL = ("digits", "wine", "breast_cancer")


# raw-archive filenames recognized by the offline import path, per dataset
_ARCHIVE_NAMES = {
    "cifar10": ("cifar-10-python.tar.gz", "cifar10.tar.gz"),
    "cifar100": ("cifar-100-python.tar.gz", "cifar100.tar.gz"),
    "mnist": ("mnist.npz",),
    "fashionmnist": ("fashionmnist.npz",),
}


def _parse_local_archive(name: str, path: str) -> Arrays:
    """Parse a locally-provided raw archive (same formats the network
    recipes download) into arrays."""
    if path.endswith(".npz"):
        with np.load(path) as z:
            return ((z["x_train"], z["y_train"]), (z["x_test"], z["y_test"]))
    if name in ("cifar10", "cifar100"):
        label_key = b"fine_labels" if name == "cifar100" else b"labels"
        xs_tr, ys_tr = [], []
        x_te = y_te = None
        with tarfile.open(path, mode="r:gz") as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                is_train = base.startswith("data_batch") or base == "train"
                is_test = base.startswith("test_batch") or base == "test"
                if not (is_train or is_test):
                    continue
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
                y = np.asarray(d[label_key], np.int64)
                if is_train:
                    xs_tr.append(x)
                    ys_tr.append(y)
                else:
                    x_te, y_te = x, y
        return ((np.concatenate(xs_tr), np.concatenate(ys_tr)),
                (x_te, y_te))
    raise IOError(f"no offline parser for {name} archive {path!r}")


def import_archive(name: str, path: str,
                   cache_dir: Optional[str] = None) -> str:
    """OFFLINE dataset import: cache a locally-provided raw archive (the
    same file the network recipe would download — e.g. CIFAR-10's
    ``cifar-10-python.tar.gz`` — or a pre-built ``.npz``) so every later
    ``load()`` treats the dataset as real, no egress needed. Airgapped
    counterpart of the reference's download-at-load
    (``data/data_loader.py:262-448``). Returns the cached npz path."""
    from .data_loader import default_cache_dir
    cache_dir = os.path.expanduser(cache_dir or default_cache_dir())
    (xtr, ytr), (xte, yte) = _parse_local_archive(name, path)
    os.makedirs(cache_dir, exist_ok=True)
    out = os.path.join(cache_dir, f"{name}.npz")
    tmp = out + ".tmp.npz"
    np.savez_compressed(tmp, x_train=xtr, y_train=ytr, x_test=xte,
                        y_test=yte)
    os.replace(tmp, out)
    logger.info("imported %s archive %s -> %s", name, path, out)
    return out


def _find_local_archive(name: str) -> Optional[str]:
    """Look for a user-provided raw archive in ``$FEDML_TPU_OFFLINE_DIR``
    — and ONLY there. CIFAR archives are python pickles, so importing one
    executes whatever it deserializes; auto-importing from the generic
    (often shared) cache dir would turn any writable cache into a code
    path. Setting the env var is the explicit "I trust these archives"
    statement; without it, use :func:`import_archive` on a path you
    chose."""
    d = os.environ.get("FEDML_TPU_OFFLINE_DIR")
    if not d:
        return None
    for fname in _ARCHIVE_NAMES.get(name, ()):
        p = os.path.join(os.path.expanduser(d), fname)
        if os.path.exists(p):
            return p
    return None


def acquire(name: str, cache_dir: str) -> Optional[str]:
    """Materialize dataset ``name`` as ``<cache_dir>/<name>.npz``; returns the
    path, or None if the dataset has no recipe or acquisition failed (the
    caller decides how loudly to fall back). A raw archive dropped in
    ``$FEDML_TPU_OFFLINE_DIR`` (explicitly set — archives there are
    trusted input) is imported without any network — see
    :func:`import_archive`."""
    cache_dir = os.path.expanduser(cache_dir or ".")
    path = os.path.join(cache_dir, f"{name}.npz")
    if os.path.exists(path):
        return path
    local = _find_local_archive(name)
    if local is not None:
        try:
            return import_archive(name, local, cache_dir)
        except Exception as e:
            logger.warning("offline archive %s for %s unusable: %s",
                           local, name, e)
    if name not in _RECIPES:
        return None
    recipe, _ = _RECIPES[name]
    try:
        (xtr, ytr), (xte, yte) = recipe()
    except Exception as e:  # no network / bad mirror / parse error
        logger.warning("could not acquire %s: %s", name, e)
        return None
    os.makedirs(cache_dir, exist_ok=True)
    tmp = path + ".tmp.npz"
    np.savez_compressed(tmp, x_train=xtr, y_train=ytr, x_test=xte, y_test=yte)
    os.replace(tmp, path)
    logger.info("cached %s -> %s", name, path)
    return path
