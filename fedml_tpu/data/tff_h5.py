"""Readers for the TFF-style HDF5 on-disk formats the reference consumes.

Parity targets:
- ``fed_cifar100``: reference ``data/fed_cifar100/data_loader.py:1-202`` —
  TFF HDF5 pair (``fed_cifar100_train.h5`` / ``fed_cifar100_test.h5``)
  with groups ``examples/<client_id>/{image,label}``; client = the natural
  TFF partition.
- ``stackoverflow_nwp``: reference ``data/stackoverflow_nwp/`` — HDF5
  ``examples/<client_id>/tokens`` (space-separated sentences) plus the
  ``stackoverflow.word_count`` vocab file; preprocessing follows the TFF
  recipe exactly (top-10k vocab, bos/eos/pad + 1 oov bucket, windows of
  seq_len + 1, next-word labels).
- ``stackoverflow_lr``: reference ``data/stackoverflow_lr/`` — same HDF5
  shape plus ``stackoverflow.tag_count`` (json); input = mean bag-of-words
  over the top-10k vocab, target = multi-hot over the top-500 tags.

The readers consume a LOCAL cache dir only (no egress — drop the reference
dataset files under ``<data_cache_dir>/<name>/``); they produce the
framework-standard padded ``FederatedDataset`` so every simulator and WAN
runner uses them unchanged. Tiny checked-in fixtures
(``tests/fixtures/``) pin the exact on-disk format.
"""

from __future__ import annotations

import json
import logging
import os
from typing import List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

NWP_VOCAB = 10_000
NWP_SEQ_LEN = 20
LR_VOCAB = 10_000
LR_TAGS = 500


def _h5_pair(data_dir: str, train_name: str, test_name: str):
    tr, te = (os.path.join(data_dir, n) for n in (train_name, test_name))
    if not (os.path.exists(tr) and os.path.exists(te)):
        return None
    import h5py
    return h5py.File(tr, "r"), h5py.File(te, "r")


def _client_keys(h5, max_clients: Optional[int]) -> List[str]:
    """Client group names, capped BEFORE any data is read — the real TFF
    StackOverflow shard has ~342k clients; a 4-client run must not parse
    them all."""
    keys = sorted(h5["examples"].keys())
    return keys[:max_clients] if max_clients else keys


def _top_words(path: str, k: int) -> List[str]:
    """First token of the first ``k`` non-blank lines of a TFF
    ``*.word_count`` file (most frequent first)."""
    words: List[str] = []
    with open(path) as f:
        for line in f:
            if len(words) >= k:
                break
            if line.strip():
                words.append(line.split()[0])
    return words


# --------------------------------------------------------------- cifar100 --

def load_fed_cifar100(data_dir: str, batch_size: int,
                      max_clients: Optional[int] = None):
    """TFF federated CIFAR-100: natural client partition from the HDF5
    groups. Returns (FederatedDataset, 100) or None if files absent."""
    pair = _h5_pair(data_dir, "fed_cifar100_train.h5", "fed_cifar100_test.h5")
    if pair is None:
        return None
    from .containers import build_federated_dataset
    tr, te = pair
    try:
        cxs = [np.asarray(tr["examples"][c]["image"][()],
                          np.float32) / 255.0
               for c in _client_keys(tr, max_clients)]
        cys = [np.asarray(tr["examples"][c]["label"][()]).reshape(-1)
               .astype(np.int64) for c in _client_keys(tr, max_clients)]
        test_keys = _client_keys(te, None)
        test_x = np.concatenate(
            [np.asarray(te["examples"][c]["image"][()], np.float32) / 255.0
             for c in test_keys])
        test_y = np.concatenate(
            [np.asarray(te["examples"][c]["label"][()]).reshape(-1)
             .astype(np.int64) for c in test_keys])
        fed = build_federated_dataset(cxs, cys, test_x, test_y,
                                      batch_size, 100)
        fed.provenance = "real"
        return fed, 100
    finally:
        tr.close()
        te.close()


# ------------------------------------------------------- stackoverflow nwp --

def _nwp_vocab(data_dir: str, vocab_size: int) -> dict:
    """word -> id, TFF layout: [pad] + top-k words + [bos] + [eos]; OOV
    hashes into 1 bucket after that (reference utils.py:57-62)."""
    words = _top_words(os.path.join(data_dir, "stackoverflow.word_count"),
                       vocab_size)
    vocab = {"<pad>": 0}
    for i, w in enumerate(words):
        vocab[w] = i + 1
    vocab["<bos>"] = len(vocab)
    vocab["<eos>"] = len(vocab)
    return vocab


def _nwp_to_ids(sentence: str, vocab: dict, seq_len: int) -> List[int]:
    """TFF tokenization (reference ``stackoverflow_nwp/utils.py:54-79``):
    truncate to seq_len words, map OOV to the single bucket after eos,
    append eos when room, prepend bos, pad to seq_len + 1."""
    oov = len(vocab)
    toks = [vocab.get(w, oov) for w in sentence.split(" ")[:seq_len]]
    if len(toks) < seq_len:
        toks = toks + [vocab["<eos>"]]
    toks = [vocab["<bos>"]] + toks
    toks += [vocab["<pad>"]] * (seq_len + 1 - len(toks))
    return toks[:seq_len + 1]


def load_stackoverflow_nwp(data_dir: str, batch_size: int,
                           max_clients: Optional[int] = None,
                           vocab_size: int = NWP_VOCAB,
                           seq_len: int = NWP_SEQ_LEN):
    """Next-word prediction over the TFF StackOverflow shard: x = ids[:-1],
    y = ids[1:] (per-token labels, sequence task). Returns
    (FederatedDataset, vocab_size + 4) or None if files absent."""
    pair = _h5_pair(data_dir, "stackoverflow_train.h5",
                    "stackoverflow_test.h5")
    if pair is None:
        return None
    from .containers import build_federated_dataset
    tr, te = pair
    try:
        vocab = _nwp_vocab(data_dir, vocab_size)
        n_ids = len(vocab) + 1  # + oov bucket

        def client_ids(h5, cap):
            xs, ys = [], []
            ex = h5["examples"]
            for cid in _client_keys(h5, cap):
                sents = [s.decode() if isinstance(s, bytes) else str(s)
                         for s in ex[cid]["tokens"][()]]
                ids = np.asarray([_nwp_to_ids(s, vocab, seq_len)
                                  for s in sents], np.int32)
                xs.append(ids[:, :-1])
                ys.append(ids[:, 1:])
            return xs, ys

        cxs, cys = client_ids(tr, max_clients)
        txs, tys = client_ids(te, None)
        fed = build_federated_dataset(
            cxs, cys, np.concatenate(txs), np.concatenate(tys),
            batch_size, n_ids, dtype=np.int32, task="sequence")
        fed.provenance = "real"
        return fed, n_ids
    finally:
        tr.close()
        te.close()


# -------------------------------------------------------- stackoverflow lr --

def load_stackoverflow_lr(data_dir: str, batch_size: int,
                          max_clients: Optional[int] = None,
                          vocab_size: int = LR_VOCAB,
                          tag_size: int = LR_TAGS):
    """Tag prediction (multilabel logistic regression) over the TFF
    StackOverflow shard: input = mean bag-of-words of the post tokens over
    the top-``vocab_size`` words, target = multi-hot over the top-
    ``tag_size`` tags (reference ``stackoverflow_lr/utils.py:68-107``).
    Returns (FederatedDataset, tag_size) or None if files absent."""
    pair = _h5_pair(data_dir, "stackoverflow_train.h5",
                    "stackoverflow_test.h5")
    if pair is None:
        return None
    from .containers import build_federated_dataset
    tr, te = pair
    try:
        words = _top_words(
            os.path.join(data_dir, "stackoverflow.word_count"), vocab_size)
        word_id = {w: i for i, w in enumerate(words)}
        with open(os.path.join(data_dir, "stackoverflow.tag_count")) as f:
            tag_id = {t: i for i, t in
                      enumerate(list(json.load(f).keys())[:tag_size])}
        n_words, n_tags = len(word_id), len(tag_id)

        def bow(sentence: str) -> np.ndarray:
            # mean one-hot over tokens; OOV occupies a dropped overflow
            # column, exactly the reference's [:vocab_size] slice
            v = np.zeros(n_words + 1, np.float32)
            toks = sentence.split(" ")
            for t in toks:
                v[word_id.get(t, n_words)] += 1.0
            return v[:n_words] / max(len(toks), 1)

        def multihot(tags: str) -> np.ndarray:
            v = np.zeros(n_tags + 1, np.float32)
            for t in tags.split("|"):
                v[tag_id.get(t, n_tags)] = 1.0
            return v[:n_tags]

        def client_arrays(h5, cap):
            xs, ys = [], []
            ex = h5["examples"]
            for cid in _client_keys(h5, cap):
                sents = [s.decode() if isinstance(s, bytes) else str(s)
                         for s in ex[cid]["tokens"][()]]
                tags = [s.decode() if isinstance(s, bytes) else str(s)
                        for s in ex[cid]["tags"][()]]
                xs.append(np.stack([bow(s) for s in sents]))
                ys.append(np.stack([multihot(t) for t in tags]))
            return xs, ys

        cxs, cys = client_arrays(tr, max_clients)
        txs, tys = client_arrays(te, None)
        fed = build_federated_dataset(
            cxs, cys, np.concatenate(txs), np.concatenate(tys),
            batch_size, n_tags, task="multilabel")
        fed.provenance = "real"
        return fed, n_tags
    finally:
        tr.close()
        te.close()


_LOADERS = {
    "fed_cifar100": load_fed_cifar100,
    "stackoverflow_nwp": load_stackoverflow_nwp,
    "stackoverflow_lr": load_stackoverflow_lr,
}


def load_tff_dataset(name: str, data_dir: str, batch_size: int,
                     max_clients: Optional[int] = None):
    """Dispatch: (FederatedDataset, output_dim) from a local cache of the
    reference's on-disk files, or None when the files are not present."""
    fn = _LOADERS.get(name)
    if fn is None:
        return None
    got = fn(data_dir, batch_size, max_clients)
    if got is not None:
        logger.info("loaded %s from local TFF cache %s (%d clients)",
                    name, data_dir, int(got[0].num_clients))
    return got
