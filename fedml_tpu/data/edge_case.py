"""Edge-case backdoor evaluation set.

Parity target: reference ``data/edge_case_examples/`` — out-of-distribution
samples of a source class (e.g. Southwest-livery planes for CIFAR) that a
backdoor adversary trains with a TARGET label; attack success is measured
as the fraction of HELD-OUT edge-case samples the poisoned global model
assigns to the target.

Here edge cases are DERIVED from the task's real data instead of shipped
as a separate download: source-class samples under a fixed strong
transform (intensity inversion + transpose) — far enough off-distribution
that a clean model handles them poorly, consistent enough that a backdoor
generalizes across them. Works for any image-shaped or flat-square-image
dataset in the zoo.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class EdgeCaseSet:
    """Poison split (for the adversary's shards) + a held-out eval split."""
    x_poison: np.ndarray
    x_eval: np.ndarray
    source_label: int
    target_label: int


def _transform(x: np.ndarray) -> np.ndarray:
    """Fixed off-distribution transform: invert intensities about the
    per-set max and transpose the spatial axes."""
    flat = x.ndim == 2
    if flat:
        side = int(round(x.shape[-1] ** 0.5))
        x = x.reshape(len(x), side, side)
        out = (x.max() - x).transpose(0, 2, 1)
        return out.reshape(len(out), -1)
    return (x.max() - x).swapaxes(1, 2)


def build_edge_case_set(x: np.ndarray, y: np.ndarray, source_label: int,
                        target_label: int, eval_fraction: float = 0.5,
                        seed: int = 0) -> EdgeCaseSet:
    """Select real samples of ``source_label``, transform them, and split
    into a poison half (train with ``target_label``) and an eval half."""
    x = np.asarray(x)
    y = np.asarray(y).reshape(-1)
    idx = np.flatnonzero(y == source_label)
    if len(idx) < 4:
        raise ValueError(f"too few source-class samples ({len(idx)})")
    rng = np.random.RandomState(seed)
    rng.shuffle(idx)
    edge = _transform(x[idx])
    n_eval = max(int(len(idx) * eval_fraction), 1)
    return EdgeCaseSet(x_poison=edge[n_eval:], x_eval=edge[:n_eval],
                       source_label=int(source_label),
                       target_label=int(target_label))


def attack_success_rate(predict_fn, edge: EdgeCaseSet) -> float:
    """Fraction of held-out edge-case samples classified as the TARGET
    label. ``predict_fn(x) -> [n] int predictions``."""
    preds = np.asarray(predict_fn(edge.x_eval)).reshape(-1)
    return float((preds == edge.target_label).mean())


def inject_edge_cases(fed, edge: EdgeCaseSet, byzantine_mask: np.ndarray):
    """Overwrite the leading samples of each byzantine client's shard with
    edge-case samples labeled TARGET (the reference adversary's data
    poisoning). Returns a new FederatedDataset; clean clients untouched."""
    import dataclasses as _dc

    x = np.array(fed.train.x)
    y = np.array(fed.train.y)
    m = np.array(fed.train.mask)
    n_poison = len(edge.x_poison)
    if n_poison == 0:
        return fed
    for cid in np.flatnonzero(np.asarray(byzantine_mask) > 0):
        flat_x = x[cid].reshape((-1,) + x.shape[3:])
        flat_y = y[cid].reshape(-1)
        flat_m = m[cid].reshape(-1)
        real = np.flatnonzero(flat_m > 0)
        take = real[:min(n_poison, len(real))]
        flat_x[take] = edge.x_poison[:len(take)].reshape(
            (len(take),) + flat_x.shape[1:])
        flat_y[take] = edge.target_label
        x[cid] = flat_x.reshape(x.shape[1:])
        y[cid] = flat_y.reshape(y.shape[1:])
    return _dc.replace(fed, train=fed.train.replace(x=x, y=y))
