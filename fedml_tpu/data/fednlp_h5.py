"""Reader for the FedNLP HDF5 on-disk format the reference consumes.

Parity target: ``data/fednlp/base/raw_data/base_raw_data_loader.py:38-45``
(the data file: ``attributes`` JSON + per-example ``X/<idx>`` text and
``Y/<idx>`` label datasets) and
``base/data_manager/base_data_manager.py:53-127`` (the partition file:
``<method>/n_clients`` + ``<method>/partition_data/<client>/{train,test}``
index lists). Datasets in this format: 20news, agnews, sst_2, semeval —
the reference's text-classification FedNLP tasks.

TPU-native redesign: instead of the reference's HF-tokenizer preprocessing
pipeline (network-dependent), texts are byte-tokenized to a fixed
``max_len`` (the same zero-egress tokenizer the LLM stack uses), producing
the framework-standard padded ``FederatedDataset`` so every simulator and
WAN runner consumes FedNLP shards unchanged. Drop the reference's
``<task>_data.h5`` + ``<task>_partition.h5`` under
``<data_cache_dir>/fednlp_<task>/`` — read locally, no network. A tiny
checked-in fixture pins the exact on-disk format.
"""

from __future__ import annotations

import json
import logging
import os
from typing import List, Optional

import numpy as np

logger = logging.getLogger(__name__)

MAX_LEN = 128


def _byte_ids(text: str, max_len: int) -> List[int]:
    """THE LLM stack's ByteTokenizer id space (one tokenizer for the
    whole framework — FedNLP shards stay directly consumable by
    LLM-stack components), truncated and padded with its pad id."""
    from ..llm.data import ByteTokenizer
    tok = ByteTokenizer()
    ids = tok.encode(text)[:max_len]
    return ids + [tok.pad_id] * (max_len - len(ids))


def load_fednlp_text_classification(data_dir: str, batch_size: int,
                                    max_clients: Optional[int] = None,
                                    partition_method: Optional[str] = None,
                                    max_len: int = MAX_LEN):
    """(FederatedDataset, num_labels) from a local FedNLP cache, or None
    when the files are absent. ``data_dir`` holds ``<task>_data.h5`` and
    ``<task>_partition.h5`` (any single task per dir)."""
    try:
        names = sorted(os.listdir(data_dir))
    except OSError:
        return None
    data_files = [n for n in names if n.endswith("_data.h5")]
    part_files = [n for n in names if n.endswith("_partition.h5")]
    if not data_files or not part_files:
        return None
    import contextlib

    import h5py

    from .containers import build_federated_dataset
    with contextlib.ExitStack() as stack:
        try:
            data_f = stack.enter_context(
                h5py.File(os.path.join(data_dir, data_files[0]), "r"))
            part_f = stack.enter_context(
                h5py.File(os.path.join(data_dir, part_files[0]), "r"))
        except OSError as e:  # corrupt/truncated cache: not-present
            logger.warning("unusable FedNLP cache in %s: %s", data_dir, e)
            return None
        attrs = json.loads(data_f["attributes"][()])
        label_vocab = attrs.get("label_vocab") or {}
        if not label_vocab:  # derive from the labels present
            seen = sorted({_as_str(data_f["Y"][k][()])
                           for k in data_f["Y"]})
            label_vocab = {lab: i for i, lab in enumerate(seen)}
        num_labels = int(attrs.get("num_labels") or len(label_vocab))

        def label_id(lab: str) -> int:
            """An INCOMPLETE declared vocab (partial/corrupt cache) would
            KeyError on the first undeclared label — extend the vocab on
            the fly instead (ids after the declared ones; num_labels is
            re-widened below before it is used). Lazy so the healthy
            complete-vocab path never pays a full Y scan."""
            idx = label_vocab.get(lab)
            if idx is None:
                idx = max(label_vocab.values(), default=-1) + 1
                label_vocab[lab] = idx
                logger.warning(
                    "FedNLP label_vocab in %s lacks label %r present in "
                    "Y; extending the vocab (id %d)", data_files[0], lab,
                    idx)
            return idx

        avail = list(part_f.keys())
        if partition_method and partition_method in part_f:
            method = partition_method
        else:
            method = avail[0]
            if partition_method and partition_method != method:
                logger.warning(
                    "FedNLP partition method %r not in %s (available: "
                    "%s); using %r", partition_method, part_files[0],
                    avail, method)
        part = part_f[method]["partition_data"]
        client_ids = sorted(part.keys(), key=lambda s: int(s))
        if max_clients:
            client_ids = client_ids[:int(max_clients)]

        def read(idx_list):
            if not idx_list:  # sparse niid partitions can leave a client
                # empty — keep the (0, max_len) shape so stacking works
                return (np.zeros((0, max_len), np.int32),
                        np.zeros((0,), np.int64))
            xs = np.asarray([_byte_ids(_as_str(data_f["X"][str(i)][()]),
                                       max_len) for i in idx_list],
                            np.int32)
            ys = np.asarray([label_id(_as_str(data_f["Y"][str(i)][()]))
                             for i in idx_list], np.int64)
            return xs, ys

        cxs, cys, test_chunks = [], [], []
        for cid in client_ids:
            tr_idx = list(part[cid]["train"][()])
            te_idx = list(part[cid]["test"][()])
            x, y = read(tr_idx)
            cxs.append(x)
            cys.append(y)
            if te_idx:
                test_chunks.append(read(te_idx))
        if not test_chunks:
            return None
        max_id = max(label_vocab.values(), default=-1)
        if num_labels <= max_id:  # every id must fit the output dim
            num_labels = max_id + 1
        test_x = np.concatenate([c[0] for c in test_chunks])
        test_y = np.concatenate([c[1] for c in test_chunks])
        fed = build_federated_dataset(cxs, cys, test_x, test_y,
                                      batch_size, num_labels,
                                      dtype=np.int32)
        fed.provenance = "real"
        logger.info("loaded FedNLP %s from %s: %d clients, %d labels",
                    data_files[0], data_dir, len(client_ids), num_labels)
        return fed, num_labels


def _as_str(v) -> str:
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, np.ndarray):  # utf8-typed scalar arrays
        return _as_str(v.item() if v.shape == () else v.tolist()[0])
    return str(v)
