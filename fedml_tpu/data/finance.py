"""Finance / vertical-FL datasets: lending-club loans and NUS-WIDE.

Parity targets: reference ``data/lending_club_loan/`` (loan table,
``loan_status`` label, features column-split between parties for the VFL
classifier ``model/finance/vfl_classifier.py``) and ``data/NUS_WIDE/``
(634-d low-level image features for party A, 1000-d tag vector for party
B, concept labels).

Acquisition policy matches the rest of ``data/``: these sets cannot be
bundled (licensed / hundreds of MB), so the loaders read preprocessed CSVs
from the disk cache — ``<cache>/lending_club/loan.csv`` with the label in
a ``loan_status`` (or last) column, ``<cache>/nus_wide/{features,tags,
labels}.csv`` — and only fall back to a loudly-labeled schema-matched
synthetic stand-in when the caller opted in (``allow_synthetic``).
"""

from __future__ import annotations

import csv
import logging
import os
from typing import Tuple

import numpy as np

logger = logging.getLogger(__name__)

# the reference's numeric feature schema for lending club (a stable subset
# of lending_club_loan/loan_processed.py) — used both to read real CSVs and
# to shape the synthetic stand-in
LENDING_CLUB_FEATURES = (
    "loan_amnt", "int_rate", "installment", "annual_inc", "dti",
    "delinq_2yrs", "fico_range_low", "open_acc", "pub_rec", "revol_bal",
    "revol_util", "total_acc",
)
NUS_WIDE_LOW_LEVEL_DIM = 634
NUS_WIDE_TAG_DIM = 1000


def _read_csv_table(path: str):
    with open(path, newline="") as f:
        reader = csv.reader(f)
        header = next(reader)
        rows = [r for r in reader if r]
    return header, rows


def load_lending_club(cache_dir: str) -> Tuple[np.ndarray, np.ndarray]:
    """``<cache>/lending_club/loan.csv`` -> (x [n, d] float32 standardized,
    y [n] int {0: fully paid, 1: charged off}). Label column:
    ``loan_status`` if present (string statuses mapped), else the last
    column (numeric)."""
    path = os.path.join(cache_dir, "lending_club", "loan.csv")
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    header, rows = _read_csv_table(path)
    cols = {c.strip().lower(): i for i, c in enumerate(header)}
    if "loan_status" in cols:
        li = cols["loan_status"]
        feat_idx = [cols[c] for c in LENDING_CLUB_FEATURES if c in cols]
        if not feat_idx:  # arbitrary numeric table: all non-label columns
            feat_idx = [i for i in range(len(header)) if i != li]
    else:
        li = len(header) - 1
        feat_idx = list(range(len(header) - 1))

    def label_of(v: str) -> int:
        v = v.strip().lower()
        if v in ("charged off", "default", "1", "late (31-120 days)"):
            return 1
        if v in ("fully paid", "0", "current"):
            return 0
        return -1  # unmapped status: dropped

    xs, ys = [], []
    for r in rows:
        lab = label_of(r[li])
        if lab < 0:
            continue
        try:
            xs.append([float(r[i] or 0.0) for i in feat_idx])
        except ValueError:
            continue
        ys.append(lab)
    x = np.asarray(xs, np.float32)
    y = np.asarray(ys, np.int32)
    mu, sd = x.mean(0, keepdims=True), x.std(0, keepdims=True) + 1e-6
    return (x - mu) / sd, y


def load_nus_wide(cache_dir: str) -> Tuple[np.ndarray, np.ndarray]:
    """``<cache>/nus_wide/`` -> (x = [low-level features | tags] float32,
    y [n] int concept). The column concatenation IS the vertical split:
    party A gets the first 634 columns, party B the tag block — matching
    the reference's two-party NUS-WIDE experiment."""
    d = os.path.join(cache_dir, "nus_wide")
    feats = np.loadtxt(os.path.join(d, "features.csv"), delimiter=",",
                       dtype=np.float32, ndmin=2)
    tags = np.loadtxt(os.path.join(d, "tags.csv"), delimiter=",",
                      dtype=np.float32, ndmin=2)
    labels = np.loadtxt(os.path.join(d, "labels.csv"), delimiter=",",
                        dtype=np.int64, ndmin=1).astype(np.int32)
    if not (len(feats) == len(tags) == len(labels)):
        raise ValueError("nus_wide: features/tags/labels row counts differ")
    x = np.concatenate([feats, tags], axis=1)
    mu, sd = x.mean(0, keepdims=True), x.std(0, keepdims=True) + 1e-6
    return (x - mu) / sd, labels


def synthetic_lending_club(n: int = 4000, seed: int = 0):
    """Schema-matched stand-in: default risk is a noisy logistic function
    of rate/dti/income — same column meanings, same label semantics."""
    rng = np.random.RandomState(seed)
    d = len(LENDING_CLUB_FEATURES)
    x = rng.randn(n, d).astype(np.float32)
    logits = 1.2 * x[:, 1] + 0.8 * x[:, 4] - 0.9 * x[:, 3] + \
        0.4 * rng.randn(n)
    y = (logits > 0).astype(np.int32)
    return x, y


def synthetic_nus_wide(n: int = 2000, n_concepts: int = 5, seed: int = 0,
                       feat_dim: int = 64, tag_dim: int = 96):
    """Stand-in with the two-block vertical structure (scaled-down dims so
    tests stay fast); label depends on BOTH blocks, so a single party
    cannot solve the task alone — the property VFL experiments need."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, n_concepts, size=n).astype(np.int32)
    proto_f = rng.randn(n_concepts, feat_dim).astype(np.float32)
    proto_t = rng.randn(n_concepts, tag_dim).astype(np.float32)
    feats = proto_f[y] + 1.2 * rng.randn(n, feat_dim).astype(np.float32)
    tags = (proto_t[y] + 1.2 * rng.randn(n, tag_dim).astype(np.float32))
    return np.concatenate([feats, tags], axis=1), y
