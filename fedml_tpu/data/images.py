"""Image-directory dataset readers: ImageNet folder layout + Landmarks.

Parity targets:
- ``ImageNet``: reference ``data/ImageNet/data_loader.py:1-411`` — an
  ImageFolder tree (``train/<wnid>/*.JPEG``, ``val/<wnid>/*.JPEG``)
  consumed through torchvision; here the tree is read with PIL straight
  into the framework's padded arrays, then federated with the standard
  partitioners (the reference also partitions centrally-loaded ImageNet).
- ``Landmarks`` (gld23k/gld160k): reference
  ``data/Landmarks/data_loader.py:123-151`` — CSV mappings with
  ``user_id,image_id,class`` rows give the NATURAL per-user federated
  partition; images live under ``<data_dir>/images/<image_id>.jpg``.

Both read a LOCAL cache dir only (no egress — drop the dataset under
``<data_cache_dir>/<name>/``); images are decoded once, resized to a
square ``image_size`` and normalized to [0, 1] float32.
"""

from __future__ import annotations

import csv
import logging
import os
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def _load_image(path: str, image_size: int) -> np.ndarray:
    from PIL import Image
    with Image.open(path) as im:
        im = im.convert("RGB").resize((image_size, image_size))
        return np.asarray(im, np.float32) / 255.0


# in-memory budget for eagerly-decoded image datasets: above this, refuse
# loudly instead of silently OOMing the host mid-decode. Full ILSVRC2012
# at 64px float32 would need ~63 GB; that scale needs a streaming/HDF5
# pipeline, not this eager loader.
MAX_EAGER_BYTES = 8 << 30


def _folder_split(root: str, image_size: int,
                  class_to_id: Optional[Dict[str, int]] = None):
    """One ImageFolder split: class subdirs -> (x, y, class_to_id)."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    if class_to_id is None:
        class_to_id = {c: i for i, c in enumerate(classes)}
    paths, ys = [], []
    for c in classes:
        cid = class_to_id.get(c)
        if cid is None:
            continue
        cdir = os.path.join(root, c)
        for fname in sorted(os.listdir(cdir)):
            if fname.lower().endswith(_IMG_EXTS):
                paths.append(os.path.join(cdir, fname))
                ys.append(cid)
    if not paths:
        raise FileNotFoundError(f"no images under {root}")
    need = len(paths) * image_size * image_size * 3 * 4
    if need > MAX_EAGER_BYTES:
        raise MemoryError(
            f"{root}: {len(paths)} images at {image_size}px need "
            f"~{need / 2**30:.0f} GiB decoded — beyond the eager loader's "
            f"{MAX_EAGER_BYTES >> 30} GiB budget. Use a class/sample "
            "subset of the tree, a smaller image_size, or a streaming "
            "pipeline for full-scale ImageNet.")
    xs = [_load_image(p, image_size) for p in paths]
    return np.stack(xs), np.asarray(ys, np.int64), class_to_id


def load_image_folder(data_dir: str, image_size: int = 64):
    """ImageNet-style tree -> ((xtr, ytr), (xte, yte), n_classes), or None
    when the tree is absent. ``val``/``test`` both accepted for the eval
    split; missing eval split falls back to a held-out tail of train."""
    train_dir = os.path.join(data_dir, "train")
    if not os.path.isdir(train_dir):
        return None
    xtr, ytr, cmap = _folder_split(train_dir, image_size)
    for split in ("val", "test"):
        sdir = os.path.join(data_dir, split)
        if os.path.isdir(sdir):
            xte, yte, _ = _folder_split(sdir, image_size, cmap)
            break
    else:
        n_te = max(1, len(xtr) // 10)
        rs = np.random.RandomState(0)
        order = rs.permutation(len(xtr))
        te, tr = order[:n_te], order[n_te:]
        xtr, ytr, xte, yte = xtr[tr], ytr[tr], xtr[te], ytr[te]
    logger.info("image folder %s: %d train / %d eval images, %d classes",
                data_dir, len(xtr), len(xte), len(cmap))
    return (xtr, ytr), (xte, yte), len(cmap)


def _read_mapping(path: str) -> "OrderedDict[str, List[dict]]":
    """user_id -> rows, preserving file order (reference
    ``Landmarks/data_loader.py:123-151``)."""
    per_user: "OrderedDict[str, List[dict]]" = OrderedDict()
    with open(path) as f:
        reader = csv.DictReader(f)
        cols = set(reader.fieldnames or ())
        if not {"user_id", "image_id", "class"} <= cols:
            raise ValueError(
                f"{path}: mapping must have user_id,image_id,class "
                f"columns (got {sorted(cols)})")
        for row in reader:
            per_user.setdefault(row["user_id"], []).append(row)
    return per_user


def _find_image(images_dir: str, image_id: str) -> Optional[str]:
    for ext in _IMG_EXTS:
        p = os.path.join(images_dir, image_id + ext)
        if os.path.exists(p):
            return p
    return None


def load_landmarks(data_dir: str, image_size: int = 64,
                   max_clients: Optional[int] = None):
    """Google-Landmarks-style federated dataset from a local cache:
    ``federated_train.csv`` (+ optional ``test.csv``) mappings + an
    ``images/`` dir. Returns (client_xs, client_ys, test_x, test_y,
    n_classes) with the NATURAL per-user partition, or None if the
    mapping files are absent."""
    train_csv = None
    for cand in ("federated_train.csv", "mini_gld_train_split.csv",
                 "train.csv"):
        p = os.path.join(data_dir, cand)
        if os.path.exists(p):
            train_csv = p
            break
    if train_csv is None:
        return None
    images_dir = os.path.join(data_dir, "images")
    per_user = _read_mapping(train_csv)
    users = list(per_user)
    if max_clients:
        users = users[:max_clients]
    classes = sorted({row["class"] for u in users for row in per_user[u]})
    class_id = {c: i for i, c in enumerate(classes)}
    client_xs, client_ys = [], []
    for u in users:
        xs, ys = [], []
        for row in per_user[u]:
            p = _find_image(images_dir, row["image_id"])
            if p is None:
                logger.warning("landmarks: missing image %s",
                               row["image_id"])
                continue
            xs.append(_load_image(p, image_size))
            ys.append(class_id[row["class"]])
        if xs:
            client_xs.append(np.stack(xs))
            client_ys.append(np.asarray(ys, np.int64))
    test_csv = None
    for cand in ("test.csv", "mini_gld_test.csv"):
        p = os.path.join(data_dir, cand)
        if os.path.exists(p):
            test_csv = p
            break
    xs, ys = [], []
    if test_csv is not None:
        with open(test_csv) as f:
            for row in csv.DictReader(f):
                p = _find_image(images_dir, row["image_id"])
                if p is not None and row["class"] in class_id:
                    xs.append(_load_image(p, image_size))
                    ys.append(class_id[row["class"]])
        if not xs:
            logger.warning(
                "landmarks: %s matched no usable rows (missing images or "
                "classes outside the train mapping) — falling back to "
                "held-out per-client test samples", test_csv)
    if xs:
        test_x, test_y = np.stack(xs), np.asarray(ys, np.int64)
    else:
        # no test mapping: hold out ONE sample per multi-image client.
        # Single-image clients contribute nothing — duplicating their only
        # sample into both splits would evaluate on training data.
        test_x = np.stack([cx[-1] for cx in client_xs if len(cx) > 1])
        test_y = np.asarray([cy[-1] for cx, cy in
                             zip(client_xs, client_ys) if len(cx) > 1],
                            np.int64)
        if len(test_x) == 0:
            raise ValueError(
                f"{data_dir}: cannot build a test split — no test csv and "
                "every user has a single image")
        client_xs = [cx[:-1] if len(cx) > 1 else cx for cx in client_xs]
        client_ys = [cy[:-1] if len(cy) > 1 else cy for cy in client_ys]
    logger.info("landmarks %s: %d users, %d classes", data_dir,
                len(client_xs), len(class_id))
    return client_xs, client_ys, test_x, test_y, len(class_id)
