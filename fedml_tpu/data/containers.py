"""Federated dataset containers: ragged per-client data → static padded
arrays.

The reference hands each client a torch ``DataLoader`` built per process
(``data/data_loader.py:234`` returns the 8-tuple of dicts keyed by client
idx). On TPU, per-client data must be a *tensor* so a whole round can jit:
clients are stacked on a leading axis, padded to a common
``[n_batches, batch_size]`` shape with an explicit mask, and the per-client
sample count rides along as the aggregation weight (SURVEY §7 hard part
"per-client data heterogeneity inside jit").
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.algframe.types import ClientData


@dataclasses.dataclass
class FederatedDataset:
    """Host-side container for one FL task.

    ``train``: ClientData with leaves stacked on a leading [num_clients] axis.
    ``test``: global test set, batched: {"x": [nb, bs, ...], "y", "mask"}.
    """
    train: ClientData
    test: Dict[str, jnp.ndarray]
    num_classes: int
    input_shape: Tuple[int, ...]
    num_clients: int
    client_num_samples: np.ndarray  # [num_clients] int — true n_k
    # task selects the TrainerSpec: classification | sequence | multilabel |
    # regression (reference encodes this in per-dataset trainer choices,
    # ml/trainer/trainer_creator.py)
    task: str = "classification"
    # "real" | "synthetic" — set by the loader so reporting can never
    # present a generated stand-in as the real task
    provenance: str = "real"

    @property
    def total_train_samples(self) -> int:
        return int(self.client_num_samples.sum())


def batchify(x: np.ndarray, y: np.ndarray, batch_size: int,
             n_batches: Optional[int] = None
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad (x, y) to ``n_batches`` full batches; returns (x, y, mask) with
    shapes [nb, bs, ...], [nb, bs], [nb, bs]."""
    n = x.shape[0]
    nb = n_batches if n_batches is not None else max(1, -(-n // batch_size))
    total = nb * batch_size
    pad = total - n
    if pad < 0:
        raise ValueError(f"n_batches={nb} too small for {n} samples")
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    xp = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)]) if pad else x
    yp = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)]) if pad else y
    return (xp.reshape((nb, batch_size) + x.shape[1:]),
            yp.reshape((nb, batch_size) + y.shape[1:]),
            mask.reshape(nb, batch_size))


def build_federated_dataset(
    client_xs: Sequence[np.ndarray],
    client_ys: Sequence[np.ndarray],
    test_x: np.ndarray,
    test_y: np.ndarray,
    batch_size: int,
    num_classes: int,
    eval_batch_size: Optional[int] = None,
    dtype=np.float32,
    task: str = "classification",
) -> FederatedDataset:
    """Stack per-client arrays into one padded ClientData."""
    num_clients = len(client_xs)
    counts = np.array([len(x) for x in client_xs], dtype=np.int64)
    nb = max(1, int(-(-counts.max() // batch_size)))
    xs, ys, ms = [], [], []
    for cx, cy in zip(client_xs, client_ys):
        bx, by, bm = batchify(np.asarray(cx, dtype), np.asarray(cy), batch_size, nb)
        xs.append(bx)
        ys.append(by)
        ms.append(bm)
    train = ClientData(
        x=jnp.asarray(np.stack(xs)),
        y=jnp.asarray(np.stack(ys)),
        mask=jnp.asarray(np.stack(ms)),
        num_samples=jnp.asarray(counts, jnp.float32),
    )
    ebs = eval_batch_size or max(batch_size, 256)
    tx, ty, tm = batchify(np.asarray(test_x, dtype), np.asarray(test_y), ebs)
    test = {"x": jnp.asarray(tx), "y": jnp.asarray(ty), "mask": jnp.asarray(tm)}
    return FederatedDataset(
        train=train, test=test, num_classes=num_classes,
        input_shape=tuple(np.asarray(client_xs[0]).shape[1:]),
        num_clients=num_clients, client_num_samples=counts, task=task)


def from_central_arrays(
    x: np.ndarray,
    y: np.ndarray,
    test_x: np.ndarray,
    test_y: np.ndarray,
    num_clients: int,
    batch_size: int,
    num_classes: int,
    partition_method: str = "hetero",
    partition_alpha: float = 0.5,
    seed: int = 0,
    task: str = "classification",
) -> FederatedDataset:
    """Central arrays + partitioner → FederatedDataset (the common loader
    tail shared by MNIST/CIFAR-style datasets)."""
    from ..core.data.noniid_partition import partition

    parts = partition(np.asarray(y), num_clients, partition_method,
                      partition_alpha, seed)
    cxs = [x[parts[i]] for i in range(num_clients)]
    cys = [y[parts[i]] for i in range(num_clients)]
    return build_federated_dataset(cxs, cys, test_x, test_y, batch_size,
                                   num_classes, task=task)
