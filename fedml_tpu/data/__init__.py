from .data_loader import load
from .containers import (FederatedDataset, build_federated_dataset,
                         from_central_arrays, batchify)

__all__ = ["load", "FederatedDataset", "build_federated_dataset",
           "from_central_arrays", "batchify"]
