"""LEAF-format federated dataset reader.

Parity target: the reference's LEAF-derived loaders (``data/FederatedEMNIST``,
``data/fed_shakespeare``, ``data/stackoverflow`` read LEAF/TFF-style
per-user splits). LEAF json layout::

    {"users": [...], "num_samples": [...],
     "user_data": {user: {"x": [...], "y": [...]}}}

Files live under ``<root>/train/*.json`` and ``<root>/test/*.json``. Natural
(per-user) partitions are preserved — these are the datasets whose
non-IIDness is real rather than synthesized by a partitioner.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from .containers import FederatedDataset, build_federated_dataset


# LEAF shakespeare's character vocabulary (80 printable chars); index 0 is
# reserved for out-of-vocabulary/padding
_LEAF_VOCAB = ("\n !\"&'(),-.0123456789:;>?ABCDEFGHIJKLMNOPQRSTUVWXYZ"
               "[]abcdefghijklmnopqrstuvwxyz}")
_CHAR_TO_ID = {c: i + 1 for i, c in enumerate(_LEAF_VOCAB)}


def _encode(values) -> np.ndarray:
    """Numeric LEAF data -> float32; text LEAF data (shakespeare/sent140
    store strings in x/y) -> int32 char-id sequences."""
    if len(values) and isinstance(values[0], str):
        seqs = [[_CHAR_TO_ID.get(c, 0) for c in s] for s in values]
        length = max(len(s) for s in seqs)
        out = np.zeros((len(seqs), length), np.int32)
        for i, s in enumerate(seqs):
            out[i, :len(s)] = s
        return out
    return np.asarray(values, np.float32)


def _read_split(split_dir: str) -> Optional[Dict[str, Tuple[np.ndarray,
                                                            np.ndarray]]]:
    if not os.path.isdir(split_dir):
        return None
    out: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    for fname in sorted(os.listdir(split_dir)):
        if not fname.endswith(".json"):
            continue
        with open(os.path.join(split_dir, fname)) as f:
            blob = json.load(f)
        for user in blob.get("users", []):
            ud = blob["user_data"][user]
            ys = ud["y"]
            y = (_encode(ys) if len(ys) and isinstance(ys[0], str)
                 else np.asarray(ys))
            out[user] = (_encode(ud["x"]), y)
    return out or None


def load_leaf_dataset(
    root: str,
    batch_size: int,
    num_classes: int,
    max_clients: Optional[int] = None,
    task: str = "classification",
) -> Optional[FederatedDataset]:
    """Build a FederatedDataset from a LEAF directory, or None if absent."""
    train = _read_split(os.path.join(root, "train"))
    if train is None:
        return None
    test = _read_split(os.path.join(root, "test"))
    users: List[str] = sorted(train)
    if max_clients:
        users = users[:max_clients]
    cxs = [train[u][0] for u in users]
    cys = [train[u][1] for u in users]
    if test:
        tx = np.concatenate([test[u][0] for u in sorted(test)])
        ty = np.concatenate([test[u][1] for u in sorted(test)])
    else:  # held-out fallback: last 10% of each user's data
        tx = np.concatenate([x[int(len(x) * 0.9):] for x in cxs])
        ty = np.concatenate([y[int(len(y) * 0.9):] for y in cys])
        cxs = [x[:int(len(x) * 0.9)] for x in cxs]
        cys = [y[:int(len(y) * 0.9)] for y in cys]
    return build_federated_dataset(cxs, cys, tx, ty, batch_size, num_classes,
                                   dtype=(np.int32 if task == "sequence"
                                          else np.float32),
                                   task=task)
