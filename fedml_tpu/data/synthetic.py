"""Synthetic datasets (deterministic, no network egress).

Counterpart of the reference's ``data/synthetic_1_1`` loader and the stand-in
for MNIST/CIFAR-shaped tasks when the real files are absent (the reference
downloads MNIST from S3, ``data/data_loader.py`` + ``constants.py:36``; this
environment has zero egress, so loaders fall back here — see
``data_loader.py``).

The generator is class-prototype + Gaussian noise: linearly separable enough
for LR to learn, hard enough that accuracy curves are informative.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def make_classification(
    n_samples: int,
    n_features: int,
    n_classes: int,
    seed: int = 0,
    noise: float = 1.0,
    flat: bool = True,
    image_shape: Tuple[int, ...] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(seed)
    prototypes = rng.randn(n_classes, n_features).astype(np.float32)
    y = rng.randint(0, n_classes, size=n_samples).astype(np.int32)
    x = prototypes[y] + noise * rng.randn(n_samples, n_features).astype(np.float32)
    if not flat and image_shape is not None:
        x = x.reshape((n_samples,) + tuple(image_shape))
    return x, y


def synthetic_mnist(n_train: int = 6000, n_test: int = 1000, seed: int = 0,
                    flat: bool = True):
    """784-feature, 10-class MNIST-shaped task."""
    shape = (28, 28, 1)
    x, y = make_classification(n_train + n_test, 784, 10, seed=seed,
                               noise=2.0, flat=flat, image_shape=shape)
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def synthetic_cifar10(n_train: int = 5000, n_test: int = 1000, seed: int = 0):
    """32×32×3, 10-class CIFAR-shaped task (images, for conv models)."""
    shape = (32, 32, 3)
    x, y = make_classification(n_train + n_test, 32 * 32 * 3, 10, seed=seed,
                               noise=3.0, flat=False, image_shape=shape)
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def synthetic_sequences(n_train: int = 2000, n_test: int = 400,
                        seq_len: int = 32, vocab: int = 64, seed: int = 0):
    """Next-token-predictable integer sequences (Shakespeare-NWP stand-in):
    class = parity pattern of a hidden Markov-ish generator."""
    rng = np.random.RandomState(seed)
    n = n_train + n_test
    # order-1 Markov chain with a random sparse transition structure
    trans = rng.dirichlet(np.ones(vocab) * 0.1, size=vocab).astype(np.float32)
    seqs = np.zeros((n, seq_len), np.int32)
    state = rng.randint(0, vocab, size=n)
    for t in range(seq_len):
        seqs[:, t] = state
        u = rng.rand(n, 1)
        state = (np.cumsum(trans[state], axis=1) < u).sum(axis=1).clip(0, vocab - 1)
    x = seqs[:, :-1]
    y = seqs[:, 1:]
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])
