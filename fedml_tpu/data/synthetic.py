"""Synthetic datasets (deterministic, no network egress).

Counterpart of the reference's ``data/synthetic_1_1`` loader and the stand-in
for MNIST/CIFAR-shaped tasks when the real files are absent (the reference
downloads MNIST from S3, ``data/data_loader.py`` + ``constants.py:36``; this
environment has zero egress, so loaders fall back here — see
``data_loader.py``).

The generator is class-prototype + Gaussian noise: linearly separable enough
for LR to learn, hard enough that accuracy curves are informative.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def make_classification(
    n_samples: int,
    n_features: int,
    n_classes: int,
    seed: int = 0,
    noise: float = 1.0,
    flat: bool = True,
    image_shape: Tuple[int, ...] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(seed)
    prototypes = rng.randn(n_classes, n_features).astype(np.float32)
    y = rng.randint(0, n_classes, size=n_samples).astype(np.int32)
    x = prototypes[y] + noise * rng.randn(n_samples, n_features).astype(np.float32)
    if not flat and image_shape is not None:
        x = x.reshape((n_samples,) + tuple(image_shape))
    return x, y


def synthetic_mnist(n_train: int = 6000, n_test: int = 1000, seed: int = 0,
                    flat: bool = True):
    """784-feature, 10-class MNIST-shaped task."""
    shape = (28, 28, 1)
    x, y = make_classification(n_train + n_test, 784, 10, seed=seed,
                               noise=2.0, flat=flat, image_shape=shape)
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def synthetic_cifar10(n_train: int = 5000, n_test: int = 1000, seed: int = 0):
    """32×32×3, 10-class CIFAR-shaped task (images, for conv models)."""
    shape = (32, 32, 3)
    x, y = make_classification(n_train + n_test, 32 * 32 * 3, 10, seed=seed,
                               noise=3.0, flat=False, image_shape=shape)
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def synthetic_federated(
    alpha: float = 1.0,
    beta: float = 1.0,
    num_clients: int = 30,
    n_features: int = 60,
    n_classes: int = 10,
    seed: int = 0,
):
    """The reference's ``synthetic_1_1``-style generator (Li et al.,
    "Federated Optimization in Heterogeneous Networks"): per-client logistic
    models W_k ~ N(u_k, 1) with u_k ~ N(0, alpha), and per-client feature
    distributions x ~ N(v_k, diag(j^-1.2)) with v_k ~ N(B_k, 1),
    B_k ~ N(0, beta). alpha controls model heterogeneity, beta data
    heterogeneity. Returns (client_xs, client_ys, test_x, test_y) with the
    natural per-client partition."""
    rng = np.random.RandomState(seed)
    diag = np.array([(j + 1) ** -1.2 for j in range(n_features)])
    samples_per = rng.lognormal(4, 1, num_clients).astype(int) + 50
    u = rng.normal(0, alpha, num_clients)
    b_loc = rng.normal(0, beta, num_clients)
    cxs, cys = [], []
    for k in range(num_clients):
        v_k = rng.normal(b_loc[k], 1.0, n_features)
        w_k = rng.normal(u[k], 1.0, (n_features, n_classes))
        bias_k = rng.normal(u[k], 1.0, n_classes)
        x = rng.multivariate_normal(v_k, np.diag(diag), samples_per[k]
                                    ).astype(np.float32)
        logits = x @ w_k + bias_k
        y = np.argmax(logits, axis=1).astype(np.int32)
        cxs.append(x)
        cys.append(y)
    # global test set: held-out 10% of each client's data (disjoint)
    txs, tys, new_cxs, new_cys = [], [], [], []
    for x, y in zip(cxs, cys):
        cut = max(len(x) // 10, 5)
        txs.append(x[:cut])
        tys.append(y[:cut])
        new_cxs.append(x[cut:])
        new_cys.append(y[cut:])
    return new_cxs, new_cys, np.concatenate(txs), np.concatenate(tys)


def synthetic_multilabel(
    n_train: int = 4000, n_test: int = 500, n_features: int = 1000,
    n_tags: int = 50, seed: int = 0,
):
    """Stackoverflow-LR stand-in: sparse bag-of-words features, multi-hot
    tag labels from a sparse linear model (the reference's task is 10k
    features / 500 tags tag-prediction with BCE)."""
    rng = np.random.RandomState(seed)
    n = n_train + n_test
    x = (rng.rand(n, n_features) < 0.02).astype(np.float32)
    w = rng.randn(n_features, n_tags) * (rng.rand(n_features, n_tags) < 0.05)
    scores = x @ w
    thresh = np.percentile(scores, 90, axis=0, keepdims=True)
    y = (scores > thresh).astype(np.float32)
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def synthetic_sequences(n_train: int = 2000, n_test: int = 400,
                        seq_len: int = 32, vocab: int = 64, seed: int = 0):
    """Next-token-predictable integer sequences (Shakespeare-NWP stand-in):
    class = parity pattern of a hidden Markov-ish generator."""
    rng = np.random.RandomState(seed)
    n = n_train + n_test
    # order-1 Markov chain with a random sparse transition structure
    trans = rng.dirichlet(np.ones(vocab) * 0.1, size=vocab).astype(np.float32)
    seqs = np.zeros((n, seq_len), np.int32)
    state = rng.randint(0, vocab, size=n)
    for t in range(seq_len):
        seqs[:, t] = state
        u = rng.rand(n, 1)
        state = (np.cumsum(trans[state], axis=1) < u).sum(axis=1).clip(0, vocab - 1)
    x = seqs[:, :-1]
    y = seqs[:, 1:]
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def synthetic_segmentation(n_train: int = 400, n_test: int = 80,
                           size: int = 16, n_classes: int = 3,
                           seed: int = 0):
    """Dense-labeling stand-in for FedSeg: each image contains an axis-
    aligned rectangle of a random foreground class on background class 0;
    the label map is per-pixel. Learnable by a small encoder-decoder."""
    rs = np.random.RandomState(seed)
    n = n_train + n_test
    x = rs.rand(n, size, size, 3).astype(np.float32) * 0.3
    y = np.zeros((n, size, size), np.int64)
    for i in range(n):
        c = rs.randint(1, n_classes)
        h0, w0 = rs.randint(0, size // 2, 2)
        h1 = h0 + rs.randint(3, size // 2)
        w1 = w0 + rs.randint(3, size // 2)
        x[i, h0:h1, w0:w1, :] += np.asarray(
            [0.8 if ch == (c - 1) % 3 else 0.1 for ch in range(3)],
            np.float32)
        y[i, h0:h1, w0:w1] = c
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])
