"""Dataset dispatch: ``fedml_tpu.data.load(args)``.

Parity target: ``data/data_loader.py:234-448`` of the reference (dispatch on
``args.dataset``, download + partition, returns dataset tuple + class count).
Here ``load`` returns a :class:`FederatedDataset` (padded stacked arrays) and
``output_dim``. Real on-disk datasets are used when present under
``args.data_cache_dir`` (numpy ``.npz`` with x_train/y_train/x_test/y_test);
otherwise deterministic synthetic stand-ins keep everything runnable with
zero egress.
"""

from __future__ import annotations

import os
import zlib
from typing import Tuple

import numpy as np

from .containers import FederatedDataset, from_central_arrays
from . import synthetic


def _try_npz(cache_dir: str, name: str):
    path = os.path.join(os.path.expanduser(cache_dir or "."), f"{name}.npz")
    if os.path.exists(path):
        z = np.load(path)
        return (z["x_train"], z["y_train"]), (z["x_test"], z["y_test"])
    return None


_IMAGE_DATASETS = {
    "mnist": ((28, 28, 1), 10),
    "femnist": ((28, 28, 1), 62),
    "fashionmnist": ((28, 28, 1), 10),
    "cifar10": ((32, 32, 3), 10),
    "cifar100": ((32, 32, 3), 100),
    "fed_cifar100": ((32, 32, 3), 100),
    "cinic10": ((32, 32, 3), 10),
}


def load(args) -> Tuple[FederatedDataset, int]:
    raw_name = str(getattr(args, "dataset", "synthetic_mnist")).lower()
    name = raw_name.removeprefix("synthetic_")
    num_clients = int(args.client_num_in_total)
    bs = int(args.batch_size)
    seed = int(getattr(args, "random_seed", 0))
    method = getattr(args, "partition_method", "hetero")
    alpha = float(getattr(args, "partition_alpha", 0.5))
    # model decides whether images stay 2D: linear models take flat input
    flat = str(getattr(args, "model", "lr")).lower() in ("lr", "logistic_regression", "mlp")

    cache_dir = os.path.expanduser(getattr(args, "data_cache_dir", None)
                                   or ".")
    # LEAF-format natural partitions take precedence when present on disk
    if name in ("femnist", "shakespeare", "fed_shakespeare", "celeba",
                "sent140", "reddit"):
        from .leaf import load_leaf_dataset
        n_classes = {"femnist": 62, "celeba": 2, "sent140": 2}.get(name, 90)
        task = ("sequence" if name in ("shakespeare", "fed_shakespeare",
                                       "reddit") else "classification")
        leaf = load_leaf_dataset(os.path.join(cache_dir, name), bs,
                                 n_classes, max_clients=num_clients,
                                 task=task)
        if leaf is not None:
            return leaf, n_classes

    if raw_name in ("synthetic", "synthetic_1_1", "synthetic_0_0",
                    "synthetic_0.5_0.5", "synthetic_iid"):
        from .containers import build_federated_dataset
        ab = {"synthetic_1_1": (1.0, 1.0), "synthetic_0_0": (0.0, 0.0),
              "synthetic_0.5_0.5": (0.5, 0.5), "synthetic_iid": (0.0, 0.0)}
        alpha_s, beta_s = ab.get(raw_name, (1.0, 1.0))
        cxs, cys, tx, ty = synthetic.synthetic_federated(
            alpha_s, beta_s, num_clients=num_clients, seed=seed)
        fed = build_federated_dataset(cxs, cys, tx, ty, bs, 10)
        return fed, 10

    if name in ("stackoverflow_lr", "multilabel"):
        from .containers import build_federated_dataset
        (xtr, ytr), (xte, yte) = synthetic.synthetic_multilabel(
            n_train=max(num_clients * 2 * bs, 2000), seed=seed)
        # multilabel labels cannot drive a label partitioner: homo split
        idxs = np.array_split(np.random.RandomState(seed).permutation(
            len(xtr)), num_clients)
        fed = build_federated_dataset(
            [xtr[i] for i in idxs], [ytr[i] for i in idxs], xte, yte, bs,
            ytr.shape[1], task="multilabel")
        return fed, ytr.shape[1]

    cached = _try_npz(getattr(args, "data_cache_dir", None), name)
    if name in _IMAGE_DATASETS:
        shape, n_classes = _IMAGE_DATASETS[name]
        if cached is not None:
            (xtr, ytr), (xte, yte) = cached
            xtr = xtr.astype(np.float32)
            xte = xte.astype(np.float32)
            if xtr.max() > 2.0:
                xtr, xte = xtr / 255.0, xte / 255.0
            if flat:
                xtr = xtr.reshape(len(xtr), -1)
                xte = xte.reshape(len(xte), -1)
            elif xtr.ndim == 3:
                xtr, xte = xtr[..., None], xte[..., None]
        else:
            n_feat = int(np.prod(shape))
            gen_seed = seed + zlib.crc32(name.encode()) % 1000
            x, y = synthetic.make_classification(
                max(num_clients * 2 * bs, 4000) + 1000, n_feat, n_classes,
                seed=gen_seed, noise=2.5, flat=flat, image_shape=shape)
            n_test = 1000
            xtr, ytr, xte, yte = x[:-n_test], y[:-n_test], x[-n_test:], y[-n_test:]
        fed = from_central_arrays(xtr, ytr, xte, yte, num_clients, bs,
                                  n_classes, method, alpha, seed)
        return fed, n_classes
    if name in ("shakespeare", "fed_shakespeare", "stackoverflow_nwp",
                "sequences", "reddit"):
        (xtr, ytr), (xte, yte) = synthetic.synthetic_sequences(
            n_train=max(num_clients * 2 * bs, 2000), seed=seed)
        vocab = 64
        fed = from_central_arrays(xtr, ytr, xte, yte, num_clients, bs,
                                  vocab, "homo", alpha, seed, task="sequence")
        return fed, vocab
    # default: mnist-shaped synthetic
    (xtr, ytr), (xte, yte) = synthetic.synthetic_mnist(
        n_train=max(num_clients * 2 * bs, 4000), seed=seed, flat=flat)
    fed = from_central_arrays(xtr, ytr, xte, yte, num_clients, bs, 10,
                              method, alpha, seed)
    return fed, 10
