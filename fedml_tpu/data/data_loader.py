"""Dataset dispatch: ``fedml_tpu.data.load(args)``.

Parity target: ``data/data_loader.py:234-448`` of the reference (dispatch on
``args.dataset``, download + partition, returns dataset tuple + class count).
Here ``load`` returns a :class:`FederatedDataset` (padded stacked arrays) and
``output_dim``.

Real-data policy (strict by design — results must not masquerade):

1. an ``.npz`` cache under ``args.data_cache_dir`` is used when present;
2. otherwise :mod:`.acquire` downloads + verifies + caches the real dataset
   (scikit-learn-bundled sets like ``digits`` need no network at all);
3. only if BOTH fail is a synthetic stand-in considered, and it is
   **opt-in**: the dataset name must be prefixed ``synthetic_`` or
   ``args.allow_synthetic`` / ``$FEDML_TPU_ALLOW_SYNTHETIC`` must be set —
   otherwise ``load`` raises. When a stand-in is substituted, a WARNING is
   logged and ``fed.provenance`` says ``synthetic`` so downstream reporting
   can't silently present generated data as the real task.
"""

from __future__ import annotations

import logging
import os
import zlib
from typing import Tuple

import numpy as np

from .containers import FederatedDataset, from_central_arrays
from . import synthetic

logger = logging.getLogger(__name__)


class DatasetUnavailableError(FileNotFoundError):
    pass


def default_cache_dir() -> str:
    """The cache dir used when no ``data_cache_dir`` is configured — also
    the default target of the offline archive import (``acquire.
    import_archive``)."""
    return os.path.expanduser(os.environ.get(
        "FEDML_TPU_DATA_DIR", "~/.cache/fedml_tpu/data"))


def _synthetic_allowed(args, raw_name: str) -> bool:
    if raw_name.startswith("synthetic"):
        return True
    if getattr(args, "allow_synthetic", False):
        return True
    env = os.environ.get("FEDML_TPU_ALLOW_SYNTHETIC", "").strip().lower()
    return env not in ("", "0", "false", "no", "off")


def _synthetic_fallback(args, raw_name: str, name: str):
    """Gate + loud warning for substituting generated data for a real task.
    Explicitly-synthetic names are fine and silent."""
    if raw_name.startswith("synthetic"):
        return
    if not _synthetic_allowed(args, raw_name):
        raise DatasetUnavailableError(
            f"dataset {name!r} is not cached under "
            f"{getattr(args, 'data_cache_dir', '.')!r} and could not be "
            f"downloaded. To run on a generated stand-in instead, rename the "
            f"dataset 'synthetic_{name}' or set allow_synthetic: true "
            f"(env FEDML_TPU_ALLOW_SYNTHETIC=1). Synthetic substitution is "
            f"opt-in so generated data can never masquerade as real-task "
            f"results.")
    logger.warning(
        "SYNTHETIC STAND-IN: dataset %r is not available; training on "
        "generated data shaped like it. Metrics do NOT reflect the real "
        "task.", name)


def _cap_train(xtr, ytr, args, seed: int):
    """Deterministically subsample the training set when the caller bounds
    total samples (quick runs, bench baselines). Never silent: the cap is
    logged and recorded on the args namespace (``_train_capped_to``) so
    benchmark output can disclose it."""
    cap = int(getattr(args, "max_total_samples", 0) or 0)
    if cap and len(xtr) > cap:
        logger.warning("training set capped to %d of %d samples "
                       "(max_total_samples)", cap, len(xtr))
        try:
            args._train_capped_to = cap
        except Exception:
            pass
        idx = np.random.RandomState(seed ^ 0x5EED).permutation(len(xtr))[:cap]
        return xtr[idx], ytr[idx]
    return xtr, ytr


def _try_npz(cache_dir: str, name: str):
    path = os.path.join(os.path.expanduser(cache_dir or "."), f"{name}.npz")
    if os.path.exists(path):
        z = np.load(path)
        return (z["x_train"], z["y_train"]), (z["x_test"], z["y_test"])
    return None


_IMAGE_DATASETS = {
    "mnist": ((28, 28, 1), 10),
    "femnist": ((28, 28, 1), 62),
    "fashionmnist": ((28, 28, 1), 10),
    "cifar10": ((32, 32, 3), 10),
    "cifar100": ((32, 32, 3), 100),
    "fed_cifar100": ((32, 32, 3), 100),
    "cinic10": ((32, 32, 3), 10),
    "digits": ((8, 8, 1), 10),     # real, bundled with scikit-learn
}

# real tabular UCI sets bundled with scikit-learn: (n_features, n_classes)
_TABULAR_DATASETS = {
    "wine": (13, 3),
    "breast_cancer": (30, 2),
}


def load(args) -> Tuple[FederatedDataset, int]:
    raw_name = str(getattr(args, "dataset", "synthetic_mnist")).lower()
    name = raw_name.removeprefix("synthetic_")
    num_clients = int(args.client_num_in_total)
    bs = int(args.batch_size)
    seed = int(getattr(args, "random_seed", 0))
    method = getattr(args, "partition_method", "hetero")
    alpha = float(getattr(args, "partition_alpha", 0.5))
    # model decides whether images stay 2D: linear models take flat input
    flat = str(getattr(args, "model", "lr")).lower() in ("lr", "logistic_regression", "mlp")

    cache_dir = os.path.expanduser(getattr(args, "data_cache_dir", None)
                                   or ".")
    # TFF HDF5 formats (the reference's fed_cifar100 / stackoverflow
    # shards) read from a local cache dir when the files are present
    if name in ("fed_cifar100", "stackoverflow_nwp", "stackoverflow_lr") \
            and not raw_name.startswith("synthetic"):
        from .tff_h5 import load_tff_dataset
        got = load_tff_dataset(name, os.path.join(cache_dir, name), bs,
                               max_clients=num_clients)
        if got is not None:
            return got

    # FedNLP text-classification shards (reference data/fednlp h5 pair:
    # <task>_data.h5 + <task>_partition.h5) from a local cache dir
    if name.startswith("fednlp") and not raw_name.startswith("synthetic"):
        from .fednlp_h5 import load_fednlp_text_classification
        got = load_fednlp_text_classification(
            os.path.join(cache_dir, name), bs, max_clients=num_clients,
            partition_method=getattr(args, "partition_method", None))
        if got is not None:
            return got

    # image-directory datasets from a local cache (no egress):
    # ImageNet-style folder trees and Landmarks CSV-mapped user partitions
    if name in ("imagenet", "ilsvrc2012", "tiny_imagenet") \
            and not raw_name.startswith("synthetic"):
        from .images import load_image_folder
        got = load_image_folder(os.path.join(cache_dir, name))
        if got is not None:
            (xtr, ytr), (xte, yte), n_classes = got
            fed = from_central_arrays(xtr, ytr, xte, yte, num_clients, bs,
                                      n_classes, partition_method=method,
                                      partition_alpha=alpha, seed=seed)
            fed.provenance = "real"
            return fed, n_classes
    if name in ("landmarks", "gld23k", "gld160k") \
            and not raw_name.startswith("synthetic"):
        from .containers import build_federated_dataset
        from .images import load_landmarks
        got = load_landmarks(os.path.join(cache_dir, name),
                             max_clients=num_clients)
        if got is not None:
            cxs, cys, test_x, test_y, n_classes = got
            fed = build_federated_dataset(cxs, cys, test_x, test_y, bs,
                                          n_classes)
            fed.provenance = "real"
            return fed, n_classes

    # LEAF-format natural partitions take precedence when present on disk
    if name in ("femnist", "shakespeare", "fed_shakespeare", "celeba",
                "sent140", "reddit"):
        from .leaf import load_leaf_dataset
        n_classes = {"femnist": 62, "celeba": 2, "sent140": 2}.get(name, 90)
        task = ("sequence" if name in ("shakespeare", "fed_shakespeare",
                                       "reddit") else "classification")
        leaf_dir = os.path.join(cache_dir, name)
        if (name in ("shakespeare", "fed_shakespeare")
                and not os.path.isdir(os.path.join(leaf_dir, "train"))
                and not raw_name.startswith("synthetic")):
            # no full LEAF download on disk: materialize the bundled REAL
            # mini-Shakespeare shard (public-domain text, client = role)
            # so the NWP task runs on real language, not a stand-in
            from .bundled import materialize_mini_shakespeare
            leaf_dir = materialize_mini_shakespeare(
                os.path.join(cache_dir, "bundled"))
        leaf = load_leaf_dataset(leaf_dir, bs,
                                 n_classes, max_clients=num_clients,
                                 task=task)
        if leaf is not None:
            return leaf, n_classes

    if raw_name in ("synthetic", "synthetic_1_1", "synthetic_0_0",
                    "synthetic_0.5_0.5", "synthetic_iid"):
        from .containers import build_federated_dataset
        ab = {"synthetic_1_1": (1.0, 1.0), "synthetic_0_0": (0.0, 0.0),
              "synthetic_0.5_0.5": (0.5, 0.5), "synthetic_iid": (0.0, 0.0)}
        alpha_s, beta_s = ab.get(raw_name, (1.0, 1.0))
        cxs, cys, tx, ty = synthetic.synthetic_federated(
            alpha_s, beta_s, num_clients=num_clients, seed=seed)
        fed = build_federated_dataset(cxs, cys, tx, ty, bs, 10)
        fed.provenance = "synthetic"
        return fed, 10

    if name in ("stackoverflow_lr", "multilabel"):
        from .containers import build_federated_dataset
        if not raw_name.startswith("synthetic"):
            _synthetic_fallback(args, raw_name, name)
        (xtr, ytr), (xte, yte) = synthetic.synthetic_multilabel(
            n_train=max(num_clients * 2 * bs, 2000), seed=seed)
        # multilabel labels cannot drive a label partitioner: homo split
        idxs = np.array_split(np.random.RandomState(seed).permutation(
            len(xtr)), num_clients)
        fed = build_federated_dataset(
            [xtr[i] for i in idxs], [ytr[i] for i in idxs], xte, yte, bs,
            ytr.shape[1], task="multilabel")
        return fed, ytr.shape[1]

    # an explicit synthetic_* name must NEVER silently pick up real data
    cached = None if raw_name.startswith("synthetic") else _try_npz(
        cache_dir, name)
    if cached is None and not raw_name.startswith("synthetic"):
        # attempt real acquisition (download+verify, or sklearn-bundled)
        from .acquire import acquire
        if acquire(name, cache_dir):
            cached = _try_npz(cache_dir, name)
    if name in _IMAGE_DATASETS:
        shape, n_classes = _IMAGE_DATASETS[name]
        if cached is not None:
            (xtr, ytr), (xte, yte) = cached
            xtr = xtr.astype(np.float32)
            xte = xte.astype(np.float32)
            if xtr.max() > 2.0:
                xtr, xte = xtr / 255.0, xte / 255.0
            if flat:
                xtr = xtr.reshape(len(xtr), -1)
                xte = xte.reshape(len(xte), -1)
            elif xtr.ndim == 3:
                xtr, xte = xtr[..., None], xte[..., None]
            provenance = "real"
        else:
            _synthetic_fallback(args, raw_name, name)
            n_feat = int(np.prod(shape))
            gen_seed = seed + zlib.crc32(name.encode()) % 1000
            # honor synthetic_size so a stand-in can match the real
            # dataset's per-client workload (bench representativeness)
            n_train = max(num_clients * 2 * bs, 4000,
                          int(getattr(args, "synthetic_size", 0) or 0))
            # synthetic_test_size: tiny-run harnesses (the examples gate)
            # shrink the eval set too — a 1000-sample resnet eval on the
            # virtual CPU mesh costs minutes
            n_test = int(getattr(args, "synthetic_test_size", 0) or 1000)
            x, y = synthetic.make_classification(
                n_train + n_test, n_feat, n_classes,
                seed=gen_seed, noise=2.5, flat=flat, image_shape=shape)
            xtr, ytr, xte, yte = x[:-n_test], y[:-n_test], x[-n_test:], y[-n_test:]
            provenance = "synthetic"
        xtr, ytr = _cap_train(xtr, ytr, args, seed)
        fed = from_central_arrays(xtr, ytr, xte, yte, num_clients, bs,
                                  n_classes, method, alpha, seed)
        fed.provenance = provenance
        return fed, n_classes
    if name in _TABULAR_DATASETS:
        n_feat, n_classes = _TABULAR_DATASETS[name]
        if cached is None:
            _synthetic_fallback(args, raw_name, name)
            x, y = synthetic.make_classification(
                max(num_clients * 2 * bs, 2000) + 400, n_feat, n_classes,
                seed=seed, noise=2.0, flat=True)
            xtr, ytr, xte, yte = x[:-400], y[:-400], x[-400:], y[-400:]
            provenance = "synthetic"
        else:
            (xtr, ytr), (xte, yte) = cached
            xtr, xte = xtr.astype(np.float32), xte.astype(np.float32)
            provenance = "real"
        fed = from_central_arrays(xtr, ytr, xte, yte, num_clients, bs,
                                  n_classes, method, alpha, seed)
        fed.provenance = provenance
        return fed, n_classes
    if name in ("lending_club", "lending_club_loan", "loan", "nus_wide"):
        # finance / vertical-FL tables (reference data/lending_club_loan,
        # data/NUS_WIDE): preprocessed CSVs from the disk cache; the
        # feature order IS the vertical column split the VFL sims use
        from . import finance
        try:
            if name == "nus_wide":
                x, y = finance.load_nus_wide(cache_dir)
            else:
                x, y = finance.load_lending_club(cache_dir)
            provenance = "real"
        except (OSError, ValueError) as e:
            logger.info("no cached %s (%s)", name, e)
            _synthetic_fallback(args, raw_name, name)
            if name == "nus_wide":
                x, y = finance.synthetic_nus_wide(
                    max(num_clients * 2 * bs, 2000) + 400, seed=seed)
            else:
                x, y = finance.synthetic_lending_club(
                    max(num_clients * 2 * bs, 2000) + 400, seed=seed)
            provenance = "synthetic"
        n_classes = int(y.max()) + 1
        n_test = max(len(x) // 6, 1)
        xtr, ytr = _cap_train(x[:-n_test], y[:-n_test], args, seed)
        fed = from_central_arrays(xtr, ytr, x[-n_test:], y[-n_test:],
                                  num_clients, bs, n_classes, method, alpha,
                                  seed)
        fed.provenance = provenance
        return fed, n_classes
    if name in ("pascal_voc", "coco_seg", "seg", "segmentation"):
        # dense-labeling task for FedSeg (reference data/pascal_voc etc.)
        if not raw_name.startswith("synthetic") and name not in ("seg",
                                                                 "segmentation"):
            _synthetic_fallback(args, raw_name, name)
        n_classes = 3
        (xtr, ytr), (xte, yte) = synthetic.synthetic_segmentation(
            n_train=max(num_clients * 2 * bs, 400), seed=seed)
        fed = from_central_arrays(xtr, ytr, xte, yte, num_clients, bs,
                                  n_classes, "homo", alpha, seed,
                                  task="segmentation")
        fed.provenance = "synthetic"
        return fed, n_classes
    if name in ("shakespeare", "fed_shakespeare", "stackoverflow_nwp",
                "sequences", "reddit"):
        if not raw_name.startswith("synthetic") and name != "sequences":
            _synthetic_fallback(args, raw_name, name)
        (xtr, ytr), (xte, yte) = synthetic.synthetic_sequences(
            n_train=max(num_clients * 2 * bs, 2000), seed=seed)
        vocab = 64
        fed = from_central_arrays(xtr, ytr, xte, yte, num_clients, bs,
                                  vocab, "homo", alpha, seed, task="sequence")
        fed.provenance = "synthetic"
        return fed, vocab
    # default: mnist-shaped synthetic
    if not raw_name.startswith("synthetic"):
        _synthetic_fallback(args, raw_name, name)
    (xtr, ytr), (xte, yte) = synthetic.synthetic_mnist(
        n_train=max(num_clients * 2 * bs, 4000), seed=seed, flat=flat)
    fed = from_central_arrays(xtr, ytr, xte, yte, num_clients, bs, 10,
                              method, alpha, seed)
    fed.provenance = "synthetic"
    return fed, 10
