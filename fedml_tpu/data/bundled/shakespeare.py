"""Mini-Shakespeare: a bundled REAL text shard for the NWP task.

Genuine public-domain Shakespeare passages (plays first published 1597-1623),
one speaking role per federated client — the same natural partition LEAF's
full fed_shakespeare uses (client = role). ``materialize_mini_shakespeare``
writes the shard as LEAF train/test JSON under a cache dir so it is read by
the ordinary LEAF reader (``data/leaf.py``): x = 80-char window, y = the
window shifted by one (per-token next-character prediction).
"""

from __future__ import annotations

import json
import os

# role -> passage. Public-domain text; sizes chosen so every client yields
# dozens of training windows.
PASSAGES = {
    "HAMLET": (
        "To be, or not to be, that is the question: "
        "Whether 'tis nobler in the mind to suffer "
        "The slings and arrows of outrageous fortune, "
        "Or to take arms against a sea of troubles, "
        "And by opposing end them. To die, to sleep; "
        "No more; and by a sleep to say we end "
        "The heartache and the thousand natural shocks "
        "That flesh is heir to: 'tis a consummation "
        "Devoutly to be wished. To die, to sleep; "
        "To sleep, perchance to dream. Ay, there's the rub, "
        "For in that sleep of death what dreams may come, "
        "When we have shuffled off this mortal coil, "
        "Must give us pause. There's the respect "
        "That makes calamity of so long life. "
        "For who would bear the whips and scorns of time, "
        "The oppressor's wrong, the proud man's contumely, "
        "The pangs of despised love, the law's delay, "
        "The insolence of office, and the spurns "
        "That patient merit of the unworthy takes, "
        "When he himself might his quietus make "
        "With a bare bodkin? Who would fardels bear, "
        "To grunt and sweat under a weary life, "
        "But that the dread of something after death, "
        "The undiscovered country from whose bourn "
        "No traveller returns, puzzles the will, "
        "And makes us rather bear those ills we have "
        "Than fly to others that we know not of?"
    ),
    "MACBETH": (
        "Tomorrow, and tomorrow, and tomorrow, "
        "Creeps in this petty pace from day to day, "
        "To the last syllable of recorded time; "
        "And all our yesterdays have lighted fools "
        "The way to dusty death. Out, out, brief candle! "
        "Life's but a walking shadow, a poor player, "
        "That struts and frets his hour upon the stage, "
        "And then is heard no more. It is a tale "
        "Told by an idiot, full of sound and fury, "
        "Signifying nothing. "
        "Is this a dagger which I see before me, "
        "The handle toward my hand? Come, let me clutch thee. "
        "I have thee not, and yet I see thee still. "
        "Art thou not, fatal vision, sensible "
        "To feeling as to sight? or art thou but "
        "A dagger of the mind, a false creation, "
        "Proceeding from the heat-oppressed brain?"
    ),
    "ROMEO": (
        "But, soft! what light through yonder window breaks? "
        "It is the east, and Juliet is the sun. "
        "Arise, fair sun, and kill the envious moon, "
        "Who is already sick and pale with grief, "
        "That thou her maid art far more fair than she. "
        "Be not her maid, since she is envious; "
        "Her vestal livery is but sick and green "
        "And none but fools do wear it; cast it off. "
        "It is my lady, O, it is my love! "
        "O, that she knew she were! "
        "She speaks yet she says nothing: what of that? "
        "Her eye discourses; I will answer it."
    ),
    "JULIET": (
        "O Romeo, Romeo! wherefore art thou Romeo? "
        "Deny thy father and refuse thy name; "
        "Or, if thou wilt not, be but sworn my love, "
        "And I'll no longer be a Capulet. "
        "'Tis but thy name that is my enemy; "
        "Thou art thyself, though not a Montague. "
        "What's Montague? it is nor hand, nor foot, "
        "Nor arm, nor face, nor any other part "
        "Belonging to a man. O, be some other name! "
        "What's in a name? that which we call a rose "
        "By any other name would smell as sweet."
    ),
    "PORTIA": (
        "The quality of mercy is not strained, "
        "It droppeth as the gentle rain from heaven "
        "Upon the place beneath: it is twice blest; "
        "It blesseth him that gives and him that takes: "
        "'Tis mightiest in the mightiest: it becomes "
        "The throned monarch better than his crown; "
        "His sceptre shows the force of temporal power, "
        "The attribute to awe and majesty, "
        "Wherein doth sit the dread and fear of kings; "
        "But mercy is above this sceptred sway; "
        "It is enthroned in the hearts of kings, "
        "It is an attribute to God himself."
    ),
    "ANTONY": (
        "Friends, Romans, countrymen, lend me your ears; "
        "I come to bury Caesar, not to praise him. "
        "The evil that men do lives after them; "
        "The good is oft interred with their bones; "
        "So let it be with Caesar. The noble Brutus "
        "Hath told you Caesar was ambitious: "
        "If it were so, it was a grievous fault, "
        "And grievously hath Caesar answered it. "
        "Here, under leave of Brutus and the rest - "
        "For Brutus is an honourable man; "
        "So are they all, all honourable men - "
        "Come I to speak in Caesar's funeral. "
        "He was my friend, faithful and just to me."
    ),
    "HENRY": (
        "Once more unto the breach, dear friends, once more; "
        "Or close the wall up with our English dead. "
        "In peace there's nothing so becomes a man "
        "As modest stillness and humility: "
        "But when the blast of war blows in our ears, "
        "Then imitate the action of the tiger; "
        "Stiffen the sinews, summon up the blood, "
        "Disguise fair nature with hard-favoured rage; "
        "Then lend the eye a terrible aspect."
    ),
    "JAQUES": (
        "All the world's a stage, "
        "And all the men and women merely players: "
        "They have their exits and their entrances; "
        "And one man in his time plays many parts, "
        "His acts being seven ages. At first the infant, "
        "Mewling and puking in the nurse's arms. "
        "And then the whining schoolboy, with his satchel "
        "And shining morning face, creeping like snail "
        "Unwillingly to school. And then the lover, "
        "Sighing like furnace, with a woeful ballad "
        "Made to his mistress' eyebrow."
    ),
    "RICHARD": (
        "Now is the winter of our discontent "
        "Made glorious summer by this sun of York; "
        "And all the clouds that loured upon our house "
        "In the deep bosom of the ocean buried. "
        "Now are our brows bound with victorious wreaths; "
        "Our bruised arms hung up for monuments; "
        "Our stern alarums changed to merry meetings, "
        "Our dreadful marches to delightful measures."
    ),
    "PROSPERO": (
        "Our revels now are ended. These our actors, "
        "As I foretold you, were all spirits and "
        "Are melted into air, into thin air: "
        "And, like the baseless fabric of this vision, "
        "The cloud-capped towers, the gorgeous palaces, "
        "The solemn temples, the great globe itself, "
        "Yea, all which it inherit, shall dissolve "
        "And, like this insubstantial pageant faded, "
        "Leave not a rack behind. We are such stuff "
        "As dreams are made on, and our little life "
        "Is rounded with a sleep."
    ),
}

SEQ_LEN = 80


def _windows(text: str, seq_len: int = SEQ_LEN, stride: int = 11):
    """Overlapping (x, y) pairs: y is x shifted one character — per-token
    next-char prediction (SequenceTrainer's label layout)."""
    xs, ys = [], []
    for start in range(0, len(text) - seq_len - 1, stride):
        xs.append(text[start:start + seq_len])
        ys.append(text[start + 1:start + seq_len + 1])
    return xs, ys


def materialize_mini_shakespeare(root: str) -> str:
    """Write the bundled shard as LEAF train/test JSON under
    ``root/shakespeare``; returns that directory. Idempotent."""
    base = os.path.join(root, "shakespeare")
    done = os.path.join(base, ".bundled")
    if os.path.exists(done):
        return base
    train_users, test_users = {}, {}
    for role, text in PASSAGES.items():
        xs, ys = _windows(text)
        n_test = max(len(xs) // 10, 1)
        train_users[role] = {"x": xs[:-n_test], "y": ys[:-n_test]}
        test_users[role] = {"x": xs[-n_test:], "y": ys[-n_test:]}
    for split, users in (("train", train_users), ("test", test_users)):
        d = os.path.join(base, split)
        os.makedirs(d, exist_ok=True)
        blob = {"users": sorted(users),
                "num_samples": [len(users[u]["x"]) for u in sorted(users)],
                "user_data": users}
        with open(os.path.join(d, "data.json"), "w") as f:
            json.dump(blob, f)
    with open(done, "w") as f:
        f.write("mini-shakespeare v1\n")
    return base
