"""Small REAL data shards bundled with the framework.

Downloads are environment-gated in many deployments, but several reference
tasks need real data to be meaningful (BENCH real-data policy). This
package carries tiny, redistributable shards: public-domain Shakespeare
text for the LEAF next-word-prediction task (reference
``data/fed_shakespeare``). Each shard materializes into the on-disk format
its loader family expects — the Shakespeare shard becomes LEAF train/test
JSON so it flows through the SAME reader as a full LEAF download.
"""

from .shakespeare import materialize_mini_shakespeare  # noqa: F401
