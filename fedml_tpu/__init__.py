"""fedml_tpu — a TPU-native federated & distributed ML framework.

Brand-new design with the capabilities of the reference FL platform
(see /root/repo/SURVEY.md): FL simulation where an entire round is one jitted
SPMD program over a named ``client`` mesh axis; cross-silo/cross-device FL
with a message-driven FSM at the WAN boundary; pluggable trust/privacy
(defenses, DP, secure aggregation); an LLM fine-tuning path on XLA FSDP with
Pallas attention; data/model zoos; federated analytics; observability.

Public API parity (reference ``python/fedml/__init__.py:67+``):

    import fedml_tpu as fedml
    args = fedml.init()
    device = fedml.device.get_device(args)
    dataset, output_dim = fedml.data.load(args)
    model = fedml.model.create(args, output_dim)
    fedml.FedMLRunner(args, device, dataset, model).run()

or the one-liner ``fedml_tpu.run_simulation(backend="tpu")``.
"""

from __future__ import annotations

import logging
import os
import random
from typing import Any, Optional

import numpy as np

from .arguments import Arguments, add_args, load_arguments
from .runner import FedMLRunner
from . import constants
from .core import mlops

__version__ = "0.1.0"


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache, on by default (opt out with
    FEDML_TPU_NO_COMPILE_CACHE=1). On the tunneled TPU platform a deep
    model's first jit goes through a remote compile service and can take
    minutes (MobileNetV3 local-train: ~7 min); with the cache it is paid
    once per (program, topology) ever, across processes."""
    if os.environ.get("FEDML_TPU_NO_COMPILE_CACHE"):
        return
    plat = (os.environ.get("JAX_PLATFORMS", "") or "default").replace(
        ",", "_")
    # primary platform decides (JAX_PLATFORMS is a priority list:
    # "tpu,cpu" is a TPU process with CPU fallback and must keep the
    # cache; only a cpu-PRIMARY process skips it)
    if plat.split("_")[0] == "cpu":
        # no cache for CPU processes: under the compile tunnel even CPU
        # programs are AOT-compiled on the remote terminal machine, and
        # re-loading those executables on this host trips machine-feature
        # mismatch warnings (and, in the worst case, SIGILL). CPU runs
        # are tests — their compiles are small; the cache's whole value
        # is the TPU path's minutes-long remote compiles.
        return
    try:
        import jax
        # platform-scoped: tunnel-compiled artifacts must never be loaded
        # by a process running a different platform
        cache_dir = os.path.join(os.environ.get(
            "FEDML_TPU_COMPILE_CACHE_DIR",
            os.path.expanduser("~/.cache/fedml_tpu/jaxcache")), plat)
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:  # never let cache setup break import
        pass


_enable_compile_cache()

_logger_configured = False


def _setup_logging() -> None:
    global _logger_configured
    if not _logger_configured:
        logging.basicConfig(
            level=logging.INFO,
            format="[fedml_tpu] %(asctime)s %(levelname)s %(name)s: %(message)s")
        # orbax/absl emit INFO for every checkpoint IO op — far too chatty
        logging.getLogger("absl").setLevel(logging.WARNING)
        _logger_configured = True


def init(args: Optional[Arguments] = None, **overrides: Any) -> Arguments:
    """Parse config + seed RNGs (reference ``__init__.py:67,103-108``).

    With no ``args``, reads ``--cf <yaml>`` from the CLI if present; keyword
    overrides always win (convenient for tests/notebooks).
    """
    _setup_logging()
    if args is None:
        cli = add_args()
        merged = dict(rank=cli.rank, role=cli.role, run_id=cli.run_id)
        merged.update(overrides)  # explicit overrides beat CLI bootstrap
        args = load_arguments(cli.yaml_config_file, **merged)
    else:
        for k, v in overrides.items():
            setattr(args, k, v)
    seed = int(getattr(args, "random_seed", 0))
    random.seed(seed)
    np.random.seed(seed)
    mlops.init(args)
    return args


def run_simulation(backend: str = "tpu", args: Optional[Arguments] = None,
                   **overrides: Any) -> Any:
    """One-call simulation entrypoint (reference ``launch_simulation.py:9``)."""
    from . import data as data_mod
    from . import model as model_mod

    args = init(args, backend=backend, **overrides)
    args.training_type = constants.FEDML_TRAINING_PLATFORM_SIMULATION
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    runner = FedMLRunner(args, dataset=fed, model=bundle)
    result = runner.run()
    save_path = getattr(args, "save_model_path", None)
    if save_path and isinstance(result, dict) and "params" in result:
        from .serving import save_model
        save_model(result["params"], os.path.expanduser(str(save_path)))
    return result


def run_cross_silo_server(args: Optional[Arguments] = None, **overrides: Any):
    args = init(args, **overrides)
    args.training_type = constants.FEDML_TRAINING_PLATFORM_CROSS_SILO
    args.role = "server"
    from . import data as data_mod
    from . import model as model_mod
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    return FedMLRunner(args, dataset=fed, model=bundle).run()


def run_cross_silo_client(args: Optional[Arguments] = None, **overrides: Any):
    args = init(args, **overrides)
    args.training_type = constants.FEDML_TRAINING_PLATFORM_CROSS_SILO
    args.role = "client"
    from . import data as data_mod
    from . import model as model_mod
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    return FedMLRunner(args, dataset=fed, model=bundle).run()
